"""Ragged decode attention: single-token attention that reads only each
row's real cache depth.

VERDICT r3 weak #5: the continuous batcher's ``decode_chunk`` attends over
the full cache width S every step ([B, S] mask on the dense path) — fine at
S=512, a real HBM cost at 8k-context serving where rows admitted at
different times sit at very different depths.  This kernel makes the decode
read ragged: grid ``(B, num_k_blocks)`` with the K/V BlockSpec index
clamped to each row's last needed block, so blocks past ``lengths[b]``
issue no DMA (repeated index => the Pallas pipeline skips the fetch) and no
MXU work (``pl.when``).  HBM traffic per step drops from B*S to
sum(lengths) KV bytes — the long-context batcher cost model.

Each K/V block carries ALL kv heads — ``(1, bk, KVH, D)`` out of the native
``[B, S, KVH, D]`` cache — and the kernel unrolls a static loop over heads.
Mosaic requires a block's last two dims to be (8,128)-divisible or equal to
the array dims; blocking heads at 1 (``(1, bk, 1, D)``) lowers only when
KVH == 1, which the first on-chip parity sweep caught (interpret mode
cannot).  Whole-KVH blocks satisfy the rule for every head count at the
same total HBM traffic per row.

The contract matches the batcher's canonical mask exactly: row ``b``
attends to cache slots ``[0, lengths[b])`` (its valid prefix INCLUDING the
slot its own token was just written to — lengths = cache_index + 1).
``models.model._attention`` routes here when ``cfg.ragged_decode`` is set
(the ContinuousBatcher sets it; the flag is the caller's assertion that its
mask is this prefix mask).  Sliding-window models pass ``window``: the
read narrows to ``[lengths[b] - window, lengths[b])`` — exact because the
contract layout is slot == position, so the slot band IS the position
window — and per-step HBM traffic drops from O(length) to O(window).

No reference counterpart: the reference's compute was a placeholder matmul
(src/worker/node.py:24-32) with no KV cache at all.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core import jaxcompat

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _kernel(
    lengths_ref,  # scalar-prefetch [B] int32
    q_ref,  # [1, KVH*Gp, D] — per-kv-head query groups, sublane-padded
    k_ref,  # [1, bk, KVH, D] — a block of the cache in its NATIVE layout
    v_ref,  # [1, bk, KVH, D]
    *rest,  # int8 leg: [ks_ref [1, bk, KVH] f32, vs_ref], then o_ref and
    #   the three VMEM scratch refs (acc [KVH*Gp, D], m/l [KVH*Gp, 128])
    scale: float,
    block_k: int,
    num_k_blocks: int,
    kvh: int,
    gp: int,
    window: int | None = None,  # row b reads [length - window, length)
    #   instead of [0, length) — exact under the contract layout
    #   (slot == position), where the query sits at position length - 1
    quant: bool = False,  # int8 K/V blocks + per-(slot, head) absmax
    #   scales: score = (q . k_i8) * k_scale and out folds v_scale into
    #   the softmax weights — the dequant never materializes in VMEM
    #   beyond one cast block
):
    if quant:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        ks_ref = vs_ref = None
        o_ref, acc_ref, m_ref, l_ref = rest
    bi, ji = pl.program_id(0), pl.program_id(1)
    length = lengths_ref[bi]
    last_needed = jax.lax.div(jnp.maximum(length - 1, 0), block_k)
    if window is None:
        first_needed = 0
    else:
        first_needed = jax.lax.div(
            jnp.maximum(length - window, 0), block_k
        )

    @pl.when(ji == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(jnp.logical_and(ji <= last_needed, ji >= first_needed))
    def _block():
        key_pos = ji * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (gp, block_k), dimension=1
        )
        # Static unrolled loop over kv heads: each iteration slices one
        # head out of the whole-KVH block already resident in VMEM and
        # updates its own Gp-row slice of the online-softmax state.
        for hh in range(kvh):
            r0, r1 = hh * gp, (hh + 1) * gp
            # Per-head cast to the compute dtype: the cache may live at a
            # different dtype (kv_dtype knob) and casting here keeps the
            # HBM read at the cache's width — never a full-cache copy.
            # Int8 leg: the cast is the only widening (one block in VMEM);
            # the absmax scales fold into the contraction below instead of
            # dequantizing the block.
            kb = k_ref[0, :, hh, :].astype(q_ref.dtype)
            vb = v_ref[0, :, hh, :].astype(q_ref.dtype)
            s = (
                jax.lax.dot_general(
                    q_ref[0, r0:r1, :], kb, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [Gp, bk] f32
            if quant:
                # score = (q . k_i8) * k_scale — per-(slot, head) scales
                # sit outside the head-dim dot product by construction
                # (checkpoint.quantize.kv_quantize blocks on HD).
                s = s * ks_ref[0, :, hh][None, :]
            keep = key_pos < length
            if window is not None:
                # layers.and_window in slot space: keys in
                # [length - window, length) == positions (p - window, p].
                keep = jnp.logical_and(keep, key_pos >= length - window)
            s = jnp.where(keep, s, _NEG_INF)
            m_prev = m_ref[r0:r1, 0]
            l_prev = l_ref[r0:r1, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            safe = jnp.where(m_new <= _NEG_INF * 0.5, 0.0, m_new)
            p = jnp.exp(s - safe[:, None])
            alpha = jnp.exp(m_prev - safe)
            l_ref[r0:r1, 0] = l_prev * alpha + jnp.sum(p, axis=-1)
            if quant:
                # out = sum_i p_i * (v_scale_i * v_i8_i): fold the scale
                # into the softmax weights (f32) before the value matmul.
                p = p * vs_ref[0, :, hh][None, :]
            acc_ref[r0:r1, :] = acc_ref[r0:r1, :] * alpha[
                :, None
            ] + jax.lax.dot_general(
                p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[r0:r1, 0] = m_new

    @pl.when(ji == num_k_blocks - 1)
    def _done():
        l = jnp.maximum(l_ref[:, 0], 1e-37)
        o_ref[0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)


def _kernel_paged(lengths_ref, tables_ref, *rest, **kw):
    """Paged variant: the page table is consumed ONLY by the BlockSpec index
    maps (it redirects each K block's DMA to the row's page in the pool);
    the compute body is identical to the contiguous kernel."""
    del tables_ref
    return _kernel(lengths_ref, *rest, **kw)


def _dequant(k, v, k_scale, v_scale, dtype):
    """Restore int8 K/V to the compute dtype for the dense fallback —
    checkpoint.quantize.kv_dequantize numerics (f32(data) * scale), the
    reference the fused kernel leg is parity-tested against."""
    from ..checkpoint.quantize import kv_dequantize

    return kv_dequantize(k, k_scale, dtype), kv_dequantize(v, v_scale, dtype)


def _check_quant(k, k_scale, v_scale):
    """Validate the int8 leg's argument contract (both scales or neither;
    int8 data when scales are present)."""
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale, or neither")
    if k_scale is not None and k.dtype != jnp.int8:
        raise ValueError(
            f"KV scales given but pages are {k.dtype}, not int8"
        )
    return k_scale is not None


def _dense_reference(q, k, v, lengths, window=None):
    """Masked dot-product prefix attention — the numerics the kernel must
    match and the fallback for untileable shapes / non-kernel modes.
    Mirrors layers.dot_product_attention exactly (f32 score accumulation,
    f32 softmax, probs cast to v.dtype) so substituting this fallback under
    ``cfg.ragged_decode`` cannot move tokens relative to the dense path."""
    from ..models import layers

    b, t, h, d = q.shape
    s = k.shape[1]
    g = h // k.shape[2]
    kf = layers.repeat_kv(k.astype(q.dtype), g)
    vf = layers.repeat_kv(v.astype(q.dtype), g)
    slots = jnp.arange(s, dtype=jnp.int32)
    mask = slots[None, :] < lengths[:, None]  # [B, S]
    if window is not None:
        mask = jnp.logical_and(mask, slots[None, :] >= lengths[:, None] - window)
    return layers.dot_product_attention(q, kf, vf, mask[:, None, None, :])


def _mode() -> str:
    """DLT_RAGGED_DECODE: "kernel" | "interpret" | "fallback" | "auto"
    (kernel iff TPU) — same resolution scheme as ops/quant_matmul.py."""
    mode = os.environ.get("DLT_RAGGED_DECODE", "auto")
    if mode in ("kernel", "interpret", "fallback"):
        return mode
    return "kernel" if jax.default_backend() == "tpu" else "fallback"


def _use_spmd(mode: str) -> bool:
    """Whether a call tracing under a GSPMD-partitioned jit should route
    through the custom_partitioning wrappers below (the decode-attention
    analogue of quant_matmul's DLT_QUANT_MATMUL_SPMD dispatch).  A plain
    pallas_call has no SPMD partitioning rule — without the wrapper XLA
    would all-gather the KV pool to one shard, defeating the sharded
    page pool entirely.  The dense fallback path needs no wrapper (XLA
    partitions plain lax ops itself), so "fallback" mode skips it.
    DLT_DECODE_ATTN_SPMD: "0" kill-switch, "1" force, default "auto"
    (wrapper whenever the kernel itself would run)."""
    from .quant_matmul import in_spmd_trace

    if not in_spmd_trace():
        return False
    env = os.environ.get("DLT_DECODE_ATTN_SPMD", "auto")
    if env == "0":
        return False
    return env == "1" or mode != "fallback"


def ragged_decode_attention(
    q: jax.Array,  # [B, 1, H, D] — one query token per row
    k: jax.Array,  # [B, S, KVH, D] full cache width
    v: jax.Array,  # [B, S, KVH, D]
    lengths: jax.Array,  # [B] int32 — row b attends slots [0, lengths[b])
    block_k: int = 256,
    window: int | None = None,  # sliding window: row b attends only
    #   [lengths[b] - window, lengths[b]) — the index maps clamp the DMA
    #   walk into that band, so windowed long-context decode reads
    #   O(window) KV bytes per row instead of O(length)
    k_scale: jax.Array | None = None,  # [B, S, KVH] f32 absmax scales —
    #   int8 leg: k/v are int8 and the kernel folds the per-(slot, head)
    #   scales into the attention contraction (q.k_i8 * scale)
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Returns [B, 1, H, D] in q.dtype.  Inference-only (no VJP).

    Under a GSPMD-partitioned trace (tensor-parallel serving) the call
    routes through :func:`_ragged_spmd` — each shard runs the kernel on
    its local KV-head slice; lengths shard with the batch axis (or
    replicate on a pure-TP mesh)."""
    mode = _mode()
    quant = _check_quant(k, k_scale, v_scale)
    if _use_spmd(mode):
        f = _ragged_spmd(block_k, window, quant, mode)
        args = (q, k, v, lengths.astype(jnp.int32))
        if quant:
            args += (k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32))
        return f(*args)
    return _ragged_impl(q, k, v, lengths, k_scale, v_scale,
                        block_k=block_k, window=window, mode=mode)


def _ragged_impl(
    q, k, v, lengths, k_scale=None, v_scale=None, *,
    block_k: int = 256, window: int | None = None, mode: str = "fallback",
) -> jax.Array:
    """The single-shard body: kernel when the (local) shapes tile, dense
    fallback otherwise — total over any shard, exactly like
    quant_matmul._qmm_flat."""
    b, t, h, d = q.shape
    assert t == 1, "ragged decode attention is single-token by construction"
    quant = k_scale is not None
    s, kvh = k.shape[1], k.shape[2]
    g = h // kvh
    # Largest K block that tiles the cache width exactly — a width that is a
    # 128-multiple but not a block_k-multiple (384, 640, ...) must step down
    # to a smaller block, not silently lose the kernel to the dense path —
    # AND whose whole-KVH K+V blocks fit double-buffered in VMEM.
    bk = next(
        (
            c
            for c in (min(block_k, 512), 256, 128)
            if c <= s and s % c == 0 and _kv_vmem_ok(c, kvh, d, k.dtype)
        ),
        None,
    )
    tileable = bk is not None and d % 128 == 0
    if mode == "fallback" or not tileable:
        if quant:
            k, v = _dequant(k, v, k_scale, v_scale, q.dtype)
        return _dense_reference(q, k, v, lengths, window)

    gp = _round_up(g, 8)  # sublane-pad the per-kv-head query group
    # [B, KVH, G, D]: head ordering h = kv*g + i matches repeat_kv /
    # flash's hi // g convention.  Reshaping/padding q copies only the tiny
    # query; k/v stay in the cache's NATIVE [B, S, KVH, D] layout — a 4D
    # BlockSpec slices (1, bk, KVH, D) blocks straight out of HBM, so the
    # cache is never transposed or copied (it is also the decode loop's
    # carry; a relayout would be a full extra read+write per step).
    qt = q[:, 0].reshape(b, kvh, g, d)
    if gp != g:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
    nk = s // bk

    def kv_index(bi, ji, lengths_ref):
        last = jax.lax.div(jnp.maximum(lengths_ref[bi] - 1, 0), bk)
        kk = jnp.minimum(ji, last)
        if window is not None:
            first = jax.lax.div(
                jnp.maximum(lengths_ref[bi] - window, 0), bk
            )
            kk = jnp.maximum(kk, first)
        return (bi, kk, 0, 0)

    def scale_index(bi, ji, lengths_ref):
        # Same DMA walk as the K/V blocks, one axis shorter ([B, S, KVH]).
        return kv_index(bi, ji, lengths_ref)[:3]

    in_specs = [
        pl.BlockSpec((1, kvh * gp, d), lambda bi, ji, L: (bi, 0, 0)),
        pl.BlockSpec((1, bk, kvh, d), kv_index),
        pl.BlockSpec((1, bk, kvh, d), kv_index),
    ]
    operands = [lengths.astype(jnp.int32), qt.reshape(b, kvh * gp, d), k, v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, bk, kvh), scale_index),
            pl.BlockSpec((1, bk, kvh), scale_index),
        ]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=d**-0.5, block_k=bk, num_k_blocks=nk,
            kvh=kvh, gp=gp, window=window, quant=quant,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, nk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, kvh * gp, d), lambda bi, ji, L: (bi, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((kvh * gp, d), jnp.float32),
                pltpu.VMEM((kvh * gp, 128), jnp.float32),
                pltpu.VMEM((kvh * gp, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kvh * gp, d), q.dtype),
        interpret=mode == "interpret",
    )(*operands)
    out = out.reshape(b, kvh, gp, d)[:, :, :g]  # [B, KVH, G, D]
    return out.reshape(b, 1, h, d)


def _kv_vmem_ok(bk: int, kvh: int, d: int, dtype) -> bool:
    """Whole-KVH K+V blocks, double-buffered, must leave room for scratch
    and the Mosaic pipeline inside ~16 MB of VMEM; budget half of it."""
    return 4 * bk * kvh * d * jnp.dtype(dtype).itemsize <= 8 * 1024 * 1024


def paged_decode_attention(
    q: jax.Array,  # [B, 1, H, D]
    k_pages: jax.Array,  # [NB, BLK, KVH, D] — the shared page pool
    v_pages: jax.Array,  # [NB, BLK, KVH, D]
    lengths: jax.Array,  # [B] int32 — row b attends its first lengths[b] slots
    tables: jax.Array,  # [B, P] int32 — page ids; entries past the row's
    #                     depth may be arbitrary (never dereferenced by the
    #                     kernel: the index map clamps to the last needed
    #                     page; the fallback masks their scores)
    k_scale: jax.Array | None = None,  # [NB, BLK, KVH] f32 absmax scales —
    #                     int8 leg: pages are int8 (QuantKVCache pools) and
    #                     the kernel fuses scale into the contraction, so
    #                     the pool reads 1 byte/elem and a dequantized page
    #                     never exists in HBM
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Paged variant of :func:`ragged_decode_attention`: the KV cache lives
    as pool pages indexed per row through a block table (vLLM-style memory
    management, TPU-native static shapes).  The page table is scalar-
    prefetched and consumed by the K/V BlockSpec index maps, so each row's
    DMA walks its own pages and reads only its real depth.  Returns
    [B, 1, H, D] in q.dtype.  Inference-only.

    Under a GSPMD-partitioned trace (tensor-parallel paged serving) the
    call routes through :func:`_paged_spmd`: the pool (and its int8
    scales) shard over the KV-head axis, each shard runs the kernel on
    its local head slice, and the page table + lengths replicate on a
    pure-TP mesh (they shard only with an explicit batch axis)."""
    mode = _mode()
    quant = _check_quant(k_pages, k_scale, v_scale)
    if _use_spmd(mode):
        f = _paged_spmd(quant, mode)
        args = (q, k_pages, v_pages, lengths.astype(jnp.int32),
                tables.astype(jnp.int32))
        if quant:
            args += (k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32))
        return f(*args)
    return _paged_impl(q, k_pages, v_pages, lengths, tables,
                       k_scale, v_scale, mode=mode)


def _paged_impl(
    q, k_pages, v_pages, lengths, tables, k_scale=None, v_scale=None, *,
    mode: str = "fallback",
) -> jax.Array:
    """Single-shard body of the paged kernel (see _ragged_impl)."""
    b, t, h, d = q.shape
    assert t == 1, "paged decode attention is single-token by construction"
    quant = k_scale is not None
    nb, blk, kvh = k_pages.shape[0], k_pages.shape[1], k_pages.shape[2]
    p = tables.shape[1]
    g = h // kvh
    tileable = (
        blk % 8 == 0 and d % 128 == 0 and _kv_vmem_ok(blk, kvh, d, k_pages.dtype)
    )
    if mode == "fallback" or not tileable:
        # Gather the rows' pages into contiguous [B, P*BLK] caches (the
        # fallback materializes; the kernel never does).  Int8 pools
        # dequantize the gathered rows at kv_dequantize numerics.
        k_rows = k_pages[tables].reshape(b, p * blk, kvh, d)
        v_rows = v_pages[tables].reshape(b, p * blk, kvh, d)
        if quant:
            k_rows, v_rows = _dequant(
                k_rows, v_rows,
                k_scale[tables].reshape(b, p * blk, kvh),
                v_scale[tables].reshape(b, p * blk, kvh),
                q.dtype,
            )
        return _dense_reference(q, k_rows, v_rows, lengths)

    gp = _round_up(g, 8)
    qt = q[:, 0].reshape(b, kvh, g, d)
    if gp != g:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, gp - g), (0, 0)))

    def kv_index(bi, ji, lengths_ref, tables_ref):
        last = jax.lax.div(jnp.maximum(lengths_ref[bi] - 1, 0), blk)
        return (tables_ref[bi, jnp.minimum(ji, last)], 0, 0, 0)

    def scale_index(bi, ji, lengths_ref, tables_ref):
        return kv_index(bi, ji, lengths_ref, tables_ref)[:3]

    in_specs = [
        pl.BlockSpec(
            (1, kvh * gp, d), lambda bi, ji, L, T: (bi, 0, 0)
        ),
        pl.BlockSpec((1, blk, kvh, d), kv_index),
        pl.BlockSpec((1, blk, kvh, d), kv_index),
    ]
    operands = [
        lengths.astype(jnp.int32), tables.astype(jnp.int32),
        qt.reshape(b, kvh * gp, d), k_pages, v_pages,
    ]
    if quant:
        in_specs += [
            pl.BlockSpec((1, blk, kvh), scale_index),
            pl.BlockSpec((1, blk, kvh), scale_index),
        ]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    out = pl.pallas_call(
        functools.partial(
            _kernel_paged, scale=d**-0.5, block_k=blk, num_k_blocks=p,
            kvh=kvh, gp=gp, quant=quant,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, p),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, kvh * gp, d), lambda bi, ji, L, T: (bi, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((kvh * gp, d), jnp.float32),
                pltpu.VMEM((kvh * gp, 128), jnp.float32),
                pltpu.VMEM((kvh * gp, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kvh * gp, d), q.dtype),
        interpret=mode == "interpret",
    )(*operands)
    out = out.reshape(b, kvh, gp, d)[:, :, :g]
    return out.reshape(b, 1, h, d)

# ---------------------------------------------------------------------------
# SPMD partitioning rules (tensor-parallel serving meshes)
# ---------------------------------------------------------------------------
#
# pallas_call has no built-in SPMD partitioning rule: traced bare under a
# GSPMD jit, XLA would all-gather the whole KV pool onto every shard —
# defeating the sharded page pool (and the contiguous mesh cache) entirely.
# The wrappers below supply the rule via jax.experimental.custom_partitioning,
# following the in-repo exemplar ops/quant_matmul._qmm_spmd: attention
# output heads are independent per KV head, so each shard runs the kernel
# unchanged on its LOCAL head slice (q heads and KV heads shard together
# over the same mesh axis; the grouped ratio g = H/KVH is shard-invariant)
# and no collective is needed.  Lengths and page tables shard only with an
# explicit batch axis — on a pure-TP mesh they replicate; int8 absmax
# scales shard with their pages on the KV-head axis.


def _spec_tuple(info, rank: int) -> tuple:
    spec = getattr(getattr(info, "sharding", None), "spec", None)
    t = tuple(spec) if spec is not None else ()
    return t + (None,) * (rank - len(t))


def _names(ax) -> tuple:
    return () if ax is None else (ax if isinstance(ax, tuple) else (ax,))


def _axis_sz(mesh, ax) -> int:
    sz = 1
    for nm in _names(ax):
        sz *= mesh.shape.get(nm, 1)
    return sz


def _resolve_decode_axes(mesh, q_info, kv_info, *, kv_batched: bool):
    """(batch_axis, head_axis) with divisibility enforced — shared by
    infer and partition (and the graftcheck GC2 audit surface) so they
    cannot disagree.  ``kv_info`` is the K operand: [B, S, KVH, D]
    contiguous (kv_batched) or [NB, BLK, KVH, D] pool pages."""
    qs = _spec_tuple(q_info, 4)
    ks = _spec_tuple(kv_info, 4)
    b_ax = qs[0]
    if b_ax is None and kv_batched:
        b_ax = ks[0]
    h_ax = ks[2] if ks[2] is not None else qs[2]
    b, _, h, _ = q_info.shape
    kvh = kv_info.shape[2]
    # Every shard must hold WHOLE heads on both operands (the kernel's
    # static head loop) — replicate the head axis when it doesn't divide.
    hs = _axis_sz(mesh, h_ax)
    if hs > 1 and (h % hs or kvh % hs):
        h_ax = None
    bs = _axis_sz(mesh, b_ax)
    if bs > 1 and b % bs:
        b_ax = None
    # A mesh axis may appear once per spec: on a collision keep the head
    # sharding (the sharded pool is the point) and replicate batch.
    if set(_names(b_ax)) & set(_names(h_ax)):
        b_ax = None
    return b_ax, h_ax


def _ragged_operand_specs(b_ax, h_ax, quant: bool) -> dict:
    from jax.sharding import PartitionSpec as P

    specs = {
        "q": P(b_ax, None, h_ax, None),
        "k": P(b_ax, None, h_ax, None),
        "v": P(b_ax, None, h_ax, None),
        "lengths": P(b_ax),
    }
    if quant:
        specs["k_scale"] = P(b_ax, None, h_ax)
        specs["v_scale"] = P(b_ax, None, h_ax)
    return specs


def _paged_operand_specs(b_ax, h_ax, quant: bool) -> dict:
    from jax.sharding import PartitionSpec as P

    specs = {
        "q": P(b_ax, None, h_ax, None),
        "k_pages": P(None, None, h_ax, None),
        "v_pages": P(None, None, h_ax, None),
        "lengths": P(b_ax),
        "tables": P(b_ax, None),
    }
    if quant:
        specs["k_scale"] = P(None, None, h_ax)
        specs["v_scale"] = P(None, None, h_ax)
    return specs


def spmd_operand_specs(
    mesh, q_shape: tuple, kv_shape: tuple, *, paged: bool,
    quant: bool = False, batch_axis="data", head_axis="model",
):
    """The operand PartitionSpecs the SPMD rule resolves for canonical
    inputs (batch over ``batch_axis``, KV heads over ``head_axis``) on
    ``mesh`` — the audit surface tools/graftcheck GC2 structure-matches
    against abstract operand trees (axis names, rank, divisibility).
    Returns (operand-spec dict, output spec).  Built on the SAME
    ``_resolve_decode_axes`` the partition rule runs, so the audit can
    never drift from the lowering."""
    from jax.sharding import PartitionSpec as P

    class _Info:
        def __init__(self, shape, spec):
            self.shape = shape
            self.sharding = type("S", (), {"spec": spec})()

    q_info = _Info(q_shape, P(batch_axis, None, head_axis, None))
    kv_spec = (P(batch_axis, None, head_axis, None) if not paged
               else P(None, None, head_axis, None))
    kv_info = _Info(kv_shape, kv_spec)
    b_ax, h_ax = _resolve_decode_axes(
        mesh, q_info, kv_info, kv_batched=not paged
    )
    build = _paged_operand_specs if paged else _ragged_operand_specs
    return build(b_ax, h_ax, quant), P(b_ax, None, h_ax, None)


@functools.lru_cache(maxsize=None)
def _ragged_spmd(block_k: int, window: int | None, quant: bool,
                 mode: str):
    """custom_partitioning wrapper for the ragged kernel: each shard runs
    :func:`_ragged_impl` on its local (batch, head) slice — untileable
    LOCAL shapes take the dense fallback inside the shard, so the wrapper
    is total over any placement.  lru_cache keyed on the static config —
    the RESOLVED mode included: a DLT_DECODE_ATTN_SPMD=1 force on a
    backend whose mode is "fallback" must run the dense body per shard,
    never the TPU kernel — so jit retracing reuses one wrapper instance
    per configuration."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    def impl(q, k, v, lengths, k_scale=None, v_scale=None):
        return _ragged_impl(q, k, v, lengths, k_scale, v_scale,
                            block_k=block_k, window=window, mode=mode)

    if quant:
        @custom_partitioning
        def f(q, k, v, lengths, k_scale, v_scale):
            return impl(q, k, v, lengths, k_scale, v_scale)
    else:
        @custom_partitioning
        def f(q, k, v, lengths):
            return impl(q, k, v, lengths)

    def _shardings(mesh, arg_infos):
        b_ax, h_ax = _resolve_decode_axes(
            mesh, arg_infos[0], arg_infos[1], kv_batched=True
        )
        specs = _ragged_operand_specs(b_ax, h_ax, quant)
        return (
            tuple(NamedSharding(mesh, s) for s in specs.values()),
            NamedSharding(mesh, P(b_ax, None, h_ax, None)),
        )

    def infer(mesh, arg_infos, result_infos):
        return _shardings(mesh, arg_infos)[1]

    def partition(mesh, arg_infos, result_infos):
        args, out = _shardings(mesh, arg_infos)
        return mesh, impl, out, args

    # Shardy factor rule: batch and heads propagate to the output; the
    # cache width and KV-head axes are independent factors (H != KVH
    # under GQA, so q's head axis cannot share the KV operands' factor).
    rule = "b u h d, b s k d, b s k d, b -> b u h d"
    if quant:
        rule = "b u h d, b s k d, b s k d, b, b s k, b s k -> b u h d"
    jaxcompat.def_partition(
        f, infer_sharding_from_operands=infer, partition=partition,
        sharding_rule=rule,
    )
    return f


@functools.lru_cache(maxsize=None)
def _paged_spmd(quant: bool, mode: str):
    """custom_partitioning wrapper for the paged kernel: the page pool
    (and its int8 scales) shard over the KV-head axis, each shard runs
    :func:`_paged_impl` on its local head slice, and the page table +
    lengths replicate on a pure-TP mesh (they shard only with an explicit
    batch axis).  No collective: attention output heads are independent
    per KV head.  Keyed on the RESOLVED mode (see _ragged_spmd)."""
    from jax.experimental.custom_partitioning import custom_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    def impl(q, k_pages, v_pages, lengths, tables, k_scale=None,
             v_scale=None):
        return _paged_impl(q, k_pages, v_pages, lengths, tables,
                           k_scale, v_scale, mode=mode)

    if quant:
        @custom_partitioning
        def f(q, k_pages, v_pages, lengths, tables, k_scale, v_scale):
            return impl(q, k_pages, v_pages, lengths, tables, k_scale,
                        v_scale)
    else:
        @custom_partitioning
        def f(q, k_pages, v_pages, lengths, tables):
            return impl(q, k_pages, v_pages, lengths, tables)

    def _shardings(mesh, arg_infos):
        b_ax, h_ax = _resolve_decode_axes(
            mesh, arg_infos[0], arg_infos[1], kv_batched=False
        )
        specs = _paged_operand_specs(b_ax, h_ax, quant)
        return (
            tuple(NamedSharding(mesh, s) for s in specs.values()),
            NamedSharding(mesh, P(b_ax, None, h_ax, None)),
        )

    def infer(mesh, arg_infos, result_infos):
        return _shardings(mesh, arg_infos)[1]

    def partition(mesh, arg_infos, result_infos):
        args, out = _shardings(mesh, arg_infos)
        return mesh, impl, out, args

    rule = "b u h d, n p k d, n p k d, b, b t -> b u h d"
    if quant:
        rule = "b u h d, n p k d, n p k d, b, b t, n p k, n p k -> b u h d"
    jaxcompat.def_partition(
        f, infer_sharding_from_operands=infer, partition=partition,
        sharding_rule=rule,
    )
    return f
