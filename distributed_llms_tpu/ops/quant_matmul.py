"""Fused dequantize-matmul Pallas kernel for weight-only quantized serving.

Replaces the dequantize-then-einsum path (models/model.py run_blocks) for
int8 / packed-int4 blockwise-quantized weights (checkpoint/quantize.py).
On the dequantize path XLA materializes a full-precision copy of every
weight in HBM each layer — measured ~9 bytes/param of HBM traffic per
decode step on a v5e (BASELINE.md config 3-int8: 52.8 tok/s, ~12% of HBM
bandwidth).  Decode is weight-bandwidth-bound, so the ceiling is set by
bytes-read-per-param: this kernel streams the int8/int4 weights HBM→VMEM,
dequantizes tiles in VMEM (VPU), and feeds the MXU directly — ~1.1 (int8)
or ~0.6 (int4) bytes/param, never writing a dequantized copy back to HBM.

The reference's quantization design (snippets.md:675-833) dequantized to
full precision before each use; there is no fused-kernel counterpart to
cite — this is the TPU-native replacement for that whole mechanism.

Numerics match checkpoint.quantize.dequantize: q is dequantized as
``f32(q) * scale`` then cast to the compute dtype before the matmul, with
f32 accumulation.  The kernel is inference-only (no VJP; training always
runs full-dtype weights).
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core import jaxcompat

# Trace-time marker: "this contraction is being traced under a
# GSPMD-partitioned jit" (tensor-parallel serving).  A plain pallas_call has
# no SPMD partitioning rule there — XLA would all-gather the full weight,
# defeating quantized residency — so quant_contract either takes the
# custom_partitioning wrapper (_qmm_spmd, the default when the kernel would
# run) or the dequantize+einsum fallback (DLT_QUANT_MATMUL_SPMD=0, or
# non-TPU).  ParallelModel wraps its GSPMD forward in spmd_fallback().
_SPMD_FALLBACK = contextvars.ContextVar("dlt_quant_spmd_fallback", default=False)


@contextlib.contextmanager
def spmd_fallback():
    token = _SPMD_FALLBACK.set(True)
    try:
        yield
    finally:
        _SPMD_FALLBACK.reset(token)


def in_spmd_trace() -> bool:
    """Whether the current trace runs under a GSPMD-partitioned jit
    (ParallelModel.forward wraps its GSPMD path in :func:`spmd_fallback`).
    Shared marker: ops/decode_attn.py consults it to route its kernels
    through their own custom_partitioning wrappers on tensor-parallel
    serving meshes."""
    return _SPMD_FALLBACK.get()

# Candidate tile sizes, largest first; a dimension uses the first candidate
# that divides it (grids must tile exactly — no masking on the K/N axes).
_BK_CANDIDATES = (512, 256, 128)
_BN_CANDIDATES = (512, 256, 128)
_BM_MAX = 256


def _pick(n: int, candidates: tuple[int, ...]) -> int | None:
    for c in candidates:
        if n % c == 0:
            return c
    return None


def _unpack_int4_rows(q: jax.Array) -> jax.Array:
    """[Kp, N] int32 packed nibbles -> [2*Kp, N] int32 values.  Low nibble =
    even K-row, high = odd (quantize() packs along the reduction axis):
    sign-extend via int32 shifts, then a sublane interleave, which Mosaic
    supports at any lane width.  Shared by the kernel and its flat-dequant
    fallback so the two layouts cannot diverge."""
    lo = (q << 28) >> 28
    hi = (q << 24) >> 28
    return jnp.stack([lo, hi], axis=1).reshape(q.shape[0] * 2, q.shape[1])


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, bits, block, nk, out_dtype):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[:].astype(jnp.int32)  # [bk, bn] int8, or [bk//2, bn] packed int4
    if bits == 4:
        q = _unpack_int4_rows(q)

    s = s_ref[0]  # [bk, bn // block] float32 (j-tile's slice of [nj, K, nb])
    bk, bn = q.shape
    wf = q.astype(jnp.float32).reshape(bk, bn // block, block) * s[:, :, None]
    w = wf.reshape(bk, bn).astype(x_ref.dtype)
    acc_ref[:] += jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _():
        o_ref[:] = acc_ref[:].astype(out_dtype)


@functools.partial(
    jax.jit,
    static_argnames=("bits", "block", "bm", "bk", "bn", "interpret", "vma"),
)
def _quant_matmul_2d(
    x: jax.Array,  # [M, K] float (M padded to a multiple of bm by caller)
    q: jax.Array,  # [K, N] int8, or [K//2, N] packed int4 (row-packed)
    s: jax.Array,  # [nj, K, bn // block] float32 — scales regrouped per
    #               N-tile so each grid step reads a full-last-dim block
    #               (Mosaic requires last-dim tiles of 128 or the whole axis)
    *,
    bits: int,
    block: int,
    bm: int,
    bk: int,
    bn: int,
    interpret: bool = False,
    vma: frozenset = frozenset(),  # varying manual axes inside shard_map
) -> jax.Array:
    m, k_dim = x.shape
    n = q.shape[1]
    grid = (m // bm, n // bn, k_dim // bk)
    bkp = bk // 2 if bits == 4 else bk
    kernel = functools.partial(
        _kernel, bits=bits, block=block, nk=grid[2], out_dtype=x.dtype
    )
    flops = 2 * m * k_dim * n
    out_shape = jaxcompat.shape_dtype_struct((m, n), x.dtype, vma=vma)
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda mi, j, k: (mi, k), memory_space=pltpu.VMEM),
            pl.BlockSpec((bkp, bn), lambda mi, j, k: (k, j), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, bk, bn // block),
                lambda mi, j, k: (j, k, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (bm, bn), lambda mi, j, k: (mi, j), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=q.size + s.size * 4 + x.size * x.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x, q, s)


def flatten_qt(qt, k_lead: int):
    """Reshape qt.data/scale to 2D for a [K, N] contraction over the first
    ``k_lead`` axes of the (logical, unpacked) weight.  Quant blocks run
    along the LAST axis only, so flattening trailing axes keeps blocks
    contiguous (block divides the last axis by quantize()'s construction).
    For int4 the data rows are packed pairs (K//2 of them); scale rows stay
    per-unpacked-row."""
    data, scale = qt.data, qt.scale
    kq = 1
    for d in data.shape[:k_lead]:
        kq *= d
    ks = 1
    for d in scale.shape[:k_lead]:
        ks *= d
    q2 = data.reshape(kq, -1)
    s2 = scale.reshape(ks, -1)
    n = q2.shape[1]
    block = n // s2.shape[1]
    return q2, s2, n, block


def _dequant_flat(q2: jax.Array, s2: jax.Array, bits: int, dtype) -> jax.Array:
    """Dequantize flat row-packed operands (the kernel's own layout) without
    the kernel — the local fallback when a (shard's) shape is untileable.
    Same math as checkpoint.quantize.dequantize for this layout."""
    q = q2.astype(jnp.int32)
    if bits == 4:
        q = _unpack_int4_rows(q)
    n = q.shape[1]
    nb = s2.shape[1]
    block = n // nb
    w = (
        q.astype(jnp.float32).reshape(q.shape[0], nb, block) * s2[:, :, None]
    ).reshape(q.shape[0], n)
    return w.astype(dtype)


def _qmm_flat(x2: jax.Array, q2: jax.Array, s2: jax.Array, *, bits: int,
              interpret: bool) -> jax.Array:
    """[M, K] @ dequant([K(-packed), N]) from flat operands.  Shapes are the
    LOCAL (per-shard, under custom_partitioning) shapes: tile sizes, M
    padding, and the scale regroup all derive from them; untileable shapes
    take the dequant+matmul fallback, so this is total over any shard."""
    m, k = x2.shape
    n = q2.shape[1]
    nb = s2.shape[1]
    block = n // nb
    bk = _pick(k, _BK_CANDIDATES)
    bn = _pick(n, _BN_CANDIDATES)
    tileable = (
        bk is not None and bn is not None
        and block % 128 == 0 and bn % block == 0
        and (bits == 8 or bk // 2 >= 8)
    )
    if not tileable:
        return x2 @ _dequant_flat(q2, s2, bits, x2.dtype)
    # Inside shard_map (the pipeline stage body) operands carry varying
    # manual axes; the kernel's out_shape must declare the same set.
    vma = frozenset().union(*(jaxcompat.vma_of(a) for a in (x2, q2, s2)))
    if vma and interpret:
        # The Pallas HLO *interpreter* (off-TPU test path) loses vma on its
        # internal dynamic_slices (same limitation as ops/flash.py); run the
        # numerically-identical flat dequant there.  Real TPU lowering takes
        # the kernel, with vma declared on its out_shape.
        return x2 @ _dequant_flat(q2, s2, bits, x2.dtype)
    bm = min(_BM_MAX, max(16, -(-m // 16) * 16))
    m_pad = -(-m // bm) * bm
    if m_pad != m:
        x2 = jnp.pad(x2, ((0, m_pad - m), (0, 0)))
    # Regroup scales per N-tile: [K, NB] -> [nj, K, nb].  Tiny arrays
    # (params/block floats); the transpose is a few % of the int8 bytes.
    nj, nbt = n // bn, bn // block
    s3 = s2.reshape(k, nj, nbt).transpose(1, 0, 2)
    return _quant_matmul_2d(
        x2, q2, s3, bits=bits, block=block, bm=bm, bk=bk, bn=bn,
        interpret=interpret, vma=vma,
    )[:m]


def _spec_tuple(info, rank: int) -> tuple:
    spec = getattr(getattr(info, "sharding", None), "spec", None)
    t = tuple(spec) if spec is not None else ()
    return t + (None,) * (rank - len(t))


@functools.lru_cache(maxsize=None)
def _qmm_spmd(bits: int, interpret: bool):
    """SPMD-partitionable fused quant matmul (default under GSPMD whenever
    the kernel would run; DLT_QUANT_MATMUL_SPMD=0 disables).  pallas_call
    has no built-in SPMD partitioning rule; this wrapper supplies one via
    jax.experimental.custom_partitioning: each shard runs the kernel on its
    local tiles (N-sharded weights run embarrassingly parallel; K-sharded
    weights — wo under tensor parallelism — compute partial products and
    psum over the contracted mesh axes).

    History: earlier JAX releases failed on custom_partitioning inside
    ``lax.scan`` (op_sharding superdim KeyError), which forced round 3's
    GSPMD serving onto the dequantize+einsum fallback.  The JAX in this
    image compiles the wrapper under a scan both with scan-invariant
    weights and with the stacked weights scanned as xs (pinned by
    tests/parallel/test_quantized_mesh.py::
    test_spmd_kernel_wrapper_under_scan), so GSPMD quantized serving now
    takes the kernel by default; DLT_QUANT_MATMUL_SPMD=0 is the
    kill-switch if real-TPU Mosaic lowering disagrees."""
    from jax.experimental.custom_partitioning import custom_partitioning

    @custom_partitioning
    def f(x2, q2, s2):
        return _qmm_flat(x2, q2, s2, bits=bits, interpret=interpret)

    def _names(ax):
        return () if ax is None else (ax if isinstance(ax, tuple) else (ax,))

    def _resolve_axes(mesh, arg_infos):
        """(m_ax, k_ax, n_ax) with every mesh axis used at most once —
        shared by infer and partition so they cannot disagree."""

        def axis_size(ax):
            sz = 1
            for nm in _names(ax):
                sz *= mesh.shape.get(nm, 1)
            return sz

        xs = _spec_tuple(arg_infos[0], 2)
        qs = _spec_tuple(arg_infos[1], 2)
        m_ax = xs[0]
        n_ax = qs[1]
        k_ax = qs[0] if qs[0] is not None else xs[1]
        # Scale blocks must divide over the N shards or each shard's local
        # block derivation goes wrong — when they don't, keep q AND s
        # replicated along N together (redundant compute, correct numerics).
        # Placement-time refinement (parallel.api._place_quantized) normally
        # makes them divide.
        nb = arg_infos[2].shape[1]
        if nb % max(axis_size(n_ax), 1):
            n_ax = None
        # A mesh axis may appear once per spec: prefer the weight's N
        # sharding over a colliding activation-K sharding, and replicate M
        # when the batch axis collides with either (FSDP-style placements) —
        # rather than crash at inference/lowering.
        if set(_names(k_ax)) & set(_names(n_ax)):
            k_ax = None
        if set(_names(m_ax)) & (set(_names(k_ax)) | set(_names(n_ax))):
            m_ax = None
        return m_ax, k_ax, n_ax

    def infer(mesh, arg_infos, result_infos):
        from jax.sharding import NamedSharding, PartitionSpec as P

        m_ax, _, n_ax = _resolve_axes(mesh, arg_infos)
        return NamedSharding(mesh, P(m_ax, n_ax))

    def partition(mesh, arg_infos, result_infos):
        from jax.sharding import NamedSharding, PartitionSpec as P

        m_ax, k_ax, n_ax = _resolve_axes(mesh, arg_infos)
        k_names = _names(k_ax)

        def lower(x2, q2, s2):
            y = _qmm_flat(x2, q2, s2, bits=bits, interpret=interpret)
            if k_names:
                y = jax.lax.psum(y, k_names)
            return y

        args = (
            NamedSharding(mesh, P(m_ax, k_ax)),
            NamedSharding(mesh, P(k_ax, n_ax)),
            NamedSharding(mesh, P(k_ax, n_ax)),
        )
        return mesh, lower, NamedSharding(mesh, P(m_ax, n_ax)), args

    jaxcompat.def_partition(
        f,
        infer_sharding_from_operands=infer,
        partition=partition,
        # Shardy factor rule: m/n propagate to the output; the contracted and
        # block axes are independent factors (int4 packs K, so x's K and q's
        # rows differ in size and cannot share a factor).  (Attached only on
        # runtimes whose def_partition takes it — jaxcompat.def_partition —
        # the 0.4.x signature raised TypeError, which silently disarmed this
        # wrapper on the current image.)
        sharding_rule="m k, p n, q b -> m n",
    )
    return f


def _kernel_mode() -> str:
    """Resolve DLT_QUANT_MATMUL: "kernel" (compiled Pallas), "interpret"
    (Pallas interpret mode — the CI leg that runs the kernel's exact program
    on CPU), "fallback" (dequantize+einsum), or "auto" (kernel iff TPU)."""
    mode = os.environ.get("DLT_QUANT_MATMUL", "auto")
    if mode in ("kernel", "interpret", "fallback"):
        return mode
    return "kernel" if jax.default_backend() == "tpu" else "fallback"


def quant_contract(
    x: jax.Array, qt, k_lead: int, eq: str | None = None, *, interpret: bool = False
):
    """x[..., K-axes] @ dequant(W)[K-axes, N-axes] with W blockwise-quantized.

    ``k_lead``: how many leading axes of the weight contract (1 for
    wq/wk/wv/w_in/w_gate/w_up/w_down, 2 for wo [H, hd, D]).  The matching
    trailing axes of ``x`` flatten to K; the weight's remaining axes are
    restored on the output.  Dispatches to the Pallas kernel on TPU (or when
    DLT_QUANT_MATMUL=kernel); otherwise dequantize + einsum over ``eq`` —
    bit-identical to the pre-kernel serving path.
    """
    out_tail = list(qt.data.shape[k_lead:])  # N axes are never packed
    lead = x.shape[: x.ndim - k_lead]
    k = 1
    for d in x.shape[x.ndim - k_lead:]:
        k *= d
    x2 = x.reshape(-1, k)

    mode = _kernel_mode()
    in_gspmd = _SPMD_FALLBACK.get()
    spmd_env = os.environ.get("DLT_QUANT_MATMUL_SPMD", "auto")
    # Under a GSPMD trace the kernel needs its custom_partitioning wrapper
    # (plain pallas_call has no SPMD rule; XLA would all-gather the weight).
    # Default ("auto"): take the wrapper whenever the kernel itself would run
    # — the JAX in this image no longer hits the op_sharding superdim bug
    # with the wrapper under lax.scan, even with the stacked weights scanned
    # as xs (verified both ways; see test_spmd_kernel_wrapper_under_scan).
    # "0" restores the round-3 dequant+einsum fallback (kill-switch if
    # Mosaic + scan misbehaves on real hardware); "1" forces the wrapper
    # even when mode would resolve to fallback.
    use_spmd_kernel = in_gspmd and (
        spmd_env == "1" or (spmd_env != "0" and mode != "fallback")
    )
    if in_gspmd and not use_spmd_kernel:
        mode = "fallback"
    elif use_spmd_kernel and mode == "fallback":
        # "1" really does force the wrapper, even on a backend whose mode
        # resolved to fallback — otherwise the dispatch gate below would
        # quietly run dequant+einsum while the operator believes the
        # wrapper was exercised.
        mode = "kernel"
    if interpret:  # explicit test request wins even inside spmd_fallback
        mode = "interpret"
    # int4: the kernel's sublane unpack (and _dequant_flat) assume the pack
    # pairs run along the LAST K axis (quantize_tree's convention).
    pack_ok = qt.bits == 8 or qt.data.ndim + qt.pack_axis == k_lead - 1
    if mode != "fallback" and pack_ok:
        interpret = mode == "interpret"
        q2, s2, n, block = flatten_qt(qt, k_lead)
        if use_spmd_kernel:
            # GSPMD trace: the custom_partitioning wrapper gives the kernel
            # an SPMD rule (per-shard tiles; psum over contracted axes).
            y2 = _qmm_spmd(qt.bits, interpret)(x2, q2, s2)
        else:
            y2 = _qmm_flat(x2, q2, s2, bits=qt.bits, interpret=interpret)
        return y2.reshape(*lead, *out_tail)

    # Fallback: dequantize then contract (XLA fuses what it can).  Matches
    # models/model.py's historical dequant-at-use numerics exactly.
    from ..checkpoint.quantize import dequantize

    w = dequantize(qt, x.dtype)
    if eq is not None:
        return jnp.einsum(eq, x, w)
    return (x2 @ w.reshape(k, -1)).reshape(*lead, *out_tail)
