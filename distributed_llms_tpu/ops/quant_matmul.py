"""Fused dequantize-matmul Pallas kernel for weight-only quantized serving.

Replaces the dequantize-then-einsum path (models/model.py run_blocks) for
int8 / packed-int4 blockwise-quantized weights (checkpoint/quantize.py).
On the dequantize path XLA materializes a full-precision copy of every
weight in HBM each layer — measured ~9 bytes/param of HBM traffic per
decode step on a v5e (BASELINE.md config 3-int8: 52.8 tok/s, ~12% of HBM
bandwidth).  Decode is weight-bandwidth-bound, so the ceiling is set by
bytes-read-per-param: this kernel streams the int8/int4 weights HBM→VMEM,
dequantizes tiles in VMEM (VPU), and feeds the MXU directly — ~1.1 (int8)
or ~0.6 (int4) bytes/param, never writing a dequantized copy back to HBM.

The reference's quantization design (snippets.md:675-833) dequantized to
full precision before each use; there is no fused-kernel counterpart to
cite — this is the TPU-native replacement for that whole mechanism.

Numerics match checkpoint.quantize.dequantize: q is dequantized as
``f32(q) * scale`` then cast to the compute dtype before the matmul, with
f32 accumulation.  The kernel is inference-only (no VJP; training always
runs full-dtype weights).
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Trace-time guard: pallas_call has no SPMD partitioning rule, so under a
# GSPMD-partitioned jit (tensor-parallel serving) the kernel would force XLA
# to all-gather the full weight — defeating quantized residency.  The
# dequantize+einsum path partitions cleanly; ParallelModel wraps its GSPMD
# forward in spmd_fallback().
_SPMD_FALLBACK = contextvars.ContextVar("dlt_quant_spmd_fallback", default=False)


@contextlib.contextmanager
def spmd_fallback():
    token = _SPMD_FALLBACK.set(True)
    try:
        yield
    finally:
        _SPMD_FALLBACK.reset(token)

# Candidate tile sizes, largest first; a dimension uses the first candidate
# that divides it (grids must tile exactly — no masking on the K/N axes).
_BK_CANDIDATES = (512, 256, 128)
_BN_CANDIDATES = (512, 256, 128)
_BM_MAX = 256


def _pick(n: int, candidates: tuple[int, ...]) -> int | None:
    for c in candidates:
        if n % c == 0:
            return c
    return None


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, bits, block, nk, out_dtype):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[:].astype(jnp.int32)  # [bk, bn] int8, or [bk//2, bn] packed int4
    if bits == 4:
        # Unpack nibbles (low = even K-row, high = odd — quantize() packs
        # along the reduction axis): sign-extend via int32 shifts, then a
        # sublane interleave, which Mosaic supports at any lane width.
        lo = (q << 28) >> 28
        hi = (q << 24) >> 28
        q = jnp.stack([lo, hi], axis=1).reshape(q.shape[0] * 2, q.shape[1])

    s = s_ref[0]  # [bk, bn // block] float32 (j-tile's slice of [nj, K, nb])
    bk, bn = q.shape
    wf = q.astype(jnp.float32).reshape(bk, bn // block, block) * s[:, :, None]
    w = wf.reshape(bk, bn).astype(x_ref.dtype)
    acc_ref[:] += jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _():
        o_ref[:] = acc_ref[:].astype(out_dtype)


@functools.partial(
    jax.jit, static_argnames=("bits", "block", "bm", "bk", "bn", "interpret")
)
def _quant_matmul_2d(
    x: jax.Array,  # [M, K] float (M padded to a multiple of bm by caller)
    q: jax.Array,  # [K, N] int8, or [K//2, N] packed int4 (row-packed)
    s: jax.Array,  # [nj, K, bn // block] float32 — scales regrouped per
    #               N-tile so each grid step reads a full-last-dim block
    #               (Mosaic requires last-dim tiles of 128 or the whole axis)
    *,
    bits: int,
    block: int,
    bm: int,
    bk: int,
    bn: int,
    interpret: bool = False,
) -> jax.Array:
    m, k_dim = x.shape
    n = q.shape[1]
    grid = (m // bm, n // bn, k_dim // bk)
    bkp = bk // 2 if bits == 4 else bk
    kernel = functools.partial(
        _kernel, bits=bits, block=block, nk=grid[2], out_dtype=x.dtype
    )
    flops = 2 * m * k_dim * n
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda mi, j, k: (mi, k), memory_space=pltpu.VMEM),
            pl.BlockSpec((bkp, bn), lambda mi, j, k: (k, j), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, bk, bn // block),
                lambda mi, j, k: (j, k, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (bm, bn), lambda mi, j, k: (mi, j), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=q.size + s.size * 4 + x.size * x.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x, q, s)


def flatten_qt(qt, k_lead: int):
    """Reshape qt.data/scale to 2D for a [K, N] contraction over the first
    ``k_lead`` axes of the (logical, unpacked) weight.  Quant blocks run
    along the LAST axis only, so flattening trailing axes keeps blocks
    contiguous (block divides the last axis by quantize()'s construction).
    For int4 the data rows are packed pairs (K//2 of them); scale rows stay
    per-unpacked-row."""
    data, scale = qt.data, qt.scale
    kq = 1
    for d in data.shape[:k_lead]:
        kq *= d
    ks = 1
    for d in scale.shape[:k_lead]:
        ks *= d
    q2 = data.reshape(kq, -1)
    s2 = scale.reshape(ks, -1)
    n = q2.shape[1]
    block = n // s2.shape[1]
    return q2, s2, n, block


def _kernel_mode() -> str:
    """Resolve DLT_QUANT_MATMUL: "kernel" (compiled Pallas), "interpret"
    (Pallas interpret mode — the CI leg that runs the kernel's exact program
    on CPU), "fallback" (dequantize+einsum), or "auto" (kernel iff TPU)."""
    mode = os.environ.get("DLT_QUANT_MATMUL", "auto")
    if mode in ("kernel", "interpret", "fallback"):
        return mode
    return "kernel" if jax.default_backend() == "tpu" else "fallback"


def quant_contract(
    x: jax.Array, qt, k_lead: int, eq: str | None = None, *, interpret: bool = False
):
    """x[..., K-axes] @ dequant(W)[K-axes, N-axes] with W blockwise-quantized.

    ``k_lead``: how many leading axes of the weight contract (1 for
    wq/wk/wv/w_in/w_gate/w_up/w_down, 2 for wo [H, hd, D]).  The matching
    trailing axes of ``x`` flatten to K; the weight's remaining axes are
    restored on the output.  Dispatches to the Pallas kernel on TPU (or when
    DLT_QUANT_MATMUL=kernel); otherwise dequantize + einsum over ``eq`` —
    bit-identical to the pre-kernel serving path.
    """
    out_tail = list(qt.data.shape[k_lead:])  # N axes are never packed
    lead = x.shape[: x.ndim - k_lead]
    k = 1
    for d in x.shape[x.ndim - k_lead:]:
        k *= d
    x2 = x.reshape(-1, k)

    mode = _kernel_mode()
    if _SPMD_FALLBACK.get():
        mode = "fallback"
    if interpret:  # explicit test request wins even inside spmd_fallback
        mode = "interpret"
    if mode != "fallback":
        interpret = mode == "interpret"
        q2, s2, n, block = flatten_qt(qt, k_lead)
        bk = _pick(k, _BK_CANDIDATES)
        bn = _pick(n, _BN_CANDIDATES)
        tileable = (
            bk is not None
            and bn is not None
            and block % 128 == 0
            and bn % block == 0
            # int4: the kernel's sublane unpack assumes the pack pairs run
            # along the LAST K axis (quantize_tree's convention); packed row
            # tiles must still meet the 8-sublane minimum.
            and (
                qt.bits == 8
                or (qt.data.ndim + qt.pack_axis == k_lead - 1 and bk // 2 >= 8)
            )
        )
        if tileable:
            m = x2.shape[0]
            bm = min(_BM_MAX, max(16, -(-m // 16) * 16))
            m_pad = -(-m // bm) * bm
            if m_pad != m:
                x2 = jnp.pad(x2, ((0, m_pad - m), (0, 0)))
            # Regroup scales per N-tile: [K, NB] -> [nj, K, nb].  Tiny arrays
            # (params/32 floats); the transpose is ~3% of the int8 bytes.
            nj, nb = n // bn, bn // block
            s3 = s2.reshape(k, nj, nb).transpose(1, 0, 2)
            y2 = _quant_matmul_2d(
                x2, q2, s3, bits=qt.bits, block=block,
                bm=bm, bk=bk, bn=bn, interpret=interpret,
            )[:m]
            return y2.reshape(*lead, *out_tail)

    # Fallback: dequantize then contract (XLA fuses what it can).  Matches
    # models/model.py's historical dequant-at-use numerics exactly.
    from ..checkpoint.quantize import dequantize

    w = dequantize(qt, x.dtype)
    if eq is not None:
        return jnp.einsum(eq, x, w)
    return (x2 @ w.reshape(k, -1)).reshape(*lead, *out_tail)
