"""Ring attention: sequence/context parallelism over a ``seq`` mesh axis.

Net-new capability relative to the reference, which has no sequence
parallelism of any kind (SURVEY §5.7: grep for ring/context/sequence over the
tree finds nothing; sequence length is never even a parameter —
snippets.md:633's dummy ``(1, 768)`` input is the only sequence notion).

Design (blockwise attention with rotating KV, scaling-book style):

- the sequence axis of Q/K/V is sharded over the ``seq`` mesh axis inside
  ``shard_map``; each device owns one contiguous sequence block;
- K/V (plus their global positions) rotate one hop around the ring per step
  via ``lax.ppermute`` over ICI, for ``seq`` steps total;
- each device accumulates attention over the visiting KV blocks with a
  numerically-stable *online softmax* (running max / numerator / denominator,
  exactly the flash-attention recurrence), so the full [Tq, Tk] score matrix
  never materializes;
- causality falls out of masking on *global positions* carried with the
  rotating KV block — no per-step index arithmetic, and fully-masked blocks
  contribute exp(-inf)=0 without NaNs;
- the ppermute is issued before the block compute consumes it on the next
  scan iteration, letting XLA overlap the hop with local attention.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core import jaxcompat

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def _block_scores(
    q: jax.Array,  # [B, Tq, H, D]
    k: jax.Array,  # [B, Tk, KVH, D]
    q_pos: jax.Array,  # [B, Tq]
    k_pos: jax.Array,  # [B, Tk]
    k_valid: jax.Array,  # [B, Tk] bool
    causal: bool,
    q_per_kv: int,
) -> jax.Array:
    """Masked f32 logits [B, H, Tq, Tk] for one KV block (GQA-aware)."""
    scale = q.shape[-1] ** -0.5
    if q_per_kv > 1:
        b, tq, h, d = q.shape
        qg = q.reshape(b, tq, h // q_per_kv, q_per_kv, d)
        logits = jnp.einsum(
            "bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32
        ).reshape(b, h, tq, k.shape[1])
    else:
        logits = jnp.einsum("bqhd,bshd->bhqs", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    mask = k_valid[:, None, None, :]
    if causal:
        mask = jnp.logical_and(mask, k_pos[:, None, None, :] <= q_pos[:, None, :, None])
    return jnp.where(mask, logits, _NEG_INF)


def _block_pv(probs: jax.Array, v: jax.Array, q_per_kv: int) -> jax.Array:
    """probs [B, H, Tq, Tk] @ v [B, Tk, KVH, D] -> [B, Tq, H, D] (GQA-aware)."""
    if q_per_kv > 1:
        b, h, tq, tk = probs.shape
        pg = probs.reshape(b, h // q_per_kv, q_per_kv, tq, tk)
        out = jnp.einsum("bkgqs,bskd->bqkgd", pg.astype(v.dtype), v)
        return out.reshape(b, tq, h, v.shape[-1])
    return jnp.einsum("bhqs,bshd->bqhd", probs.astype(v.dtype), v)


def ring_attention(
    q: jax.Array,  # [B, Tq_local, H, D]   — local sequence block
    k: jax.Array,  # [B, Tk_local, KVH, D]
    v: jax.Array,  # [B, Tk_local, KVH, D]
    q_positions: jax.Array,  # [B, Tq_local] global positions
    k_positions: jax.Array,  # [B, Tk_local] global positions
    axis_name: str = "seq",
    causal: bool = True,
    k_valid: jax.Array | None = None,  # [B, Tk_local] bool
) -> jax.Array:
    """Ring attention body — call *inside* ``shard_map`` with the sequence
    axis sharded over ``axis_name``.  Returns [B, Tq_local, H, D].

    Works for any KVH dividing H (grouped-query attention); the score matrix
    per step is only [B, H, Tq/S, Tk/S].
    """
    try:
        num_blocks = jaxcompat.axis_size(axis_name)
    except NameError as e:
        raise RuntimeError(
            f"ring attention needs a bound {axis_name!r} mesh axis — call it "
            "inside shard_map (e.g. via ParallelModel with MeshConfig(seq=N)); "
            "attn_impl='ring' is set internally by that path, not by user config"
        ) from e
    q_per_kv = q.shape[2] // k.shape[2]
    b, tq, h, d = q.shape
    if k_valid is None:
        # Freshly created => not device-varying over the ring axis yet; mark
        # it so the rotating scan carry has consistent vma types.
        k_valid = jaxcompat.pcast(
            jnp.ones(k_positions.shape, dtype=bool), (axis_name,), to="varying"
        )

    perm = [(i, (i + 1) % num_blocks) for i in range(num_blocks)]

    def accumulate(acc, k_blk, v_blk, kpos_blk, kvalid_blk):
        num, den, mx = acc
        logits = _block_scores(q, k_blk, q_positions, kpos_blk, kvalid_blk, causal, q_per_kv)
        blk_max = jnp.max(logits, axis=-1)  # [B, H, Tq]
        new_max = jnp.maximum(mx, blk_max)
        # Rows where every block so far is masked have new_max == _NEG_INF
        # (finite finfo.min, not -inf): subtracting it verbatim would give
        # exp(0)=1 on masked entries.  Substitute 0 so those rows underflow
        # to exp(_NEG_INF) = 0 and contribute nothing.
        safe_max = jnp.where(new_max <= _NEG_INF * 0.5, 0.0, new_max)
        probs = jnp.exp(logits - safe_max[..., None])
        alpha = jnp.exp(mx - safe_max)  # rescale old accumulators (0 while mx unseeded)
        num = num * alpha[..., None].transpose(0, 2, 1, 3) + _block_pv(
            probs, v_blk, q_per_kv
        ).astype(jnp.float32)
        den = den * alpha + jnp.sum(probs, axis=-1)
        return num, den, new_max

    def step(carry, _):
        # Rotate first, then accumulate: the local block's contribution is
        # peeled off before the scan, so only num_blocks-1 hops are issued —
        # no discarded final ppermute.  XLA overlaps the hop with compute.
        k_blk, v_blk, kpos_blk, kvalid_blk, *acc = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        kpos_blk = jax.lax.ppermute(kpos_blk, axis_name, perm)
        kvalid_blk = jax.lax.ppermute(kvalid_blk, axis_name, perm)
        acc = accumulate(tuple(acc), k_blk, v_blk, kpos_blk, kvalid_blk)
        return (k_blk, v_blk, kpos_blk, kvalid_blk, *acc), None

    # Accumulators are device-varying over the ring axis (vma tracking).
    varying = lambda x: jaxcompat.pcast(x, (axis_name,), to="varying")
    num0 = varying(jnp.zeros((b, tq, h, d), jnp.float32))
    den0 = varying(jnp.zeros((b, h, tq), jnp.float32))
    max0 = varying(jnp.full((b, h, tq), _NEG_INF, jnp.float32))
    acc = accumulate((num0, den0, max0), k, v, k_positions, k_valid)
    carry = (k, v, k_positions, k_valid, *acc)
    (_, _, _, _, num, den, _), _ = jax.lax.scan(
        step, carry, None, length=num_blocks - 1
    )
    den = den.transpose(0, 2, 1)[..., None]  # [B, Tq, H, 1]
    out = num / jnp.maximum(den, 1e-37)
    return out.astype(q.dtype)


def seq_cached_decode_attention(
    q: jax.Array,  # [B, 1, H, D] — replicated over the seq axis
    ck_local: jax.Array,  # [B, S_loc, KVH, D] — this device's prefill KV block
    cv_local: jax.Array,
    dk: jax.Array,  # [B, N, KVH, D] — decode-region KV, replicated
    dv: jax.Array,
    mask_local: jax.Array,  # [B, S_loc] bool — this device's slice of the key mask
    mask_dec: jax.Array,  # [B, N] bool
    axis_name: str = "seq",
) -> jax.Array:
    """Single-token decode over a sequence-sharded KV cache (long-context
    generation, SURVEY §5.7 — the part ring prefill alone leaves open).

    Decode inverts ring attention's economics: the query is one token, so
    rotating KV blocks would move O(S) bytes to meet O(1) queries.  Instead
    the KV stays put: every device computes flash-style partial softmax stats
    (max / numerator / denominator) over its resident block, and one psum
    over ``axis_name`` merges them — the only collective in the step.  The
    decode region (tokens generated after prefill) is replicated on every
    device — it is bounded by max_new_tokens, a sliver next to a long
    prompt — so its stats merge locally with no ownership bookkeeping.

    Returns [B, 1, H, D], identical on every device of the seq axis.
    """
    q_per_kv = q.shape[2] // ck_local.shape[2]

    def stats(k_blk, v_blk, valid):
        logits = _block_scores(
            q, k_blk, q, k_blk, valid, causal=False, q_per_kv=q_per_kv
        )  # positions unused with causal=False
        mx = jnp.max(logits, axis=-1)  # [B, H, 1]
        safe = jnp.where(mx <= _NEG_INF * 0.5, 0.0, mx)
        probs = jnp.exp(logits - safe[..., None])
        num = _block_pv(probs, v_blk, q_per_kv).astype(jnp.float32)  # [B,1,H,D]
        den = jnp.sum(probs, axis=-1)  # [B, H, 1]
        return num, den, mx

    # Local prefill block -> psum-merged global prefill stats.
    num_l, den_l, mx_l = stats(ck_local, cv_local, mask_local)
    mx_p = jax.lax.pmax(mx_l, axis_name)
    safe_p = jnp.where(mx_p <= _NEG_INF * 0.5, 0.0, mx_p)
    scale_l = jnp.exp(mx_l - safe_p)  # 0 for fully-masked local blocks
    num_p = jax.lax.psum(num_l * scale_l[..., None].transpose(0, 2, 1, 3), axis_name)
    den_p = jax.lax.psum(den_l * scale_l, axis_name)

    # Decode region (replicated, computed identically everywhere).
    num_d, den_d, mx_d = stats(dk, dv, mask_dec)

    # Final merge of the two partial softmaxes.
    mx = jnp.maximum(mx_p, mx_d)
    safe = jnp.where(mx <= _NEG_INF * 0.5, 0.0, mx)
    a_p = jnp.exp(mx_p - safe)[..., None].transpose(0, 2, 1, 3)
    a_d = jnp.exp(mx_d - safe)[..., None].transpose(0, 2, 1, 3)
    num = num_p * a_p + num_d * a_d
    den = (den_p * jnp.exp(mx_p - safe) + den_d * jnp.exp(mx_d - safe))
    den = den.transpose(0, 2, 1)[..., None]  # [B, 1, H, 1]
    return (num / jnp.maximum(den, 1e-37)).astype(q.dtype)


def ring_self_attention(
    mesh: Mesh,
    q: jax.Array,  # [B, T, H, D] global
    k: jax.Array,  # [B, T, KVH, D]
    v: jax.Array,
    positions: jax.Array,  # [B, T]
    causal: bool = True,
    seq_axis: str = "seq",
) -> jax.Array:
    """Host-level wrapper: shards the sequence axis over ``seq_axis`` and runs
    :func:`ring_attention`.  Batch stays on 'data'; heads stay on 'model'
    (GSPMD-auto inside the body)."""
    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal)
    seq_sharded = P(None, seq_axis, None, None)
    pos_sharded = P(None, seq_axis)
    return jaxcompat.shard_map(
        fn,
        mesh=mesh,
        in_specs=(seq_sharded, seq_sharded, seq_sharded, pos_sharded, pos_sharded),
        out_specs=seq_sharded,
        axis_names={seq_axis},
    )(q, k, v, positions, positions)
