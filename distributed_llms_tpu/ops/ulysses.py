"""Ulysses sequence parallelism: all-to-all head-scatter attention.

The second sequence-parallel strategy (SURVEY §2.3: "Ulysses ... all-to-all
on heads<->sequence ... optional, after ring attention").  Where ring
attention keeps the sequence sharded and rotates KV blocks around the ring,
Ulysses re-shards *once* per attention call:

    [B, T/S, H, D]  --all_to_all-->  [B, T, H/S, D]
      (seq sharded)                   (heads sharded)

so each device runs *full* attention over the whole sequence for its subset
of heads, then the inverse all-to-all restores sequence sharding for the
(position-wise) MLP.  Two collectives per layer instead of S-1 ppermute
hops — cheaper when the per-hop latency dominates, but requires
``num_heads % S == 0`` and ``num_kv_heads % S == 0`` (use ring attention
when the KV-head count is smaller than the seq axis).

The local attention is the Pallas flash kernel (ops/flash.py) with explicit
global positions, so causality holds for any contiguous block sharding and
long gathered sequences never materialize dense score matrices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import jaxcompat
from . import flash


def ulysses_attention(
    q: jax.Array,  # [B, T_local, H, D] — sequence sharded over axis_name
    k: jax.Array,  # [B, T_local, KVH, D]
    v: jax.Array,  # [B, T_local, KVH, D]
    q_positions: jax.Array,  # [B, T_local] global positions
    axis_name: str = "seq",
    causal: bool = True,
    k_valid: jax.Array | None = None,  # [B, T_local] bool
) -> jax.Array:
    """Ulysses attention body — call *inside* ``shard_map`` with the sequence
    axis sharded over ``axis_name``.  Returns [B, T_local, H, D]."""
    try:
        s = jaxcompat.axis_size(axis_name)
    except NameError as e:
        raise RuntimeError(
            f"ulysses attention needs a bound {axis_name!r} mesh axis — call "
            "it inside shard_map (e.g. via ParallelModel with "
            "MeshConfig(seq=N) and attn_impl='ulysses')"
        ) from e
    h, kvh = q.shape[2], k.shape[2]
    if h % s or kvh % s:
        raise ValueError(
            f"ulysses needs num_heads ({h}) and num_kv_heads ({kvh}) divisible "
            f"by the seq axis ({s}); use attn_impl='ring' for small-KV GQA"
        )

    # Head-scatter / sequence-gather: [B, T/S, H, D] -> [B, T, H/S, D].
    a2a = lambda x: jax.lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    qg, kg, vg = a2a(q), a2a(k), a2a(v)
    pos = jax.lax.all_gather(q_positions, axis_name, axis=1, tiled=True)
    kv_full = (
        None
        if k_valid is None
        else jax.lax.all_gather(k_valid, axis_name, axis=1, tiled=True)
    )

    out = flash.flash_attention(
        qg, kg, vg,
        q_positions=pos, k_positions=pos, k_valid=kv_full, causal=causal,
    )  # [B, T, H/S, D]

    # Inverse: sequence-scatter / head-gather back to [B, T/S, H, D].
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)
