"""Signal-driven autoscaler: the fleet grows and shrinks with load.

PRs 6-7 made the fleet fault-tolerant but left its SIZE a boot-time
constant: a diurnal trough pays for idle replicas, a flash crowd sheds
work a larger fleet would have served.  This module closes ROADMAP item
4's elastic half — a control loop over the existing
:class:`~.fleet.ReplicaFleet` drain/respawn machinery:

- **Signals.**  Router committed-token mass (the same per-replica
  accounting placement uses: prompt + budget per in-flight request) and
  router queue depth (in-flight proxies), read off the fleet handles the
  router already maintains — no new wires.  ``load`` is committed tokens
  over the fleet's aggregate KV capacity: the fraction of the fleet's
  token budget already spoken for.
- **Decisions.**  Scale UP when load has exceeded ``up_load`` for
  ``hysteresis`` consecutive ticks; scale DOWN below ``down_load`` the
  same way; never outside ``[min_replicas, max_replicas]``; and a
  ``cooldown_s`` quiet period follows every action (including a FAILED
  one) — hysteresis filters noise, the cooldown prevents oscillation
  while a just-booted replica warms its compile caches.
- **Mechanics.**  Up = ``fleet.add_replica`` (the factory builds off the
  event loop; a boot failure registers nothing).  Down = ``fleet.
  remove_replica``: GRACEFUL drain only — in-flight requests finish
  byte-exact, stragglers past the deadline migrate through the router's
  exact-failover path, and the drained-away replica's capacity returns.
  The least-committed routable replica is chosen (its drain is the
  cheapest), never below the floor.
- **Chaos.**  The ``fleet.scale_up`` / ``fleet.scale_down`` fault sites
  fire before each action (tag = replica name where known):  ``raise``/
  ``drop`` fail or veto the action — the loop degrades cleanly (counts
  the failure, keeps serving at the current size, retries after the
  cooldown), exactly how a cloud API erroring a provision call must be
  absorbed.  ``delay`` is returned un-slept (this loop must never
  block); chaos drills stall the scaled REPLICA, not the controller.

Everything here is event-loop confined (the fleet's model); the control
loop never blocks it — factory builds ride ``asyncio.to_thread`` inside
``fleet._boot`` and every fault-site fire defers stalls.

**Disaggregated fleets** scale per TIER instead: :class:`TieredAutoscaler`
runs one independent control loop per role over the same fleet.  The
prefill tier scales off queue depth (in-flight handoff RPCs per routable
prefill replica — the router counts them on the handle), the decode tier
off committed-token mass over tier KV capacity; each tier has its own
``min/max/hysteresis/cooldown`` (:class:`TierPolicy`) so a prompt-heavy
burst grows prefill without over-provisioning decode and vice versa.
Scale-downs stay graceful-drain-only and role-scoped — a decode drain
never touches the prefill tier.  When the prefill tier is pinned at its
floor and saturated, nothing here forces the issue: the router's handoff
ladder already degrades overflow requests to colocated prefill on the
decode replica, counted per-reason at ``router.handoff_fallbacks.*``.
"""

from __future__ import annotations

import asyncio

from ..core.observability import METRICS, get_logger
from ..runtime.faults import InjectedFault

log = get_logger("autoscale")


# Machine-readable transition system for one autoscaled tier plus the
# epoch-keyed placement directory, declared next to the code it models
# (PROTOCOL_MODELS["fleet.autoscale"], runtime/faults.py).  ``python -m
# tools.graftmodel`` explores every interleaving of tick-driven streak /
# hysteresis / cooldown decisions, an in-flight graceful drain, load
# shifts, directory lookups, and the declared fleet.scale_up /
# fleet.scale_down fault actions, checking GM4 on every reachable
# state: the tier stays within [MIN, MAX], every scale-down goes
# through a drain (downs == drains — no abrupt leg), a drain only runs
# above the floor, and a stale directory entry is dropped at lookup,
# never served.  ``t``/``lc``/``lq`` are tick / load-shift / lookup
# budgets bounding the exploration; cooldown clears on the next tick
# after any action (including a vetoed one).
AUTOSCALE_MODEL = {
    "name": "fleet.autoscale",
    "doc": "tiered autoscaler: hysteresis + cooldown, graceful-drain-only "
           "downs within [MIN, MAX], epoch-stale directory entries "
           "dropped at lookup",
    "params": {"MIN": 1, "MAX": 3, "K": 2, "TMAX": 7, "LCMAX": 3,
               "LQ": 3},
    "state": {"n": 1, "load": 1, "up_s": 0, "down_s": 0, "cool": 0,
              "drain": 0, "t": 0, "lc": 0, "lq": 0, "stale": 0,
              "downs": 0, "drains": 0, "fails": 0, "stale_drops": 0},
    "actions": [
        # One tick per load level: streaks grow under sustained signal,
        # reset on the opposite signal, and the mid band resets both
        # (hysteresis).  Every tick retires the cooldown.
        {"name": "tick_high", "guard": "t < TMAX and load == 2",
         "update": {"t": "t + 1", "cool": "0", "down_s": "0",
                    "up_s": "up_s + 1 if up_s < K else up_s"}},
        {"name": "tick_mid", "guard": "t < TMAX and load == 1",
         "update": {"t": "t + 1", "cool": "0", "up_s": "0",
                    "down_s": "0"}},
        {"name": "tick_low", "guard": "t < TMAX and load == 0",
         "update": {"t": "t + 1", "cool": "0", "up_s": "0",
                    "down_s": "down_s + 1 if down_s < K else down_s"}},
        {"name": "scale_up",
         "guard": "load == 2 and up_s >= K and cool == 0 and drain == 0 "
                  "and n < MAX",
         "update": {"n": "n + 1", "up_s": "0", "cool": "1",
                    "stale": "1"}},
        # The ONLY way down: pick a routable victim, drain it
        # gracefully, then retire it.
        {"name": "drain_start",
         "guard": "load == 0 and down_s >= K and cool == 0 and drain == 0 "
                  "and n > MIN",
         "update": {"drain": "1", "down_s": "0"}},
        {"name": "drain_done", "guard": "drain == 1",
         "update": {"drain": "0", "n": "n - 1", "cool": "1", "stale": "1",
                    "downs": "downs + 1", "drains": "drains + 1"}},
        {"name": "load_shift_up", "guard": "lc < LCMAX and load < 2",
         "update": {"load": "load + 1", "lc": "lc + 1"}},
        {"name": "load_shift_down", "guard": "lc < LCMAX and load > 0",
         "update": {"load": "load - 1", "lc": "lc + 1"}},
        # Epoch-keyed directory: scale actions bump the fleet epoch; a
        # lookup against a stale epoch is DROPPED (counted, recompute),
        # never served; a refresh catches the directory up.
        {"name": "dir_refresh", "guard": "stale == 1",
         "update": {"stale": "0"}},
        {"name": "lookup_fresh", "guard": "stale == 0 and lq < LQ",
         "update": {"lq": "lq + 1"}},
        {"name": "lookup_stale_drop", "guard": "stale == 1 and lq < LQ",
         "update": {"lq": "lq + 1", "stale_drops": "stale_drops + 1"}},
    ],
    "faults": [
        # Failed provision: degrade cleanly — size kept, failure
        # counted, cooldown armed so the retry waits a tick.
        {"name": "up_raise", "site": "fleet.scale_up", "action": "raise",
         "metric": "autoscale.decode.scale_failures",
         "guard": "load == 2 and up_s >= K and cool == 0 and drain == 0 "
                  "and n < MAX",
         "update": {"up_s": "0", "cool": "1", "fails": "fails + 1"}},
        {"name": "up_drop", "site": "fleet.scale_up", "action": "drop",
         "metric": "autoscale.decode.scale_failures",
         "guard": "load == 2 and up_s >= K and cool == 0 and drain == 0 "
                  "and n < MAX",
         "update": {"up_s": "0", "cool": "1", "fails": "fails + 1"}},
        # Vetoed drain: the fleet keeps its size — there is no abrupt
        # scale-down leg to fall back to.
        {"name": "down_raise", "site": "fleet.scale_down", "action": "raise",
         "metric": "autoscale.decode.scale_failures",
         "guard": "load == 0 and down_s >= K and cool == 0 and drain == 0 "
                  "and n > MIN",
         "update": {"down_s": "0", "cool": "1", "fails": "fails + 1"}},
        {"name": "down_drop", "site": "fleet.scale_down", "action": "drop",
         "metric": "autoscale.decode.scale_failures",
         "guard": "load == 0 and down_s >= K and cool == 0 and drain == 0 "
                  "and n > MIN",
         "update": {"down_s": "0", "cool": "1", "fails": "fails + 1"}},
    ],
    "invariants": [
        {"rule": "GM4", "name": "size-within-bounds",
         "expr": "MIN <= n <= MAX"},
        {"rule": "GM4", "name": "downs-only-via-drain",
         "expr": "downs == drains"},
        {"rule": "GM4", "name": "drain-only-above-floor",
         "expr": "drain == 0 or n > MIN"},
        {"rule": "GM4", "name": "streaks-bounded",
         "expr": "up_s <= K and down_s <= K"},
        {"rule": "GM4", "name": "stale-lookups-dropped-not-served",
         "expr": "stale_drops <= lq"},
    ],
    # The budgets bound the run: stuck states are tick-exhausted (an
    # in-flight drain can always finish, so none is pending here).
    "terminal": "t >= TMAX and drain == 0",
}


class Autoscaler:
    """Control loop over a :class:`~.fleet.ReplicaFleet`.

    ``replica_capacity_tokens`` is one replica's KV capacity (the
    denominator of the load signal); None reads it off the first live
    replica's batcher at tick time — a host read of a static number.
    ``factory`` overrides the fleet's default replica factory for
    scale-ups (tests inject light stubs)."""

    def __init__(
        self,
        fleet,
        min_replicas: int = 1,
        max_replicas: int = 4,
        interval_s: float = 1.0,
        up_load: float = 0.8,
        down_load: float = 0.25,
        hysteresis: int = 3,
        cooldown_s: float = 10.0,
        drain_timeout_s: float = 30.0,
        replica_capacity_tokens: int | None = None,
        factory=None,
        faults=None,
    ) -> None:
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas {max_replicas} < min_replicas {min_replicas}"
            )
        if not 0.0 <= down_load < up_load:
            raise ValueError(
                f"need 0 <= down_load < up_load, got "
                f"{down_load} / {up_load}"
            )
        if hysteresis < 1:
            raise ValueError(f"hysteresis must be >= 1, got {hysteresis}")
        self.fleet = fleet
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.interval_s = interval_s
        self.up_load = up_load
        self.down_load = down_load
        self.hysteresis = hysteresis
        self.cooldown_s = cooldown_s
        self.drain_timeout_s = drain_timeout_s
        self.replica_capacity_tokens = replica_capacity_tokens
        self.factory = factory
        self.faults = faults
        self._up_streak = 0      # consecutive ticks above up_load
        self._down_streak = 0    # consecutive ticks below down_load
        self._cooldown_until = 0.0  # loop-clock quiet period
        self._task: asyncio.Task | None = None
        self._loop: asyncio.AbstractEventLoop | None = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._task = asyncio.create_task(self._run())
        log.info(
            "autoscaler on: %d..%d replicas, up at load>%.2f, down at "
            "load<%.2f (x%d ticks, %.1fs cooldown)",
            self.min_replicas, self.max_replicas, self.up_load,
            self.down_load, self.hysteresis, self.cooldown_s,
        )

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                # The controller must outlive any one bad tick: a scale
                # action failing mid-flight is a degraded fleet, not a
                # dead autoscaler.
                log.exception("autoscaler tick failed")

    # -- signals -----------------------------------------------------------

    def _capacity(self) -> int:
        if self.replica_capacity_tokens is not None:
            return self.replica_capacity_tokens
        for h in self.fleet.replicas:
            server = getattr(h, "server", None)
            if server is not None and getattr(server, "batcher", None) \
                    is not None:
                return max(1, server.batcher.capacity_tokens())
        return 1

    def signals(self) -> dict:
        """The tick's inputs, also published as gauges: committed token
        mass and queue depth summed over ROUTABLE replicas (the work the
        router can actually spread), live replica count, and the load
        fraction against aggregate capacity."""
        now = self._loop.time() if self._loop is not None else 0.0
        live = [h for h in self.fleet.replicas if h.state != "dead"]
        routable = [h for h in live if h.routable(now)]
        committed = sum(h.committed_tokens for h in routable)
        depth = sum(len(h.inflight) for h in routable)
        cap = self._capacity() * max(1, len(routable))
        load = committed / cap
        METRICS.set_gauges({
            "autoscale.replicas": len(live),
            "autoscale.load": load,
            "autoscale.queue_depth": depth,
        })
        return {"replicas": len(live), "routable": len(routable),
                "committed_tokens": committed, "queue_depth": depth,
                "load": load}

    # -- the control loop --------------------------------------------------

    async def tick(self) -> str | None:
        """One decision: returns "up"/"down" when an action was TAKEN,
        None otherwise (tests drive this directly for determinism —
        tick() binds the loop itself, no start() required)."""
        if self._loop is None:
            self._loop = asyncio.get_running_loop()
        sig = self.signals()
        n = sig["replicas"]
        self._up_streak = self._up_streak + 1 \
            if sig["load"] >= self.up_load else 0
        self._down_streak = self._down_streak + 1 \
            if sig["load"] <= self.down_load else 0
        now = self._loop.time()
        if now < self._cooldown_until:
            return None
        if (self._up_streak >= self.hysteresis and n < self.max_replicas
                and sig["routable"] > 0):
            self._up_streak = 0
            self._cooldown_until = now + self.cooldown_s
            return "up" if await self._scale_up(sig) else None
        if self._down_streak >= self.hysteresis and n > self.min_replicas:
            self._down_streak = 0
            self._cooldown_until = now + self.cooldown_s
            return "down" if await self._scale_down(sig) else None
        return None

    @staticmethod
    def _vetoed(fire_one) -> bool:
        """Whether a scale-action fault rule vetoed/failed the action
        (``raise``, ``drop``, or ``close`` — the caller degrades
        cleanly); every other outcome proceeds."""
        try:
            rule = fire_one()
        except InjectedFault:
            return True
        return rule is not None and rule.action in ("drop", "close")

    async def _scale_up(self, sig: dict) -> bool:
        # defer_stall on every scale-site fire: this loop runs next to
        # probing and routing — a stall rule must not freeze failure
        # detection; chaos drills stall replicas, not the controller.
        if self.faults is not None and self._vetoed(
            lambda: self.faults.fire("fleet.scale_up", defer_stall=True)
        ):
            METRICS.inc("autoscale.scale_failures")
            log.warning(
                "scale-up failed (injected); serving at %d replica(s), "
                "retry after cooldown", sig["replicas"],
            )
            return False
        t0 = self._loop.time()
        try:
            h = await self.fleet.add_replica(factory=self.factory)
        except Exception:
            # A real provision failure (factory OOM, port exhaustion):
            # same degrade as the drill — the fleet is unchanged
            # (add_replica registers nothing on failure), serving
            # continues at the current size, the cooldown spaces retries.
            METRICS.inc("autoscale.scale_failures")
            log.exception("scale-up failed; serving at current size")
            return False
        METRICS.inc("autoscale.scale_ups")
        METRICS.observe("autoscale.scale_seconds", self._loop.time() - t0)
        log.info(
            "scaled up: replica %s joined (%s) at load %.2f — %d live",
            h.name, h.state, sig["load"], len(self.fleet.replicas),
        )
        return True

    async def _scale_down(self, sig: dict) -> bool:
        now = self._loop.time()
        cands = [h for h in self.fleet.replicas if h.routable(now)]
        if len(cands) <= self.min_replicas:
            return False  # only unroutable excess — draining those is
            #               the probe/respawn plane's job, not scaling's
        victim = min(cands, key=lambda h: (h.committed_tokens,
                                           len(h.inflight), h.name))
        if self.faults is not None and self._vetoed(
            lambda: self.faults.fire("fleet.scale_down", tag=victim.name,
                                     defer_stall=True)
        ):
            METRICS.inc("autoscale.scale_failures")
            log.warning("scale-down of %s vetoed (injected)", victim.name)
            return False
        t0 = self._loop.time()
        await self.fleet.remove_replica(
            victim.name, drain_timeout_s=self.drain_timeout_s
        )
        METRICS.inc("autoscale.scale_downs")
        METRICS.observe("autoscale.scale_seconds", self._loop.time() - t0)
        log.info(
            "scaled down: replica %s drained away at load %.2f — %d live",
            victim.name, sig["load"], len(self.fleet.replicas),
        )
        return True


class TierPolicy:
    """One tier's scaling knobs for :class:`TieredAutoscaler` — pure
    configuration (no per-run state), so a policy may be shared across
    autoscaler instances.  Validation mirrors :class:`Autoscaler` so a
    bad per-tier flag fails the same way a bad flat flag does."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 up_load: float = 0.8, down_load: float = 0.25,
                 hysteresis: int = 3, cooldown_s: float = 10.0) -> None:
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas {max_replicas} < min_replicas {min_replicas}"
            )
        if not 0.0 <= down_load < up_load:
            raise ValueError(
                f"need 0 <= down_load < up_load, got "
                f"{down_load} / {up_load}"
            )
        if hysteresis < 1:
            raise ValueError(f"hysteresis must be >= 1, got {hysteresis}")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.up_load = up_load
        self.down_load = down_load
        self.hysteresis = hysteresis
        self.cooldown_s = cooldown_s


class _TierState:
    """Per-tier control-loop state (streaks + cooldown), kept off the
    shareable :class:`TierPolicy`."""

    __slots__ = ("up_streak", "down_streak", "cooldown_until")

    def __init__(self) -> None:
        self.up_streak = 0
        self.down_streak = 0
        self.cooldown_until = 0.0


class TieredAutoscaler:
    """Per-role control loops over one DISAGGREGATED fleet.

    Two tiers, two signals (module docstring): prefill scales off
    in-flight handoffs per routable prefill replica, decode off
    committed-token mass over the tier's aggregate KV capacity.  Each
    tier keeps its own hysteresis streaks and cooldown clock — a decode
    scale-up never resets the prefill tier's streak or quiets its
    actions.  ``prefill_factory``/``decode_factory`` build role-pinned
    replicas (the CLI partials its replica factory per role); scaled-up
    names mint as ``p<n>``/``d<n>`` alongside the boot-time tiers."""

    ROLES = ("prefill", "decode")

    def __init__(
        self,
        fleet,
        *,
        prefill: TierPolicy | None = None,
        decode: TierPolicy | None = None,
        prefill_factory=None,
        decode_factory=None,
        interval_s: float = 1.0,
        drain_timeout_s: float = 30.0,
        replica_capacity_tokens: int | None = None,
        faults=None,
    ) -> None:
        self.fleet = fleet
        self.policies = {
            # Prefill work is transient (prompt+1 per handoff): a small
            # tier saturates later than decode, so its default ceiling
            # stays low.
            "prefill": prefill or TierPolicy(max_replicas=2),
            "decode": decode or TierPolicy(),
        }
        self.factories = {"prefill": prefill_factory,
                          "decode": decode_factory}
        self.interval_s = interval_s
        self.drain_timeout_s = drain_timeout_s
        self.replica_capacity_tokens = replica_capacity_tokens
        self.faults = faults
        self._state = {role: _TierState() for role in self.ROLES}
        self._task: asyncio.Task | None = None
        self._loop: asyncio.AbstractEventLoop | None = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._task = asyncio.create_task(self._run())
        for role in self.ROLES:
            pol = self.policies[role]
            log.info(
                "tiered autoscaler on: %s %d..%d replicas, up at "
                "load>%.2f, down at load<%.2f (x%d ticks, %.1fs cooldown)",
                role, pol.min_replicas, pol.max_replicas, pol.up_load,
                pol.down_load, pol.hysteresis, pol.cooldown_s,
            )

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                # One tier's bad tick must not kill the other tier's
                # controller: degraded fleet, not dead autoscaler.
                log.exception("tiered autoscaler tick failed")

    # -- signals -----------------------------------------------------------

    def _capacity(self, role: str) -> int:
        if self.replica_capacity_tokens is not None:
            return self.replica_capacity_tokens
        for h in self.fleet.replicas:
            if getattr(h, "role", "colocated") != role:
                continue
            server = getattr(h, "server", None)
            if server is not None and getattr(server, "batcher", None) \
                    is not None:
                return max(1, server.batcher.capacity_tokens())
        return 1

    def signals(self, role: str) -> dict:
        """One tier's tick inputs, published as role-keyed gauges.
        Decode load = committed-token mass over the tier's routable KV
        capacity (the flat autoscaler's signal, scoped to the role);
        prefill load = in-flight handoff RPCs per routable prefill
        replica — handoff charges are transient, so token mass would
        flap where the outstanding-RPC count tracks the actual queue."""
        now = self._loop.time() if self._loop is not None else 0.0
        live = [h for h in self.fleet.replicas
                if h.state != "dead"
                and getattr(h, "role", "colocated") == role]
        routable = [h for h in live if h.routable(now)]
        committed = sum(h.committed_tokens for h in routable)
        if role == "prefill":
            depth = sum(getattr(h, "handoffs", 0) for h in routable)
            load = depth / max(1, len(routable))
        else:
            depth = sum(len(h.inflight) for h in routable)
            cap = self._capacity(role) * max(1, len(routable))
            load = committed / cap
        METRICS.set_gauges({
            f"autoscale.{role}.replicas": len(live),
            f"autoscale.{role}.load": load,
        })
        return {"replicas": len(live), "routable": len(routable),
                "committed_tokens": committed, "queue_depth": depth,
                "load": load}

    # -- the control loops -------------------------------------------------

    async def tick(self) -> dict:
        """One decision per tier: ``{"prefill": ..., "decode": ...}``
        with "up"/"down" where an action was TAKEN, None otherwise
        (tests drive this directly for determinism — binds the loop
        itself, no start() required)."""
        if self._loop is None:
            self._loop = asyncio.get_running_loop()
        return {role: await self.tick_tier(role) for role in self.ROLES}

    async def tick_tier(self, role: str) -> str | None:
        if self._loop is None:
            self._loop = asyncio.get_running_loop()
        pol, st = self.policies[role], self._state[role]
        sig = self.signals(role)
        n = sig["replicas"]
        st.up_streak = st.up_streak + 1 \
            if sig["load"] >= pol.up_load else 0
        st.down_streak = st.down_streak + 1 \
            if sig["load"] <= pol.down_load else 0
        now = self._loop.time()
        if now < st.cooldown_until:
            return None
        if (st.up_streak >= pol.hysteresis and n < pol.max_replicas
                and sig["routable"] > 0):
            st.up_streak = 0
            st.cooldown_until = now + pol.cooldown_s
            return "up" if await self._scale_up(role, sig) else None
        if st.down_streak >= pol.hysteresis and n > pol.min_replicas:
            st.down_streak = 0
            st.cooldown_until = now + pol.cooldown_s
            return "down" if await self._scale_down(role, sig) else None
        return None

    async def _scale_up(self, role: str, sig: dict) -> bool:
        # Same scale sites as the flat loop, tag = role, so a drill can
        # veto one tier's growth while the other keeps scaling; every
        # fire defers stalls (this loop runs next to probing/routing).
        if self.faults is not None and Autoscaler._vetoed(
            lambda: self.faults.fire("fleet.scale_up", tag=role,
                                     defer_stall=True)
        ):
            METRICS.inc("autoscale.scale_failures")
            METRICS.inc(f"autoscale.{role}.scale_failures")
            log.warning(
                "%s scale-up failed (injected); serving at %d "
                "replica(s), retry after cooldown", role, sig["replicas"],
            )
            return False
        t0 = self._loop.time()
        try:
            h = await self.fleet.add_replica(
                factory=self.factories[role], role=role
            )
        except Exception:
            METRICS.inc("autoscale.scale_failures")
            METRICS.inc(f"autoscale.{role}.scale_failures")
            log.exception("%s scale-up failed; serving at current size",
                          role)
            return False
        METRICS.inc("autoscale.scale_ups")
        METRICS.inc(f"autoscale.{role}.scale_ups")
        METRICS.observe("autoscale.scale_seconds", self._loop.time() - t0)
        log.info(
            "scaled up: %s replica %s joined (%s) at load %.2f",
            role, h.name, h.state, sig["load"],
        )
        return True

    async def _scale_down(self, role: str, sig: dict) -> bool:
        now = self._loop.time()
        cands = [h for h in self.fleet.replicas
                 if h.routable(now)
                 and getattr(h, "role", "colocated") == role]
        if len(cands) <= self.policies[role].min_replicas:
            return False
        victim = min(cands, key=lambda h: (h.committed_tokens,
                                           getattr(h, "handoffs", 0),
                                           len(h.inflight), h.name))
        if self.faults is not None and Autoscaler._vetoed(
            lambda: self.faults.fire("fleet.scale_down", tag=victim.name,
                                     defer_stall=True)
        ):
            METRICS.inc("autoscale.scale_failures")
            METRICS.inc(f"autoscale.{role}.scale_failures")
            log.warning("%s scale-down of %s vetoed (injected)",
                        role, victim.name)
            return False
        t0 = self._loop.time()
        await self.fleet.remove_replica(
            victim.name, drain_timeout_s=self.drain_timeout_s
        )
        METRICS.inc("autoscale.scale_downs")
        METRICS.inc(f"autoscale.{role}.scale_downs")
        METRICS.observe("autoscale.scale_seconds", self._loop.time() - t0)
        log.info(
            "scaled down: %s replica %s drained away at load %.2f",
            role, victim.name, sig["load"],
        )
        return True
