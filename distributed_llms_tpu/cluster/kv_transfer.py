"""KV-handoff plane: ship finished prefill KV pages to a decode engine.

Disaggregated serving (DistServe/Mooncake-style) splits prefill and decode
into separate engines so a long prompt never stalls another request's
decode tokens.  The seam is the handoff: a prefill-role engine finishes a
prompt's KV pages and must get them into the decode-role engine's
``PagePool`` byte-exactly, over a wire that drops, corrupts, duplicates,
and stalls.  This module is that seam, built on ``cluster/protocol.py``
framing (length-prefixed JSON over asyncio TCP):

- **KV_PAGES** carries one transfer: the prompt's token ids, the prefix
  cache's CHAINED page digests (the same content addresses the automatic
  prefix cache keys pages by — equal digests mean equal full prefixes),
  the page payload (k/v pool pages, base64), dtype/shape metadata, and a
  blake2b checksum over everything that matters.
- **KV_ACK** answers every accepted-or-rejected transfer: ``ok`` plus a
  structured ``reason`` ("imported", "duplicate", "digest mismatch",
  "no capacity", ...).  No ack within the deadline = the frame (or its
  ack) was lost; the sender retries.

Safety contract, end to end:

- **Verified.**  The receiver recomputes the checksum over the decoded
  payload AND recomputes the chained page digests from the carried token
  ids — a corrupted payload, corrupted digest list, or sender-side
  hashing bug all NACK instead of poisoning the decode cache (a wrong
  page published under a prompt's digest would silently serve wrong KV
  to every later match).
- **Deadline + jittered exponential retry.**  Each attempt opens a fresh
  connection, sends one frame, and awaits the ack under ``attempt_s``;
  timeouts, connection failures, and retryable NACKs back off
  (``backoff_base_s * 2^n`` + jitter) and retry up to ``max_retries``
  times.  Permanent failures (frame too large, receiver says the payload
  can never verify against THIS sender's bytes) stop early.
- **Idempotent.**  Duplicate delivery (a retry racing a delayed ack, or a
  ``dup`` fault) is absorbed by the receiver's digest check: pages whose
  digests are already resident ack ``ok`` without re-importing.

Fault sites (runtime/faults.py): ``xfer.send`` (drop / corrupt / dup /
delay / stall on the sender), ``xfer.recv`` (drop / corrupt / delay on the
receiver), ``xfer.verify`` (``corrupt`` forces a verification failure).
All three are traversed by asyncio event loops, so ``fire`` is called with
``defer_stall=True`` and stalls are applied as awaited delays.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import random
import time
from dataclasses import dataclass

import numpy as np

from ..core.observability import METRICS, get_logger
from . import protocol

log = get_logger("kv_transfer")

# Reasons a receiver may NACK with.  "permanent" reasons tell the sender a
# byte-identical retry cannot succeed either — it must stop retrying and
# let the caller degrade to colocated prefill.
_PERMANENT_NACKS = frozenset({"bad frame", "not a decode-role engine",
                              "pool shape mismatch"})


# Machine-readable transition system for the KV handoff plane — one push
# handoff (``send_kv_pages`` -> ``handle_kv_connection``) and one
# cross-replica pull (directory lookup -> ``export`` -> the same receive
# path) running concurrently, declared next to the code it models
# (PROTOCOL_MODELS["cluster.kv_handoff"], runtime/faults.py).  ``python
# -m tools.graftmodel`` explores every interleaving under the declared
# xfer.send / xfer.recv / xfer.verify / prefill.crash / xfer.pull /
# directory.lookup fault actions and checks GM3 on every reachable
# state: adoption is at-most-once (first-writer-wins import), an acked
# transfer was actually imported, and every fallback is counted exactly
# once.  Vars per transfer: ``*_s`` sender phase (0 about to attempt,
# 1 awaiting ack, 2 adopted+acked, 3 degraded to local/colocated
# compute), ``*_att`` attempts used (<= ATT, the retry budget),
# ``*_fly`` frames in flight (dup can make it 2), ``*_bad`` an
# in-flight frame is corrupt, ``*_adopted`` receiver-side imports.
# The pull adds ``p_dir``: 0 unresolved, 1 resolved to the right
# sibling, 2 mis-steered (corrupt — the export finds nothing and the
# frame never flies), 3 miss (drop — degrade immediately).
HANDOFF_MODEL = {
    "name": "cluster.kv_handoff",
    "doc": "KV handoff + cross-replica pull: checksummed frames, bounded "
           "retries, at-most-once adoption, per-reason counted fallback",
    "params": {"ATT": 2},
    "state": {"h_s": 0, "h_att": 0, "h_fly": 0, "h_bad": 0, "h_adopted": 0,
              "p_dir": 0, "p_s": 0, "p_att": 0, "p_fly": 0, "p_bad": 0,
              "p_adopted": 0, "fb": 0},
    "actions": [
        # -- push handoff ------------------------------------------------
        {"name": "h_send", "guard": "h_s == 0 and h_att < ATT",
         "update": {"h_s": "1", "h_att": "h_att + 1",
                    "h_fly": "h_fly + 1"}},
        {"name": "h_adopt",
         "guard": "h_s == 1 and h_fly > h_bad "
                  "and h_adopted == 0",
         "update": {"h_s": "2", "h_fly": "h_fly - 1",
                    "h_adopted": "h_adopted + 1"}},
        # A late clean frame after the sender already degraded: the
        # receiver imports it anyway (first-writer-wins cache insert,
        # benign) — adoption must STILL be at-most-once.
        {"name": "h_late_adopt",
         "guard": "h_s == 3 and h_fly > h_bad "
                  "and h_adopted == 0",
         "update": {"h_fly": "h_fly - 1", "h_adopted": "h_adopted + 1"}},
        {"name": "h_dup_absorb",
         "guard": "h_fly > h_bad and h_adopted == 1",
         "update": {"h_fly": "h_fly - 1",
                    "h_s": "2 if h_s == 1 else h_s"}},
        {"name": "h_nack_retry",
         "guard": "h_s == 1 and h_fly > 0 and h_bad == 1 and h_att < ATT",
         "update": {"h_s": "0", "h_fly": "h_fly - 1", "h_bad": "0"}},
        {"name": "h_nack_exhaust",
         "guard": "h_s == 1 and h_fly > 0 and h_bad == 1 "
                  "and h_att >= ATT",
         "update": {"h_s": "3", "h_fly": "h_fly - 1", "h_bad": "0",
                    "fb": "fb + 1"}},
        {"name": "h_nack_late",
         "guard": "h_s != 1 and h_fly > 0 and h_bad == 1",
         "update": {"h_fly": "h_fly - 1", "h_bad": "0"}},
        {"name": "h_timeout_retry",
         "guard": "h_s == 1 and h_fly == 0 and h_att < ATT",
         "update": {"h_s": "0"}},
        {"name": "h_timeout_exhaust",
         "guard": "h_s == 1 and h_fly == 0 and h_att >= ATT",
         "update": {"h_s": "3", "fb": "fb + 1"}},
        # -- cross-replica pull ------------------------------------------
        {"name": "p_lookup", "guard": "p_dir == 0", "update": {"p_dir": "1"}},
        {"name": "p_miss_fallback", "guard": "p_dir == 3 and p_s == 0",
         "update": {"p_s": "3", "fb": "fb + 1"}},
        # A mis-steered pull ships no frame (the sibling exports
        # nothing) — the attempt burns and the timeout path retries.
        {"name": "p_send",
         "guard": "p_s == 0 and p_att < ATT and p_dir in (1, 2)",
         "update": {"p_s": "1", "p_att": "p_att + 1",
                    "p_fly": "p_fly + (1 if p_dir == 1 else 0)"}},
        {"name": "p_adopt",
         "guard": "p_s == 1 and p_fly > p_bad "
                  "and p_adopted == 0",
         "update": {"p_s": "2", "p_fly": "p_fly - 1",
                    "p_adopted": "p_adopted + 1"}},
        {"name": "p_late_adopt",
         "guard": "p_s == 3 and p_fly > p_bad "
                  "and p_adopted == 0",
         "update": {"p_fly": "p_fly - 1", "p_adopted": "p_adopted + 1"}},
        {"name": "p_dup_absorb",
         "guard": "p_fly > p_bad and p_adopted == 1",
         "update": {"p_fly": "p_fly - 1",
                    "p_s": "2 if p_s == 1 else p_s"}},
        {"name": "p_nack_retry",
         "guard": "p_s == 1 and p_fly > 0 and p_bad == 1 and p_att < ATT",
         "update": {"p_s": "0", "p_fly": "p_fly - 1", "p_bad": "0"}},
        {"name": "p_nack_exhaust",
         "guard": "p_s == 1 and p_fly > 0 and p_bad == 1 "
                  "and p_att >= ATT",
         "update": {"p_s": "3", "p_fly": "p_fly - 1", "p_bad": "0",
                    "fb": "fb + 1"}},
        {"name": "p_nack_late",
         "guard": "p_s != 1 and p_fly > 0 and p_bad == 1",
         "update": {"p_fly": "p_fly - 1", "p_bad": "0"}},
        {"name": "p_timeout_retry",
         "guard": "p_s == 1 and p_fly == 0 and p_att < ATT",
         "update": {"p_s": "0"}},
        {"name": "p_timeout_exhaust",
         "guard": "p_s == 1 and p_fly == 0 and p_att >= ATT",
         "update": {"p_s": "3", "fb": "fb + 1"}},
    ],
    "faults": [
        # Dropping the last in-flight frame clears the corrupt bit with
        # it; with a duplicate still flying the clean copy is assumed
        # dropped (the surviving bad frame still NACKs — conservative).
        {"name": "h_send_drop", "site": "xfer.send", "action": "drop",
         "metric": "router.handoff_fallbacks.timeout",
         "guard": "h_s == 1 and h_fly > 0",
         "update": {"h_fly": "h_fly - 1",
                    "h_bad": "0 if h_fly == 1 else h_bad"}},
        {"name": "h_send_corrupt", "site": "xfer.send", "action": "corrupt",
         "metric": "router.handoff_fallbacks.verify",
         "guard": "h_s == 1 and h_fly > 0 and h_bad == 0",
         "update": {"h_bad": "1"}},
        {"name": "h_send_dup", "site": "xfer.send", "action": "dup",
         "metric": "faults.fired.dup",
         "guard": "h_s == 1 and h_fly == 1",
         "update": {"h_fly": "2"}},
        {"name": "h_recv_drop", "site": "xfer.recv", "action": "drop",
         "metric": "router.handoff_fallbacks.timeout",
         "guard": "h_fly > 0",
         "update": {"h_fly": "h_fly - 1",
                    "h_bad": "0 if h_fly == 1 else h_bad"}},
        {"name": "h_recv_corrupt", "site": "xfer.recv", "action": "corrupt",
         "metric": "router.handoff_fallbacks.verify",
         "guard": "h_fly > 0 and h_bad == 0",
         "update": {"h_bad": "1"}},
        {"name": "h_verify_corrupt", "site": "xfer.verify",
         "action": "corrupt",
         "metric": "router.handoff_fallbacks.verify",
         "guard": "h_fly > 0 and h_bad == 0",
         "update": {"h_bad": "1"}},
        # The prefill replica dies mid-handoff; frames already on the
        # wire still arrive at the receiver (late adoption, benign).
        {"name": "h_prefill_crash", "site": "prefill.crash",
         "action": "close",
         "metric": "router.handoff_fallbacks.prefill_crash",
         "guard": "h_s in (0, 1)",
         "update": {"h_s": "3", "fb": "fb + 1"}},
        {"name": "p_dir_drop", "site": "directory.lookup", "action": "drop",
         "metric": "directory.pull_fallbacks.stale",
         "guard": "p_dir == 0", "update": {"p_dir": "3"}},
        {"name": "p_dir_corrupt", "site": "directory.lookup",
         "action": "corrupt",
         "metric": "directory.pull_fallbacks.empty",
         "guard": "p_dir == 0", "update": {"p_dir": "2"}},
        {"name": "p_pull_drop", "site": "xfer.pull", "action": "drop",
         "metric": "directory.pull_fallbacks.refused",
         "guard": "p_s == 1 and p_fly > 0",
         "update": {"p_fly": "p_fly - 1",
                    "p_bad": "0 if p_fly == 1 else p_bad"}},
        {"name": "p_pull_corrupt", "site": "xfer.pull", "action": "corrupt",
         "metric": "directory.pull_fallbacks.verify",
         "guard": "p_s == 1 and p_fly > 0 and p_bad == 0",
         "update": {"p_bad": "1"}},
        {"name": "p_pull_dup", "site": "xfer.pull", "action": "dup",
         "metric": "faults.fired.dup",
         "guard": "p_s == 1 and p_fly == 1",
         "update": {"p_fly": "2"}},
    ],
    "invariants": [
        {"rule": "GM3", "name": "handoff-adopted-at-most-once",
         "expr": "h_adopted <= 1"},
        {"rule": "GM3", "name": "pull-adopted-at-most-once",
         "expr": "p_adopted <= 1"},
        {"rule": "GM3", "name": "handoff-ack-implies-import",
         "expr": "h_s != 2 or h_adopted == 1"},
        {"rule": "GM3", "name": "pull-ack-implies-import",
         "expr": "p_s != 2 or p_adopted == 1"},
        {"rule": "GM3", "name": "every-fallback-counted-once",
         "expr": "fb == (h_s == 3) + (p_s == 3)"},
        {"rule": "GM4", "name": "handoff-retries-bounded",
         "expr": "h_att <= ATT"},
        {"rule": "GM4", "name": "pull-retries-bounded",
         "expr": "p_att <= ATT"},
    ],
    # Stuck only once both transfers settled: adopted+acked or degraded
    # to the byte-exact local/colocated compute path.
    "terminal": "h_s in (2, 3) and p_s in (2, 3)",
}


@dataclass
class KVTransferPayload:
    """One transfer's content, independent of the wire encoding."""

    transfer_id: str
    token_ids: list[int]        # the tokens the shipped pages cover
    page_size: int
    digests: list[bytes]        # chained page digests, one per shipped page
    k_pages: np.ndarray         # [L, P, BLK, KVH, HD]
    v_pages: np.ndarray


def checksum(token_ids: list[int], digests: list[bytes],
             k_bytes: bytes, v_bytes: bytes) -> str:
    """Transport-integrity digest over everything the import trusts."""
    h = hashlib.blake2b(b"dlt-kv-transfer-v1", digest_size=16)
    h.update(np.asarray(token_ids, np.int64).tobytes())
    for d in digests:
        h.update(d)
    h.update(k_bytes)
    h.update(v_bytes)
    return h.hexdigest()


def encode_kv_pages(p: KVTransferPayload) -> dict:
    """Build the KV_PAGES message for one transfer.  Raises
    :class:`protocol.ProtocolError` (via ``protocol.encode`` at send time)
    when the payload exceeds MAX_FRAME — an oversized handoff must fail
    loudly at the sender, never as a silent connection drop."""
    k = np.ascontiguousarray(p.k_pages)
    v = np.ascontiguousarray(p.v_pages)
    kb, vb = k.tobytes(), v.tobytes()
    return protocol.message("KV_PAGES", {
        "transfer_id": p.transfer_id,
        "token_ids": list(map(int, p.token_ids)),
        "page_size": int(p.page_size),
        "digests": [d.hex() for d in p.digests],
        "shape": list(k.shape),
        "dtype": str(k.dtype),
        "k": base64.b64encode(kb).decode("ascii"),
        "v": base64.b64encode(vb).decode("ascii"),
        "checksum": checksum(p.token_ids, p.digests, kb, vb),
    })


def _corrupt_b64(s: str) -> str:
    """Flip one payload character to a different valid base64 symbol, so
    the frame still parses but the checksum no longer matches — the
    in-flight bit-flip a verify pass exists to catch."""
    if not s:
        return s
    i = len(s) // 2
    repl = "A" if s[i] != "A" else "B"
    return s[:i] + repl + s[i + 1:]


def corrupt_payload(msg: dict) -> dict:
    """A copy of a KV_PAGES message with its k-payload corrupted (fault
    actions ``corrupt`` at xfer.send / xfer.recv)."""
    out = dict(msg)
    out["payload"] = dict(msg["payload"])
    out["payload"]["k"] = _corrupt_b64(out["payload"]["k"])
    return out


def verify_and_decode(msg: dict, page_digests_fn) -> tuple[KVTransferPayload | None, str]:
    """Receiver-side verification: structural checks, checksum over the
    decoded payload, and a digest-chain recompute from the carried token
    ids via ``page_digests_fn(ids, page_size, n_pages) -> list[bytes]``
    (the prefix cache's own hashing — the ONE definition of page
    content addressing).  Returns ``(payload, "ok")`` or ``(None,
    reason)``; every failure reason is a stable string the ack carries."""
    p = msg.get("payload")
    if not isinstance(p, dict):
        return None, "bad frame"
    try:
        tid = str(p["transfer_id"])
        ids = [int(t) for t in p["token_ids"]]
        page_size = int(p["page_size"])
        digests = [bytes.fromhex(d) for d in p["digests"]]
        shape = tuple(int(s) for s in p["shape"])
        dtype = np.dtype(p["dtype"])
        kb = base64.b64decode(p["k"], validate=True)
        vb = base64.b64decode(p["v"], validate=True)
        want_sum = str(p["checksum"])
    except (KeyError, TypeError, ValueError) as e:
        return None, f"bad frame: {type(e).__name__}"
    if page_size < 1 or len(shape) != 5 or shape[2] != page_size \
            or shape[1] != len(digests):
        return None, "bad frame: inconsistent geometry"
    if checksum(ids, digests, kb, vb) != want_sum:
        METRICS.inc("xfer.verify_failures")
        return None, "checksum mismatch"
    expect = page_digests_fn(ids, page_size, len(digests))
    if expect != digests:
        # The payload arrived intact but its digests do not commit to the
        # carried tokens — a sender-side hashing bug.  Publishing these
        # pages would serve wrong KV to every later prefix match.
        METRICS.inc("xfer.verify_failures")
        return None, "digest mismatch"
    n = int(np.prod(shape))
    if len(kb) != n * dtype.itemsize or len(vb) != n * dtype.itemsize:
        METRICS.inc("xfer.verify_failures")
        return None, "checksum mismatch"  # size lies are payload corruption
    k = np.frombuffer(kb, dtype=dtype).reshape(shape)
    v = np.frombuffer(vb, dtype=dtype).reshape(shape)
    return KVTransferPayload(
        transfer_id=tid, token_ids=ids, page_size=page_size,
        digests=digests, k_pages=k, v_pages=v,
    ), "ok"


@dataclass
class SendResult:
    ok: bool
    reason: str
    attempts: int
    bytes_sent: int = 0
    elapsed_s: float = 0.0


async def _apply_deferred(rule):
    """Await a ``delay``/``stall`` rule fired with ``defer_stall=True`` on
    an event loop (a blocking sleep would freeze every transfer and the
    router with it).  Returns the rule for context actions."""
    if rule is not None and rule.action in ("delay", "stall"):
        await asyncio.sleep(rule.arg or 0.0)
    return rule


async def send_kv_pages(
    host: str, port: int, msg: dict, *,
    faults=None,
    attempt_s: float = 5.0,
    max_retries: int = 3,
    backoff_base_s: float = 0.05,
    backoff_cap_s: float = 1.0,
    rng: random.Random | None = None,
) -> SendResult:
    """Ship one KV_PAGES message and await its KV_ACK, retrying with
    jittered exponential backoff on timeout / connection failure /
    retryable NACK.  ``msg`` is the encoded frame (``encode_kv_pages``);
    the ``xfer.send`` fault site is consulted once per attempt."""
    rng = rng or random.Random()
    t0 = time.perf_counter()
    attempts = 0
    reason = "unsent"
    try:
        # Encode (and, for large frames, compress) exactly ONCE, OFF the
        # event loop: retries rewrite the same bytes, and zlib over a
        # multi-MB page payload costs hundreds of ms — run synchronously
        # it would stall the same loop that answers /healthz probes,
        # turning a busy prefill replica into a flapping-unhealthy one.
        frame = await asyncio.to_thread(protocol.encode, msg)
    except protocol.ProtocolError as e:
        # Permanent: an over-MAX_FRAME handoff can never be delivered.
        return SendResult(False, f"frame too large: {e}", 0)
    while attempts <= max_retries:
        if attempts:
            METRICS.inc("xfer.retries")
            back = min(backoff_cap_s, backoff_base_s * (2 ** (attempts - 1)))
            await asyncio.sleep(back * (0.5 + rng.random()))
        attempts += 1
        METRICS.inc("xfer.sends")
        rule = await _apply_deferred(
            faults.fire("xfer.send", tag=msg["payload"]["transfer_id"],
                        defer_stall=True)
            if faults is not None else None
        )
        send_frame, send_twice, swallow = frame, False, False
        if rule is not None:
            if rule.action == "drop":
                swallow = True          # the wire never sees the frame
            elif rule.action == "corrupt":
                send_frame = await asyncio.to_thread(
                    protocol.encode, corrupt_payload(msg)
                )
            elif rule.action == "dup":
                send_twice = True
        try:
            conn = asyncio.open_connection(host, port)
            reader, writer = await asyncio.wait_for(conn, attempt_s)
            try:
                if not swallow:
                    writer.write(send_frame)
                    if send_twice:
                        writer.write(send_frame)
                    await writer.drain()
                    METRICS.inc("xfer.bytes",
                                len(send_frame) * (2 if send_twice else 1))
                ack = await protocol.receive_message(
                    reader, timeout=attempt_s, writer=writer
                )
            finally:
                writer.close()
        except (ConnectionError, OSError, EOFError, asyncio.TimeoutError,
                asyncio.IncompleteReadError, protocol.ProtocolError) as e:
            reason = f"{type(e).__name__}: {e}"
            log.warning("kv transfer %s attempt %d failed (%s)",
                        msg["payload"]["transfer_id"], attempts, reason)
            continue
        if ack.get("type") != "KV_ACK":
            reason = f"unexpected ack type {ack.get('type')!r}"
            continue
        body = ack.get("payload") or {}
        if body.get("ok"):
            el = time.perf_counter() - t0
            METRICS.observe("xfer.send_seconds", el)
            return SendResult(True, str(body.get("reason", "imported")),
                              attempts, len(frame), el)
        reason = str(body.get("reason", "nack"))
        if reason in _PERMANENT_NACKS:
            break  # a byte-identical retry cannot succeed
    return SendResult(False, reason, attempts, 0,
                      time.perf_counter() - t0)


@dataclass
class ReceiverStats:
    imported: int = 0
    duplicates: int = 0
    rejected: int = 0
    last_reason: str = ""


async def handle_kv_connection(
    reader: asyncio.StreamReader, writer: asyncio.StreamWriter, *,
    page_digests_fn, import_fn, faults=None, import_timeout_s: float = 60.0,
    stats: ReceiverStats | None = None,
) -> None:
    """Decode-role receiver loop for one connection: read KV_PAGES frames,
    fire ``xfer.recv`` / ``xfer.verify``, verify, hand verified payloads to
    ``import_fn(payload) -> awaitable (ok, reason)`` (the engine-thread
    import), and answer each frame with a KV_ACK.  Every structured
    failure is acked with its reason; a ``drop`` rule swallows the frame
    silently so the sender exercises its timeout path."""
    stats = stats if stats is not None else ReceiverStats()
    try:
        while True:
            try:
                msg = await protocol.receive_message(reader, writer=writer)
            except (asyncio.IncompleteReadError, ConnectionError, OSError,
                    EOFError):
                return  # peer hung up
            except protocol.ProtocolError:
                await protocol.send_message(writer, protocol.message(
                    "KV_ACK", {"ok": False, "reason": "bad frame"}
                ))
                return
            if msg.get("type") != "KV_PAGES":
                await protocol.send_message(writer, protocol.message(
                    "KV_ACK", {"ok": False, "reason": "bad frame"}
                ))
                continue
            tid = (msg.get("payload") or {}).get("transfer_id")
            rule = await _apply_deferred(
                faults.fire("xfer.recv", tag=tid, defer_stall=True)
                if faults is not None else None
            )
            if rule is not None and rule.action == "drop":
                continue  # pretend the frame was lost in flight: no ack
            if rule is not None and rule.action == "corrupt":
                msg = corrupt_payload(msg)
            # Verification decodes + checksums a multi-MB payload: run it
            # off the loop so concurrent imports never stall the decode
            # replica's own /healthz.
            payload, reason = await asyncio.to_thread(
                verify_and_decode, msg, page_digests_fn
            )
            vrule = await _apply_deferred(
                faults.fire("xfer.verify", tag=tid, defer_stall=True)
                if faults is not None else None
            )
            if payload is not None and vrule is not None \
                    and vrule.action == "corrupt":
                METRICS.inc("xfer.verify_failures")
                payload, reason = None, "digest mismatch"
            if payload is None:
                stats.rejected += 1
                stats.last_reason = reason
                await protocol.send_message(writer, protocol.message(
                    "KV_ACK", {"ok": False, "reason": reason,
                               "transfer_id": tid}
                ))
                if reason.startswith("bad frame"):
                    return
                continue
            try:
                ok, reason = await asyncio.wait_for(
                    import_fn(payload), import_timeout_s
                )
            except asyncio.TimeoutError:
                ok, reason = False, "import timed out"
            if ok and reason == "duplicate":
                stats.duplicates += 1
                METRICS.inc("xfer.dup_deliveries")
            elif ok:
                stats.imported += 1
            else:
                stats.rejected += 1
            stats.last_reason = reason
            await protocol.send_message(writer, protocol.message(
                "KV_ACK", {"ok": ok, "reason": reason, "transfer_id": tid}
            ))
    finally:
        writer.close()
