"""Thin control-plane client (used by the CLI and tests): ask the
coordinator for status/metrics or submit generation, over the JSON protocol.
Plus :class:`ServingClient`, an overload-aware HTTP client for the serving
gateway (runtime/server.py): 429/503 answers carry ``Retry-After``, and the
client honors it with jittered exponential backoff on top — the polite-load
half of the server's shedding contract (bench.py's overload ladder row and
the overload tests drive traffic through it)."""

from __future__ import annotations

import asyncio
import json
import random
import uuid
from typing import Any

from . import protocol


class CoordinatorClient:
    def __init__(self, host: str, port: int) -> None:
        self.host = host
        self.port = port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None

    async def __aenter__(self) -> "CoordinatorClient":
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        return self

    async def __aexit__(self, *exc) -> None:
        if self._writer:
            self._writer.close()

    async def request(self, type_: str, payload: Any = None, timeout: float = 30.0) -> Any:
        assert self._reader and self._writer, "use 'async with'"
        msg_id = uuid.uuid4().hex
        await protocol.send_message(
            self._writer, protocol.message(type_, payload, msg_id=msg_id)
        )
        try:
            while True:
                msg = await protocol.receive_message(
                    self._reader, timeout=timeout, writer=self._writer
                )
                if msg.get("msg_id") == msg_id:
                    if msg["type"] == "ERROR":
                        raise RuntimeError(str(msg.get("payload")))
                    return msg.get("payload")
        except TimeoutError:
            # a timeout can strand a half-read frame; this stream is dead
            self._writer.close()
            self._reader = self._writer = None
            raise

    async def status(self) -> dict:
        return await self.request("GET_STATUS")

    async def metrics(self) -> dict:
        return await self.request("GET_METRICS")


class ServingClient:
    """Async HTTP client for the serving gateway with overload-aware
    retries.

    A 429 (queue full / cost gate) or 503 (draining / shed) answer is
    retried up to ``max_retries`` times: the wait honors the server's
    ``Retry-After`` header (clamped to ``retry_after_cap_s`` when set — CI
    and benches cannot sleep 30 s per hint) PLUS a jittered exponential
    term ``U(0,1) * min(backoff_cap_s, backoff_base_s * 2^attempt)``, so a
    thundering herd that was shed together does not come back together.
    Connection errors retry on the same schedule (the server may be
    mid-restart) — NOTE that a connection dying mid-response therefore
    re-submits a request the server may have fully served (at-least-once
    semantics; fine for the benches/tests this client drives, not for
    billing-sensitive traffic).  ``retries_taken`` counts backoff waits
    for tests/bench.

    Multi-endpoint mode: construct with ``endpoints=[(host, port), ...]``
    (every replica of a fleet, or several routers) and every failure
    ROTATES to the next endpoint before retrying — a dead endpoint fails
    over immediately to a not-yet-tried one, while 429/503 answers still
    honor ``Retry-After`` before the rotated retry.  ``failovers`` counts
    rotations.
    """

    def __init__(self, host: str | None = None, port: int | None = None,
                 max_retries: int = 4,
                 backoff_base_s: float = 0.25, backoff_cap_s: float = 8.0,
                 retry_after_cap_s: float | None = None,
                 rng: random.Random | None = None,
                 endpoints: "list[tuple[str, int]] | None" = None,
                 tenant: str | None = None) -> None:
        # Client-side failover: pass ``endpoints`` (a list of (host, port)
        # pairs — e.g. every replica of a fleet, or several routers) and a
        # connect error or 429/503 ROTATES to the next endpoint for the
        # retry.  A fresh endpoint after a connection failure is tried
        # immediately (the backoff sleep protects overloaded servers, not
        # dead sockets); once every endpoint failed in the current
        # rotation, the usual Retry-After-honoring jittered backoff
        # applies.  ``host``/``port`` remain the single-endpoint spelling.
        if endpoints:
            self.endpoints = [(h, int(p)) for h, p in endpoints]
        elif host is not None and port is not None:
            self.endpoints = [(host, int(port))]
        else:
            raise ValueError("pass host+port or a non-empty endpoints list")
        self.host, self.port = self.endpoints[0]
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.retry_after_cap_s = retry_after_cap_s
        self.retries_taken = 0
        self.failovers = 0  # endpoint rotations taken (tests/bench)
        # Multi-tenant QoS: every request this client sends carries the
        # tenant id as the X-Tenant header; a per-tenant 429
        # (reason "tenant_quota") is retried on the SERVER's per-tenant
        # Retry-After through the existing backoff, and the last shed's
        # machine-readable reason is surfaced for callers/bench.
        # The id is interpolated into the raw request preamble, so it
        # must pass the gateway's canonical rule (one definition — a
        # crafted value could otherwise inject headers and desync the
        # HTTP framing).
        from ..runtime.server import valid_tenant_id

        if tenant is not None and not valid_tenant_id(tenant):
            raise ValueError(
                f"tenant must be 1-64 chars of [A-Za-z0-9._-] "
                f"('-' is reserved), got {tenant!r}"
            )
        self.tenant = tenant
        self.last_shed_reason: str | None = None
        self.tenant_sheds = 0  # 429s with reason tenant_quota observed
        self._ep = 0
        self._rng = rng if rng is not None else random.Random()

    def _rotate(self) -> None:
        self._ep = (self._ep + 1) % len(self.endpoints)
        self.host, self.port = self.endpoints[self._ep]
        self.failovers += 1

    async def _once(self, path: str, body: dict) -> tuple[int, dict, dict]:
        reader, writer = await asyncio.open_connection(self.host, self.port)
        try:
            payload = json.dumps(body).encode()
            tenant_line = (f"X-Tenant: {self.tenant}\r\n"
                           if self.tenant else "")
            writer.write(
                f"POST {path} HTTP/1.1\r\nHost: {self.host}\r\n"
                f"{tenant_line}"
                f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload
            )
            await writer.drain()
            status = int((await reader.readline()).split()[1])
            headers: dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1").partition(":")
                headers[name.strip().lower()] = value.strip()
            raw = await reader.read()
            out = json.loads(raw) if raw.strip() else {}
            return status, headers, out
        finally:
            writer.close()

    def _delay_s(self, attempt: int, headers: dict[str, str]) -> float:
        try:
            hinted = float(headers.get("retry-after", 0) or 0)
        except ValueError:
            hinted = 0.0
        if self.retry_after_cap_s is not None:
            hinted = min(hinted, self.retry_after_cap_s)
        jittered = self._rng.random() * min(
            self.backoff_cap_s, self.backoff_base_s * (2 ** attempt)
        )
        return hinted + jittered

    async def completions(
        self, body: dict, path: str = "/v1/completions",
    ) -> tuple[int, dict]:
        """POST a completion request; returns (status, response body).
        Retries 429/503 (and connection failures) with Retry-After-honoring
        jittered exponential backoff, rotating through ``endpoints`` on
        each failure; any other status returns as-is.  A dead endpoint
        fails over to a not-yet-tried one IMMEDIATELY (no sleep) — the
        backoff protects busy servers, not severed sockets."""
        attempt = 0
        fresh = len(self.endpoints) - 1  # endpoints untried this rotation
        while True:
            headers: dict[str, str] = {}
            try:
                status, headers, out = await self._once(path, body)
            except (ConnectionError, OSError, IndexError, ValueError):
                status, out = None, {}
            if status in (429, 503) and isinstance(out, dict):
                # Surface the shed's machine-readable reason (the server
                # stamps it next to the overloaded_error): callers can
                # tell "MY tenant quota is exhausted" (honor Retry-After
                # instead of hot-retrying; quota ledgers are PER REPLICA,
                # so a rotation may find headroom elsewhere — see the
                # README's quota note) from generic fleet overload.
                reason = (out.get("error") or {}).get("reason")
                if reason is not None:
                    self.last_shed_reason = reason
                    if reason == "tenant_quota":
                        self.tenant_sheds += 1
            if status is not None and status not in (429, 503):
                return status, out
            if attempt >= self.max_retries:
                return (status if status is not None else 599), out
            attempt += 1
            if len(self.endpoints) > 1:
                self._rotate()
            if status is None and fresh > 0:
                # Connect failure with an untried endpoint left: fail over
                # now instead of sleeping at a dead host.
                fresh -= 1
                continue
            fresh = len(self.endpoints) - 1
            await asyncio.sleep(self._delay_s(attempt - 1, headers))
            self.retries_taken += 1
