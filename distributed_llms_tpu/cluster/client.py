"""Thin control-plane client (used by the CLI and tests): ask the
coordinator for status/metrics or submit generation, over the JSON protocol."""

from __future__ import annotations

import asyncio
import uuid
from typing import Any

from . import protocol


class CoordinatorClient:
    def __init__(self, host: str, port: int) -> None:
        self.host = host
        self.port = port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None

    async def __aenter__(self) -> "CoordinatorClient":
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        return self

    async def __aexit__(self, *exc) -> None:
        if self._writer:
            self._writer.close()

    async def request(self, type_: str, payload: Any = None, timeout: float = 30.0) -> Any:
        assert self._reader and self._writer, "use 'async with'"
        msg_id = uuid.uuid4().hex
        await protocol.send_message(
            self._writer, protocol.message(type_, payload, msg_id=msg_id)
        )
        try:
            while True:
                msg = await protocol.receive_message(
                    self._reader, timeout=timeout, writer=self._writer
                )
                if msg.get("msg_id") == msg_id:
                    if msg["type"] == "ERROR":
                        raise RuntimeError(str(msg.get("payload")))
                    return msg.get("payload")
        except TimeoutError:
            # a timeout can strand a half-read frame; this stream is dead
            self._writer.close()
            self._reader = self._writer = None
            raise

    async def status(self) -> dict:
        return await self.request("GET_STATUS")

    async def metrics(self) -> dict:
        return await self.request("GET_METRICS")
