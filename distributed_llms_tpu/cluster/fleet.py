"""Replica fleet: lifecycle + health for N independent serving replicas.

One ``InferenceServer`` (runtime/server.py) is crash-safe — its supervisor
respawns a crashed batcher — but it is still ONE failure domain: a wedged
process, an OOM'd respawn, or a partitioned host takes the whole service
down.  This module treats each full server/batcher stack as a REPLICA and
owns everything about replicas that is not request routing:

- **Lifecycle.**  Each :class:`ReplicaHandle` wraps a factory that builds a
  fresh server; :meth:`ReplicaFleet.start` boots them all, and
  :meth:`respawn` rebuilds one from scratch (new pool, new caches, new
  port) — the process-level analogue of the PR-2 supervisor's batcher
  respawn.
- **Health.**  A probe loop GETs every replica's real ``/healthz``
  readiness/liveness report (the PR-2 watchdog surface) on a fixed
  interval: 200 marks it routable, a 503 (stalled engine, draining, dead
  supervisor) or ``probe_failures`` consecutive unreachable probes marks it
  un-routable AND aborts the router's in-flight requests on it, so
  zero-streamed work migrates instead of hanging.
- **Rolling drain/respawn.**  :meth:`drain` stops new placement, lets the
  router's in-flight requests on the replica finish (stragglers past the
  deadline migrate — they are aborted and the router re-places the
  zero-streamed ones), gracefully stops the server, and respawns it;
  :meth:`rolling_restart` walks the whole fleet one replica at a time —
  a zero-downtime restart as long as N >= 2.
- **Replica-scoped chaos** (runtime/faults.py).  Every probe tick consults
  three injection sites per replica, tag = replica name:

  - ``replica.crash`` — action ``close`` (or ``raise``): the replica dies
    abruptly (``InferenceServer.kill``: sockets severed unflushed, engine
    reaped — SIGKILL semantics, no drain);
  - ``replica.stall`` — action ``delay:<s>``: the replica's engine wedges
    for ``<s>`` seconds (one blocking stall armed on its own fault plane at
    ``batcher.decode``), long enough past the watchdog that ``/healthz``
    flips unhealthy — the wedged-device drill;
  - ``replica.partition`` — action ``drop[:<s>]``: the replica becomes
    unreachable FROM THE ROUTER for ``<s>`` seconds (no arg: until respawn)
    while its own engine keeps running — the asymmetric network failure a
    crash drill cannot model.

All fleet state is confined to the asyncio event loop (the coordinator's
confinement model); the replicas' engine threads never touch it.
"""

from __future__ import annotations

import asyncio
import json
import math

from ..core.observability import METRICS, get_logger

log = get_logger("fleet")


class ReplicaHandle:
    """One replica as the fleet/router sees it.  ``committed_tokens`` and
    ``inflight`` are ROUTER-side accounting (the router is the only writer;
    both confined to the event loop): estimated token mass placed on the
    replica and the in-flight proxy records, each carrying an ``abort``
    event the fleet sets when the replica stops being trustworthy."""

    def __init__(self, name: str, factory) -> None:
        self.name = name
        self.factory = factory  # () -> InferenceServer (unstarted, port 0)
        self.server = None
        self.host: str | None = None
        self.port: int | None = None
        # Disaggregated serving: the replica's role ("colocated" /
        # "prefill" / "decode") and, on decode replicas, where its KV
        # import listener landed — both read off the server at boot.
        self.role = "colocated"
        self.kv_port: int | None = None
        # Cache-lifetime epoch: bumped every (re)boot.  A respawned
        # replica's pool and prefix cache are COLD — router-side prefix
        # affinity entries recorded against an older epoch are stale and
        # must not beat least-loaded placement.
        self.epoch = 0
        # starting | healthy | unhealthy | draining | dead
        self.state = "starting"
        self.partitioned_until = 0.0  # loop-clock; math.inf = until respawn
        self.probe_failures = 0
        self.restarts = 0
        self.committed_tokens = 0
        self.inflight: set = set()  # router _Inflight records
        # In-flight prefill handoff RPCs the router has outstanding on
        # this handle (prefill role only) — the prefill tier's
        # queue-depth signal for cluster/autoscale.py.
        self.handoffs = 0
        self.last_report: dict = {}

    def routable(self, now: float) -> bool:
        """Whether the router may place NEW work here."""
        return self.state == "healthy" and now >= self.partitioned_until

    def reachable(self, now: float) -> bool:
        return self.state != "dead" and now >= self.partitioned_until

    def abort_inflight(self) -> None:
        """Wake every in-flight proxy on this replica: zero-streamed
        requests fail over to a healthy replica, streamed ones fail with a
        structured engine_error (the router's call, mirroring the PR-2
        supervisor's triage one level up)."""
        for rec in list(self.inflight):
            rec.abort.set()


class ReplicaFleet:
    """Owns N replica handles, their probe loop, and drain/respawn.

    ``factories`` builds each replica's :class:`InferenceServer` (bound to
    an ephemeral port; the fleet records where it actually landed).  The
    optional ``faults`` plane is consulted once per probe tick per replica
    at the ``replica.*`` sites (module docstring)."""

    def __init__(self, factories, names=None, probe_interval_s: float = 0.25,
                 probe_failures: int = 2, probe_timeout_s: float = 2.0,
                 faults=None) -> None:
        names = names or [f"r{i}" for i in range(len(factories))]
        if len(names) != len(factories):
            raise ValueError(f"{len(names)} names for {len(factories)} factories")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.replicas = [ReplicaHandle(n, f) for n, f in zip(names, factories)]
        self._by_name = {h.name: h for h in self.replicas}
        # Elastic serving (cluster/autoscale.py): the factory new
        # replicas boot from when add_replica is called without one, and
        # a monotone counter so scaled-up names never collide with a
        # drained-away predecessor's.
        self._default_factory = factories[0] if factories else None
        self._next_name = len(self.replicas)
        self.probe_interval_s = probe_interval_s
        self.probe_failures = probe_failures
        self.probe_timeout_s = probe_timeout_s
        self.faults = faults
        self._probe_task: asyncio.Task | None = None
        self._loop: asyncio.AbstractEventLoop | None = None

    def __getitem__(self, name: str) -> ReplicaHandle:
        return self._by_name[name]

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        for h in self.replicas:
            await self._boot(h)
        self._probe_task = asyncio.create_task(self._probe_loop())

    async def _boot(self, h: ReplicaHandle) -> None:
        # The factory builds a full server/batcher stack — model jits and
        # pool allocation measured in wall-clock — so it runs OFF the
        # event loop: probing, routing, and failure detection for every
        # OTHER replica must not freeze while a new one warms up (the
        # autoscaler boots replicas while the fleet is at its busiest).
        h.server = await asyncio.to_thread(h.factory)
        h.host, h.port = await h.server.start()
        h.role = getattr(h.server, "role", "colocated")
        h.kv_port = getattr(h.server, "kv_bound_port", None)
        h.epoch += 1  # fresh pool + prefix cache: older affinity is stale
        h.state = "starting"
        h.probe_failures = 0
        h.partitioned_until = 0.0
        log.info("replica %s (%s) serving on %s:%s", h.name, h.role,
                 h.host, h.port)

    async def stop(self) -> None:
        if self._probe_task is not None:
            self._probe_task.cancel()
            try:
                await self._probe_task
            except asyncio.CancelledError:
                pass
            self._probe_task = None
        for h in self.replicas:
            if h.state != "dead" and h.server is not None:
                await h.server.stop()
                h.state = "dead"

    # -- chaos + probing ---------------------------------------------------

    async def kill(self, name_or_handle) -> None:
        """Kill one replica abruptly (process-death semantics — see
        ``InferenceServer.kill``).  The replica stays ``dead`` until an
        explicit :meth:`respawn`; its in-flight router requests abort so
        the zero-streamed ones migrate immediately, not at probe time."""
        h = (name_or_handle if isinstance(name_or_handle, ReplicaHandle)
             else self._by_name[name_or_handle])
        if h.state == "dead":
            return
        log.warning("replica %s killed", h.name)
        h.state = "dead"
        METRICS.inc("router.replica_kills")
        h.abort_inflight()
        if h.server is not None:
            await h.server.kill()
        self._publish_health()

    def _wedge(self, h: ReplicaHandle, seconds: float) -> None:
        """Wedge the replica's engine: one blocking ``seconds``-long stall
        armed on its own fault plane at ``batcher.decode`` — its watchdog
        then flips ``/healthz`` unhealthy while work is in flight, exactly
        like a stuck device call.  The rule must land on a plane PRIVATE
        to THIS replica's batcher: the fleet's own plane is traversed by
        the event loop and (if shared across batchers) by every engine
        thread at once, so arming an untagged ``batcher.decode`` rule
        there would stall whichever replica decodes next, not the drill's
        target — the CLI gives each replica its own plane for exactly
        this reason."""
        from ..runtime.faults import FaultPlane

        batcher = h.server.batcher
        if batcher.faults is None or batcher.faults is self.faults:
            batcher.faults = FaultPlane()
        batcher.faults.add("batcher.decode", "stall", when="1", arg=seconds)
        log.warning("replica %s: engine wedge armed (%.2fs)", h.name, seconds)

    def _partition(self, h: ReplicaHandle, seconds: float | None) -> None:
        now = self._loop.time()
        h.partitioned_until = (math.inf if seconds is None
                               else now + seconds)
        log.warning("replica %s partitioned from the router (%s)",
                    h.name, "until respawn" if seconds is None
                    else f"{seconds:g}s")
        h.abort_inflight()
        self._publish_health()

    async def _chaos(self, h: ReplicaHandle) -> None:
        """Consult the replica-scoped fault sites for one tick.  These
        sites are traversed by the EVENT LOOP, so every fire() defers
        stall application — a blocking sleep here would freeze probing
        for the whole fleet and the router with it; a ``stall`` rule at
        ``replica.stall`` gets the same wedge semantics as ``delay``."""
        from ..runtime.faults import InjectedFault

        plane = self.faults
        if plane is None:
            return
        try:
            rule = plane.fire("replica.crash", tag=h.name, defer_stall=True)
        except InjectedFault:
            rule = None
            await self.kill(h)
        else:
            if rule is not None and rule.action == "close":
                await self.kill(h)
        rule = plane.fire("replica.stall", tag=h.name, defer_stall=True)
        if (rule is not None and rule.action in ("delay", "stall")
                and h.state != "dead"):
            self._wedge(h, rule.arg or 0.0)
        rule = plane.fire("replica.partition", tag=h.name, defer_stall=True)
        if rule is not None and rule.action == "drop" and h.state != "dead":
            self._partition(h, rule.arg)

    async def _probe(self, h: ReplicaHandle) -> tuple[int, dict]:
        reader, writer = await asyncio.open_connection(h.host, h.port)
        try:
            writer.write(b"GET /healthz HTTP/1.1\r\nHost: fleet\r\n\r\n")
            await writer.drain()
            status = int((await reader.readline()).split()[1])
            clen = 0
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1").partition(":")
                if name.strip().lower() == "content-length":
                    clen = int(value.strip())
            body = await reader.readexactly(clen) if clen else b""
            return status, (json.loads(body) if body else {})
        finally:
            writer.close()

    async def _probe_loop(self) -> None:
        while True:
            await asyncio.sleep(self.probe_interval_s)
            # One task per replica: a slow/unreachable probe (up to
            # probe_timeout_s) or a chaos kill awaiting an engine join
            # must not delay every OTHER replica's failure detection —
            # serial ticks would couple failover latency to the slowest
            # replica in the fleet.
            # ONE snapshot for both the gather and the attribution zip:
            # the autoscaler may add/remove replicas mid-gather, and a
            # re-snapshot would misalign handles with results (a probe
            # failure logged against the wrong replica, or dropped).
            handles = list(self.replicas)
            results = await asyncio.gather(
                *[self._tick_one(h) for h in handles],
                return_exceptions=True,
            )
            for h, r in zip(handles, results):
                if isinstance(r, BaseException):
                    log.error("probe tick for replica %s failed",
                              h.name, exc_info=r)
            self._publish_health()

    async def _tick_one(self, h: ReplicaHandle) -> None:
        await self._chaos(h)
        await self._tick(h)

    async def wait_healthy(self, n: int | None = None,
                           timeout_s: float = 60.0) -> bool:
        """Block until ``n`` replicas (default: all) probe healthy, or the
        timeout lapses.  Boot-time convenience: replicas start in state
        ``starting`` and only the probe loop flips them routable — serving
        before the first healthy probe sheds 503s from an idle fleet."""
        want = len(self.replicas) if n is None else n
        deadline = self._loop.time() + timeout_s
        while self._loop.time() < deadline:
            now = self._loop.time()
            if sum(1 for h in self.replicas if h.routable(now)) >= want:
                return True
            await asyncio.sleep(min(0.02, self.probe_interval_s / 2))
        return False

    async def _tick(self, h: ReplicaHandle) -> None:
        """One probe of one replica.  Only ``starting``/``healthy``/
        ``unhealthy`` transition here — ``draining`` and ``dead`` are
        operator states the probe must not overwrite."""
        if h.state in ("dead", "draining"):
            return
        now = self._loop.time()
        if now < h.partitioned_until:
            # The router cannot reach it; neither can this probe (the
            # probe IS the router's view).
            self._note_unreachable(h)
            return
        try:
            code, report = await asyncio.wait_for(
                self._probe(h), self.probe_timeout_s
            )
        except (OSError, ConnectionError, EOFError, ValueError, IndexError,
                asyncio.TimeoutError, asyncio.IncompleteReadError):
            self._note_unreachable(h)
            return
        h.last_report = report
        if code == 200:
            h.probe_failures = 0
            if h.state != "healthy":
                log.info("replica %s healthy", h.name)
                h.state = "healthy"
        else:
            # The replica itself says not-ready (stalled past the
            # watchdog, draining, dead engine): believe it immediately.
            self._mark_unhealthy(h, report.get("status", str(code)))

    def _note_unreachable(self, h: ReplicaHandle) -> None:
        h.probe_failures += 1
        if h.probe_failures >= self.probe_failures:
            self._mark_unhealthy(h, "unreachable")

    def _mark_unhealthy(self, h: ReplicaHandle, reason: str) -> None:
        if h.state in ("starting", "healthy"):
            log.warning("replica %s unhealthy (%s)", h.name, reason)
            h.state = "unhealthy"
            # In-flight proxies must not wait out a wedged replica:
            # zero-streamed requests migrate NOW.
            h.abort_inflight()

    def _publish_health(self) -> None:
        now = self._loop.time() if self._loop is not None else 0.0
        METRICS.set_gauge(
            "router.replicas_healthy",
            sum(1 for h in self.replicas if h.routable(now)),
        )

    # -- elastic scaling (cluster/autoscale.py drives these) ---------------

    def _fresh_name(self, prefix: str = "r") -> str:
        while True:
            name = f"{prefix}{self._next_name}"
            self._next_name += 1
            if name not in self._by_name:
                return name

    async def add_replica(self, factory=None, name: str | None = None,
                          wait_healthy_s: float = 60.0,
                          role: str | None = None) -> ReplicaHandle:
        """Scale UP: boot one more replica (fresh server/batcher stack on
        an ephemeral port) and register it with the fleet once its boot
        SUCCEEDED — a factory/start failure raises with nothing
        registered, so a failed scale-up leaves the fleet exactly as it
        was (no half-booted handle for the router to trip on).  Returns
        after the replica's first healthy probe (or ``wait_healthy_s``;
        the caller reads ``handle.state``).  ``role`` only picks the
        minted name's prefix (``p``/``d`` for prefill/decode, matching
        the CLI's boot-time names) — the handle's actual role is read
        off the server the factory builds, same as every boot."""
        factory = factory or self._default_factory
        if factory is None:
            raise ValueError("fleet has no replica factory to scale with")
        if name is not None and name in self._by_name:
            raise ValueError(f"replica name {name!r} already exists")
        prefix = {"prefill": "p", "decode": "d"}.get(role, "r")
        h = ReplicaHandle(name or self._fresh_name(prefix), factory)
        await self._boot(h)  # raises -> nothing registered (clean failure)
        self.replicas.append(h)
        self._by_name[h.name] = h
        METRICS.inc("autoscale.replicas_added")
        self._publish_health()
        deadline = self._loop.time() + wait_healthy_s
        while h.state != "healthy" and self._loop.time() < deadline:
            await asyncio.sleep(self.probe_interval_s / 2)
        return h

    async def remove_replica(self, name: str,
                             drain_timeout_s: float = 30.0) -> None:
        """Scale DOWN, gracefully: stop new placement (state
        ``draining``), let the router's in-flight requests on the replica
        FINISH (byte-exact — nothing is cut mid-decode), abort stragglers
        at the deadline (zero-streamed ones migrate via the router's
        exact failover), stop the server, and drop the handle from the
        fleet.  Unlike :meth:`drain`, nothing respawns — the capacity is
        returned."""
        h = self._by_name[name]
        log.info("scaling down: draining replica %s away", h.name)
        h.state = "draining"
        METRICS.inc("autoscale.replicas_removed")
        self._publish_health()
        deadline = self._loop.time() + drain_timeout_s
        while h.inflight and self._loop.time() < deadline:
            await asyncio.sleep(0.02)
        h.abort_inflight()
        try:
            if h.server is not None:
                await h.server.stop(
                    drain_timeout=max(0.0, deadline - self._loop.time())
                )
        finally:
            # The handle leaves the fleet even if the server's stop
            # raised — a zombie entry would keep the router placing
            # against a dead replica forever.
            h.state = "dead"
            self.replicas.remove(h)
            del self._by_name[h.name]
            self._publish_health()

    # -- rolling drain/respawn ---------------------------------------------

    async def respawn(self, name: str, wait_healthy_s: float = 60.0) -> None:
        """Replace one replica's server with a fresh build (new pool,
        caches, port) and wait for its first healthy probe."""
        h = self._by_name[name]
        old = h.server
        if h.state != "dead" and old is not None:
            await old.stop()
        h.state = "dead"
        await self._boot(h)
        h.restarts += 1
        METRICS.inc("router.respawns")
        deadline = self._loop.time() + wait_healthy_s
        while h.state != "healthy" and self._loop.time() < deadline:
            await asyncio.sleep(self.probe_interval_s / 2)
        self._publish_health()

    async def drain(self, name: str, drain_timeout_s: float = 30.0) -> None:
        """Zero-downtime restart of ONE replica: stop new placement
        (state ``draining``), let the router's in-flight requests finish,
        abort stragglers at the deadline (zero-streamed ones migrate),
        stop the server gracefully, respawn it, and wait until it probes
        healthy again."""
        h = self._by_name[name]
        log.info("draining replica %s", h.name)
        h.state = "draining"
        METRICS.inc("router.drains")
        self._publish_health()
        deadline = self._loop.time() + drain_timeout_s
        while h.inflight and self._loop.time() < deadline:
            await asyncio.sleep(0.02)
        h.abort_inflight()
        await h.server.stop(
            drain_timeout=max(0.0, deadline - self._loop.time())
        )
        await self.respawn(name)

    async def rolling_restart(self, drain_timeout_s: float = 30.0) -> None:
        """Drain + respawn every replica, one at a time — the whole fleet
        restarts with zero downtime as long as N >= 2."""
        for h in list(self.replicas):
            await self.drain(h.name, drain_timeout_s=drain_timeout_s)

    # -- reporting ---------------------------------------------------------

    def report(self) -> dict:
        """Fleet view for the router's /healthz."""
        now = self._loop.time() if self._loop is not None else 0.0
        return {
            "replicas": {
                h.name: {
                    "state": h.state,
                    "role": h.role,
                    "routable": h.routable(now),
                    "partitioned": now < h.partitioned_until,
                    "committed_tokens": h.committed_tokens,
                    "inflight": len(h.inflight),
                    "restarts": h.restarts,
                }
                for h in self.replicas
            },
            "healthy": sum(1 for h in self.replicas if h.routable(now)),
        }
