"""Control-plane wire protocol: length-prefixed JSON over asyncio TCP.

Replaces the reference's MessageProtocol (src/network/protocol.py) one-for-one
on the *control* plane only — tensors NEVER transit this socket (the data
plane is compiled XLA collectives over ICI; shard "distribution" is
device_put, SURVEY §2.4).  Differences by design:

- JSON, never pickle (the reference pickled headers and payloads,
  protocol.py:58,105 — arbitrary-code-execution on connect);
- 8-byte big-endian length prefix instead of 10-byte ASCII (protocol.py:8);
- a single framing (the reference half-migrated TCP->ZMQ and broke both,
  defects D1-D3);
- every message carries ``type`` + ``payload``; requests carry ``msg_id`` so
  replies correlate (the reference matched on task_id with a re-queue race,
  D9).

Message set (reference's MESSAGE_TYPES at protocol.py:12-20 mapped to the
mesh runtime):
  REGISTER, REGISTER_ACK, HEARTBEAT, PLACE_SHARDS (was LOAD_SHARD),
  UNLOAD_SHARDS, GENERATE (was RUN_INFERENCE), SCHEDULE_COMPUTATION,
  RESULT, ERROR, GET_STATUS, GET_METRICS, SHUTDOWN
"""

from __future__ import annotations

import asyncio
import json
import struct
from typing import Any

MAX_FRAME = 64 * 1024 * 1024  # control plane only; nothing big belongs here

MESSAGE_TYPES = frozenset(
    {
        "REGISTER",
        "REGISTER_ACK",
        "HEARTBEAT",
        "PLACE_SHARDS",
        "UNLOAD_SHARDS",
        "GENERATE",
        "SCHEDULE_COMPUTATION",
        "RESULT",
        "ERROR",
        "GET_STATUS",
        "GET_METRICS",
        "SHUTDOWN",
    }
)


class ProtocolError(Exception):
    pass


def encode(msg: dict[str, Any]) -> bytes:
    if msg.get("type") not in MESSAGE_TYPES:
        raise ProtocolError(f"unknown message type {msg.get('type')!r}")
    body = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        raise ProtocolError(f"frame too large ({len(body)} bytes)")
    return struct.pack(">Q", len(body)) + body


def decode_header(header: bytes) -> int:
    (n,) = struct.unpack(">Q", header)
    if n > MAX_FRAME:
        raise ProtocolError(f"frame too large ({n} bytes)")
    return n


async def send_message(writer: asyncio.StreamWriter, msg: dict[str, Any]) -> None:
    writer.write(encode(msg))
    await writer.drain()


async def receive_message(
    reader: asyncio.StreamReader, timeout: float | None = None
) -> dict[str, Any]:
    """Read one frame.  A TimeoutError may fire mid-frame (header consumed,
    body pending) which desynchronizes the stream — callers must treat the
    connection as dead after a timeout and reconnect (CoordinatorClient
    does)."""
    async def _recv() -> dict[str, Any]:
        header = await reader.readexactly(8)
        n = decode_header(header)
        body = await reader.readexactly(n)
        try:
            msg = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ProtocolError(f"invalid frame body: {e}") from e
        if not isinstance(msg, dict) or msg.get("type") not in MESSAGE_TYPES:
            raise ProtocolError(f"invalid message: {str(msg)[:200]}")
        return msg

    if timeout is None:
        return await _recv()
    return await asyncio.wait_for(_recv(), timeout)


def message(type_: str, payload: Any = None, msg_id: str | None = None, **extra) -> dict:
    out: dict[str, Any] = {"type": type_, "payload": payload}
    if msg_id is not None:
        out["msg_id"] = msg_id
    out.update(extra)
    return out
