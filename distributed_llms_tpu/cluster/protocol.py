"""Control-plane wire protocol: length-prefixed JSON over asyncio TCP.

Replaces the reference's MessageProtocol (src/network/protocol.py) one-for-one
on the *control* plane only — tensors NEVER transit this socket (the data
plane is compiled XLA collectives over ICI; shard "distribution" is
device_put, SURVEY §2.4).  Differences by design:

- JSON, never pickle (the reference pickled headers and payloads,
  protocol.py:58,105 — arbitrary-code-execution on connect);
- 8-byte big-endian length prefix instead of 10-byte ASCII (protocol.py:8);
- a single framing (the reference half-migrated TCP->ZMQ and broke both,
  defects D1-D3);
- every message carries ``type`` + ``payload``; requests carry ``msg_id`` so
  replies correlate (the reference matched on task_id with a re-queue race,
  D9);
- large frames are transparently zlib-compressed (flag bit in the length
  prefix) and multiple messages can ride one frame via BATCH — the
  compression/batching the reference planned (plan.md:285-288, 482-486) but
  never built.

Message set (reference's MESSAGE_TYPES at protocol.py:12-20 mapped to the
mesh runtime):
  REGISTER, REGISTER_ACK, HEARTBEAT, PLACE_SHARDS (was LOAD_SHARD),
  UNLOAD_SHARDS, GENERATE (was RUN_INFERENCE), SCHEDULE_COMPUTATION,
  RESULT, ERROR, GET_STATUS, GET_METRICS, SHUTDOWN
plus the disaggregated-serving KV-handoff pair (cluster/kv_transfer.py):
  KV_PAGES (prefill -> decode: page payload + chained digests + checksum),
  KV_ACK   (decode -> prefill: verified import, or a structured NACK)
"""

from __future__ import annotations

import asyncio
import json
import struct
import zlib
from typing import Any

MAX_FRAME = 64 * 1024 * 1024  # control plane only; nothing big belongs here
COMPRESS_MIN = 2048  # frames at least this large get zlib'd
_FLAG_ZLIB = 0x01  # stored in the top byte of the 8-byte length prefix
_LEN_MASK = (1 << 56) - 1

MESSAGE_TYPES = frozenset(
    {
        "REGISTER",
        "REGISTER_ACK",
        "HEARTBEAT",
        "PLACE_SHARDS",
        "UNLOAD_SHARDS",
        "GENERATE",
        "SCHEDULE_COMPUTATION",
        "RESULT",
        "ERROR",
        "GET_STATUS",
        "GET_METRICS",
        "SHUTDOWN",
        "BATCH",
        # KV-handoff plane (cluster/kv_transfer.py): a prefill-role engine
        # ships a finished row's KV pages (payload + chained page digests +
        # checksum) to a decode-role engine, which verifies and acks.  The
        # ONE exception to "nothing big belongs here": page payloads ride
        # base64 in the JSON body, bounded by MAX_FRAME like every frame
        # (an oversized handoff fails loudly at send time).
        "KV_PAGES",
        "KV_ACK",
    }
)


class ProtocolError(Exception):
    pass


# -- deterministic fault injection (runtime/faults.py) ----------------------
#
# A process-wide FaultPlane consulted by send_message / receive_message at
# sites "proto.send" / "proto.recv", tagged with the message type — so a
# test (or an operator drill) can drop, delay, or sever exact control-plane
# frames instead of killing processes and sleeping past wall-clock
# deadlines.  Process-global on purpose: the framing functions are free
# functions with no instance to hang state on.  Tests MUST uninstall
# (set_fault_plane(None)) in teardown.

_FAULTS = None


def set_fault_plane(plane) -> None:
    """Install (or with ``None`` uninstall) the process-wide FaultPlane for
    protocol framing.  Returns nothing; idempotent."""
    global _FAULTS
    _FAULTS = plane


def get_fault_plane():
    return _FAULTS


async def _apply_frame_fault(site: str, msg: dict,
                             writer: asyncio.StreamWriter | None) -> str | None:
    """Consult the installed plane for one frame.  Returns "drop" when the
    caller must swallow the frame; applies "delay" here; "close" severs the
    stream and raises so both peers observe a real connection failure."""
    if _FAULTS is None:
        return None
    # defer_stall: this function runs ON the event loop — a stall rule
    # gets awaited-delay semantics instead of a blocking sleep (which
    # would freeze every peer sharing the loop, /healthz included).
    rule = _FAULTS.fire(site, tag=msg.get("type"), defer_stall=True)
    if rule is None:
        return None
    if rule.action == "drop":
        return "drop"
    if rule.action in ("delay", "stall"):
        await asyncio.sleep(rule.arg or 0.0)
        return "delay"
    if rule.action == "close":
        if writer is not None:
            writer.close()
        raise ConnectionResetError(
            f"fault injection: connection closed at {site} "
            f"({msg.get('type')})"
        )
    return rule.action


def _dump_body(msg: dict[str, Any]) -> bytes:
    """Validate + JSON-encode one message body (no compression)."""
    if msg.get("type") not in MESSAGE_TYPES:
        raise ProtocolError(f"unknown message type {msg.get('type')!r}")
    body = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME:
        # Check the *logical* size pre-compression: the receiver enforces the
        # same bound post-decompression, so an over-limit-but-compressible
        # frame must fail at send time, not as a silent connection drop.
        raise ProtocolError(f"frame too large ({len(body)} bytes)")
    return body


def _frame(body: bytes, flags: int) -> bytes:
    if len(body) > MAX_FRAME:
        raise ProtocolError(f"frame too large ({len(body)} bytes)")
    return struct.pack(">Q", (flags << 56) | len(body)) + body


def _compress_frame(body: bytes) -> bytes:
    """zlib the body and frame whichever representation is smaller.
    CPU-bound (hundreds of ms on a multi-MB KV payload): event-loop
    senders reach this only through :func:`encode_on_loop`'s
    ``asyncio.to_thread`` hop — graftflow's GF201 pins that."""
    packed = zlib.compress(body, 6)
    if len(packed) < len(body):
        return _frame(packed, _FLAG_ZLIB)
    return _frame(body, 0)


def encode(msg: dict[str, Any], compress: bool | None = None) -> bytes:
    """Frame one message (synchronous).  ``compress=None`` auto-compresses
    bodies >= COMPRESS_MIN when it actually shrinks them.  Event-loop
    callers must use :func:`encode_on_loop` (or wrap this in
    ``asyncio.to_thread``, as cluster/kv_transfer.py does): the zlib pass
    over a large frame would stall the same loop that answers /healthz."""
    body = _dump_body(msg)
    if compress is None:
        compress = len(body) >= COMPRESS_MIN
    if compress:
        return _compress_frame(body)
    return _frame(body, 0)


async def encode_on_loop(msg: dict[str, Any]) -> bytes:
    """Event-loop-side encode: the WHOLE pass (json dump + zlib + frame)
    runs off the loop.  A message's size is unknowable before it is
    dumped, and json.dumps of a near-MAX_FRAME payload stalls the loop
    just like the zlib pass PR 7 shipped — so neither gets to run there;
    the ~100 us thread hop is noise against control-plane RTTs."""
    return await asyncio.to_thread(encode, msg)


def decode_header(header: bytes) -> tuple[int, int]:
    """-> (body length, flags)."""
    (v,) = struct.unpack(">Q", header)
    flags, n = v >> 56, v & _LEN_MASK
    if n > MAX_FRAME:
        raise ProtocolError(f"frame too large ({n} bytes)")
    return n, flags


def _inflate(body: bytes) -> bytes:
    """Bounded inflate: cap the output BEFORE allocating it, so a
    decompression bomb can't balloon past MAX_FRAME.  CPU-bound — the
    receive path runs it through ``asyncio.to_thread``."""
    try:
        d = zlib.decompressobj()
        out = d.decompress(body, MAX_FRAME + 1)
    except zlib.error as e:
        raise ProtocolError(f"bad compressed frame: {e}") from e
    if len(out) > MAX_FRAME or d.unconsumed_tail:
        raise ProtocolError("decompressed frame too large")
    return out


async def send_message(writer: asyncio.StreamWriter, msg: dict[str, Any]) -> None:
    if _FAULTS is not None:
        if await _apply_frame_fault("proto.send", msg, writer) == "drop":
            return  # frame swallowed: the wire never sees it
    writer.write(await encode_on_loop(msg))
    await writer.drain()


async def receive_message(
    reader: asyncio.StreamReader, timeout: float | None = None,
    *, writer: asyncio.StreamWriter | None = None
) -> dict[str, Any]:
    """Read one frame.  A TimeoutError may fire mid-frame (header consumed,
    body pending) which desynchronizes the stream — callers must treat the
    connection as dead after a timeout and reconnect (CoordinatorClient
    does).  ``writer`` is the stream's paired writer, used only by an
    installed FaultPlane: a ``proto.recv ... close`` rule severs it so the
    PEER observes a real connection failure too, not just a local raise."""
    async def _recv() -> dict[str, Any]:
        while True:
            header = await reader.readexactly(8)
            n, flags = decode_header(header)
            body = await reader.readexactly(n)
            if flags & _FLAG_ZLIB:
                # Inflate OFF the loop: compressed frames are >= COMPRESS_MIN
                # by construction and can inflate to MAX_FRAME — a receive
                # path must never stall the loop it shares with /healthz.
                body = await asyncio.to_thread(_inflate, body)
            try:
                msg = json.loads(body.decode("utf-8"))
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise ProtocolError(f"invalid frame body: {e}") from e
            if not isinstance(msg, dict) or msg.get("type") not in MESSAGE_TYPES:
                raise ProtocolError(f"invalid message: {str(msg)[:200]}")
            if _FAULTS is not None:
                # "drop" on receive: pretend this frame was lost in flight
                # and keep reading (the sender believes it was delivered).
                if await _apply_frame_fault("proto.recv", msg, writer) == "drop":
                    continue
            return msg

    if timeout is None:
        return await _recv()
    return await asyncio.wait_for(_recv(), timeout)


def message(type_: str, payload: Any = None, msg_id: str | None = None, **extra) -> dict:
    out: dict[str, Any] = {"type": type_, "payload": payload}
    if msg_id is not None:
        out["msg_id"] = msg_id
    out.update(extra)
    return out


# -- batching ---------------------------------------------------------------

def batch(msgs: list[dict]) -> dict:
    """Wrap several messages into one frame (one syscall, one compression
    context).  Receivers expand with :func:`unbatch`."""
    return message("BATCH", {"messages": list(msgs)})


def unbatch(msg: dict) -> list[dict]:
    """Expand a BATCH message; any other message passes through as [msg]."""
    if msg.get("type") != "BATCH":
        return [msg]
    inner = (msg.get("payload") or {}).get("messages")
    if not isinstance(inner, list):
        raise ProtocolError("BATCH payload must carry a 'messages' list")
    for m in inner:
        if not isinstance(m, dict) or m.get("type") not in MESSAGE_TYPES or m.get("type") == "BATCH":
            raise ProtocolError(f"invalid batched message: {str(m)[:200]}")
    return inner


async def send_messages(writer: asyncio.StreamWriter, msgs: list[dict]) -> None:
    """Send several messages in one frame (BATCH) — message batching the
    reference planned at plan.md:285-288."""
    if len(msgs) == 1:
        await send_message(writer, msgs[0])
        return
    writer.write(await encode_on_loop(batch(msgs)))
    await writer.drain()
