"""Multi-host initialization: jax.distributed over DCN.

The reference "scales" by adding TCP workers to a star (SURVEY §2.4); a TPU
slice scales by joining processes into one global runtime —
``jax.distributed.initialize`` handshakes every host with the coordinator,
after which ``jax.devices()`` spans the slice and the same pjit/shard_map
programs run SPMD across hosts (collectives ride ICI within a slice, DCN
across slices).  Single-process use never calls this.
"""

from __future__ import annotations

import jax

from ..core.config import ClusterConfig
from ..core.observability import get_logger

log = get_logger("distributed")


def initialize_distributed(cfg: ClusterConfig) -> None:
    """Join this process into the multi-host runtime (no-op for 1 process)."""
    if cfg.num_processes <= 1:
        return
    if cfg.distributed_coordinator is None:
        raise ValueError(
            "cluster.distributed_coordinator (host:port) is required when "
            f"num_processes={cfg.num_processes}"
        )
    import os

    plat = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    if not plat or plat.startswith("cpu"):
        # Cross-process collectives on the CPU backend need an explicit
        # implementation (TPU/GPU bring their own fabric); without this any
        # multi-host psum/ppermute fails at compile time.  Empty platform
        # counts too: an accelerator-less host resolves to CPU implicitly,
        # and the setting only affects the CPU backend so it is harmless
        # when an accelerator is present.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    log.info(
        "jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
        cfg.distributed_coordinator, cfg.num_processes, cfg.process_id,
    )
    jax.distributed.initialize(
        coordinator_address=cfg.distributed_coordinator,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
