"""Prometheus-format HTTP exposition for the coordinator.

The reference specified a custom ``/metrics`` endpoint on the master plus
Prometheus scraping (implementation.md:34-37, :146-157) as future scope and
never built it.  Here it is a dependency-free asyncio HTTP/1.1 server:

- ``GET /metrics``  -> Prometheus text exposition (version 0.0.4)
- ``GET /healthz``  -> 200 ``ok`` (K8s liveness/readiness probe target)
- ``GET /status``   -> coordinator status as JSON (worker registry, shard
  assignment, queue depth — the REPL's ``status`` verb over HTTP)
"""

from __future__ import annotations

import asyncio
import json
from typing import Callable

from ..core.observability import METRICS, get_logger

log = get_logger("metrics_http")

_MAX_REQUEST_LINE = 8192


class MetricsServer:
    """Serves the process-wide METRICS registry plus an optional status
    callback over plain HTTP."""

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 9100,
        status_fn: Callable[[], dict] | None = None,
    ) -> None:
        self.host = host
        self.port = port
        self.status_fn = status_fn
        self._server: asyncio.base_events.Server | None = None
        self._conns: set[asyncio.StreamWriter] = set()

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        addr = self._server.sockets[0].getsockname()
        log.info("metrics endpoint on http://%s:%s/metrics", addr[0], addr[1])
        return addr[0], addr[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Python 3.12's wait_closed waits for in-flight handlers; kick
            # idle/slow connections loose so shutdown can't be held hostage.
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._conns.add(writer)

        async def read_phase() -> tuple[str, str] | None:
            line = await reader.readline()
            if len(line) > _MAX_REQUEST_LINE:
                await self._respond(writer, 414, "text/plain", "request line too long")
                return None
            parts = line.decode("latin-1", "replace").split()
            if len(parts) < 2:
                await self._respond(writer, 400, "text/plain", "bad request")
                return None
            # Drain headers (we never need them; the count cap plus the
            # outer deadline keep this bounded).
            for _ in range(100):
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
            else:
                await self._respond(writer, 431, "text/plain", "too many headers")
                return None
            return parts[0], parts[1]

        try:
            # One deadline for the whole read phase: an idle or trickling
            # client can hold a connection (and therefore wait_closed at
            # shutdown) for at most this long.  (wait_for, not
            # asyncio.timeout: pyproject allows Python 3.10.)
            parsed = await asyncio.wait_for(read_phase(), 10.0)
            if parsed is None:
                return
            method, path = parsed
            if method != "GET":
                await self._respond(writer, 405, "text/plain", "method not allowed")
            elif path == "/metrics":
                await self._respond(
                    writer,
                    200,
                    "text/plain; version=0.0.4; charset=utf-8",
                    METRICS.prometheus_text(),
                )
            elif path == "/healthz":
                await self._respond(writer, 200, "text/plain", "ok\n")
            elif path == "/status" and self.status_fn is not None:
                await self._respond(
                    writer, 200, "application/json", json.dumps(self.status_fn()) + "\n"
                )
            else:
                await self._respond(writer, 404, "text/plain", "not found")
        except (asyncio.TimeoutError, ConnectionError, OSError, ValueError):
            # ValueError: StreamReader raises it (via LimitOverrunError) when
            # a line exceeds the reader's own buffer limit.
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    async def _respond(
        self, writer: asyncio.StreamWriter, code: int, ctype: str, body: str
    ) -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed", 414: "URI Too Long",
                  431: "Request Header Fields Too Large"}.get(code, "")
        payload = body.encode()
        writer.write(
            (
                f"HTTP/1.1 {code} {reason}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            ).encode()
            + payload
        )
        await writer.drain()
