"""Coordinator: control-plane successor of the reference's MasterNode
(src/master/node.py:14-277), minus every socket-era defect.

Capabilities (with the reference parity point for each):
- worker registry with capabilities            (:164-170, :193-197)
- deadline-based liveness eviction             (fixes D10 — heartbeats were
                                                recorded at :199-201 but
                                                never evaluated)
- model lifecycle: plan (stage assignment) and place (instruct hosts to load
  their stages from the shard store)           (initialize/assign/distribute,
                                                :54-115 — but placement is
                                                device_put on the host, no
                                                tensor bytes on this socket)
- task queue with ids, timeouts, and retry/reassignment on worker failure
                                               (:117-138, :227-277; retry was
                                                planned at plan.md:430-436,
                                                never built; D8/D9 races gone
                                                — single-threaded asyncio)
- result aggregation: returns the generated text, not the first worker's raw
  partial                                      (fixes D9)
- metrics endpoint                             (implementation.md:34-37,
                                                planned only)
"""

from __future__ import annotations

import asyncio
import itertools
import time
import uuid
from dataclasses import dataclass, field
from typing import Any

from ..core.config import ClusterConfig
from ..core.observability import METRICS, get_logger
from . import protocol

log = get_logger("coordinator")


@dataclass
class WorkerInfo:
    worker_id: str
    capabilities: dict
    writer: asyncio.StreamWriter
    last_heartbeat: float
    status: str = "idle"  # idle | busy | dead
    shards: list[int] = field(default_factory=list)


@dataclass
class Task:
    task_id: str
    payload: dict
    future: asyncio.Future
    attempts: int = 0
    max_attempts: int = 3
    assigned_to: str | None = None


class Coordinator:
    def __init__(self, cfg: ClusterConfig | None = None,
                 faults: Any = None) -> None:
        # ``faults``: FaultPlane | None (runtime/faults.py).  Site
        # "coordinator.dispatch" (tag = task type): a "drop" rule models a
        # dispatch lost in flight — the task stays assigned and unanswered,
        # exercising the submitter-timeout / retry machinery without
        # wall-clock-killing a worker.
        self.cfg = cfg or ClusterConfig()
        self.faults = faults
        # The control-plane state below is confined to the asyncio event
        # loop (single-threaded by construction — the fix for the
        # reference's D8/D9 races).  graftlint's lock-discipline rule pins
        # the confinement: accesses must sit in async defs, or in sync
        # helpers explicitly annotated "# graftlint: holds(event-loop)"
        # (called only from coroutines / loop callbacks).
        self.workers: dict[str, WorkerInfo] = {}  # guarded-by: event-loop
        self.task_queue: asyncio.Queue[Task] = asyncio.Queue()
        self.tasks: dict[str, Task] = {}  # guarded-by: event-loop
        # shard -> worker_id
        self.shard_assignment: dict[int, str] = {}  # guarded-by: event-loop
        self.num_shards = 0
        self.store_dir: str | None = None
        self._server: asyncio.base_events.Server | None = None
        self._metrics_server = None
        self._bg: list[asyncio.Task] = []
        self._counter = itertools.count()

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._handle_connection, self.cfg.coordinator_host, self.cfg.coordinator_port
        )
        addr = self._server.sockets[0].getsockname()
        self._bg.append(asyncio.create_task(self._liveness_loop()))
        self._bg.append(asyncio.create_task(self._dispatch_loop()))
        if self.cfg.metrics_port is not None:
            from .metrics_http import MetricsServer

            self._metrics_server = MetricsServer(
                self.cfg.coordinator_host, self.cfg.metrics_port, status_fn=self.status
            )
            try:
                await self._metrics_server.start()
            except OSError:
                # A half-started coordinator must not leak its control socket
                # and background tasks when the metrics port can't bind.
                self._metrics_server = None
                await self.stop()
                raise
        log.info("coordinator listening on %s:%s", addr[0], addr[1])
        return addr[0], addr[1]

    @property
    def metrics_port(self) -> int | None:
        return self._metrics_server.bound_port if self._metrics_server else None

    async def stop(self) -> None:
        if self._metrics_server is not None:
            await self._metrics_server.stop()
        for t in self._bg:
            t.cancel()
        for w in list(self.workers.values()):
            w.writer.close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    # -- connection handling ----------------------------------------------

    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        worker_id: str | None = None
        try:
            while True:
                frame = await protocol.receive_message(reader, writer=writer)
                for msg in protocol.unbatch(frame):
                    worker_id = await self._handle_message(msg, writer, worker_id)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except protocol.ProtocolError as e:
            log.warning("protocol error from %s: %s", worker_id, e)
        finally:
            info = self.workers.get(worker_id) if worker_id else None
            # Only evict if this connection still owns the registration — a
            # worker that re-registered under a stable id (new connection)
            # must not be evicted when its stale connection finally closes.
            if info is not None and info.writer is writer:
                await self._evict(worker_id, reason="connection closed")
            writer.close()

    async def _handle_message(
        self, msg: dict, writer: asyncio.StreamWriter, worker_id: str | None
    ) -> str | None:
        mtype = msg["type"]
        payload = msg.get("payload") or {}
        if mtype == "REGISTER":
            worker_id = payload.get("worker_id") or f"worker-{next(self._counter)}"
            prior = self.workers.get(worker_id)
            if prior is not None and prior.writer is not writer:
                # Same stable id on a fresh connection (host restart):
                # replace the registration and drop the stale socket.
                prior.writer.close()
            self.workers[worker_id] = WorkerInfo(
                worker_id=worker_id,
                capabilities=payload.get("capabilities", {}),
                writer=writer,
                last_heartbeat=time.monotonic(),
            )
            METRICS.set_gauge("coordinator.workers", len(self.workers))
            log.info("registered %s caps=%s", worker_id, payload.get("capabilities"))
            await protocol.send_message(
                writer,
                protocol.message(
                    "REGISTER_ACK",
                    {"worker_id": worker_id, "heartbeat_interval_s": self.cfg.heartbeat_interval_s},
                ),
            )
            # A registration whose id already has assigned shards is a rejoin
            # (stale-connection replacement, or a post-eviction comeback when
            # no survivor could take the orphans): the fresh process has
            # nothing loaded, so re-place its assignment — without this,
            # shard_assignment routes generates at an empty worker.
            shards = sorted(
                s for s, w in self.shard_assignment.items() if w == worker_id
            )
            if shards and self.store_dir is not None:
                self._bg.append(
                    asyncio.create_task(self._place_on(worker_id, shards))
                )
            if prior is not None and prior.writer is not writer:
                # Tasks in flight on the dead connection will never answer.
                for task in list(self.tasks.values()):
                    if task.assigned_to == worker_id and not task.future.done():
                        await self._retry(
                            task, reason=f"worker {worker_id} re-registered"
                        )
        elif mtype == "HEARTBEAT":
            if worker_id in self.workers:
                self.workers[worker_id].last_heartbeat = time.monotonic()
        elif mtype == "RESULT":
            task_id = msg.get("msg_id")
            task = self.tasks.get(task_id)
            # The sender is done either way — a late reply (task already
            # timed out and popped) must still free the worker.
            if worker_id in self.workers:
                self.workers[worker_id].status = "idle"
            if task is not None and not task.future.done():
                task.future.set_result(payload)
                METRICS.inc("coordinator.tasks_completed")
        elif mtype == "ERROR":
            task_id = msg.get("msg_id")
            task = self.tasks.get(task_id)
            log.warning("worker %s error on %s: %s", worker_id, task_id, payload)
            if worker_id in self.workers:
                self.workers[worker_id].status = "idle"
            if task is not None and not task.future.done():
                await self._retry(task, reason=str(payload))
        elif mtype == "GET_STATUS":
            await protocol.send_message(
                writer,
                protocol.message("RESULT", self.status(), msg_id=msg.get("msg_id")),
            )
        elif mtype == "GET_METRICS":
            await protocol.send_message(
                writer,
                protocol.message("RESULT", METRICS.snapshot(), msg_id=msg.get("msg_id")),
            )
        else:
            log.warning("unhandled message type %s", mtype)
            if msg.get("msg_id") is not None:
                await protocol.send_message(
                    writer,
                    protocol.message(
                        "ERROR", {"error": f"unsupported command {mtype}"},
                        msg_id=msg["msg_id"],
                    ),
                )
        return worker_id

    # -- liveness (fixes D10) ---------------------------------------------

    async def _liveness_loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.heartbeat_interval_s / 2)
            now = time.monotonic()
            for wid, info in list(self.workers.items()):
                if now - info.last_heartbeat > self.cfg.heartbeat_timeout_s:
                    await self._evict(wid, reason="heartbeat timeout")

    async def _evict(self, worker_id: str, reason: str) -> None:
        info = self.workers.pop(worker_id, None)
        if info is None:
            return
        log.warning("evicting %s (%s)", worker_id, reason)
        METRICS.set_gauge("coordinator.workers", len(self.workers))
        METRICS.inc("coordinator.evictions")
        # Close the connection so the worker *sees* the eviction (EOF) and can
        # exit or reconnect — otherwise it heartbeats into the void forever.
        info.writer.close()
        # Reassign its shards to survivors and requeue its in-flight tasks.
        orphaned = sorted(
            s for s, w in self.shard_assignment.items() if w == worker_id
        )
        if orphaned and self.workers:
            for s in orphaned:
                del self.shard_assignment[s]
            self._bg.append(asyncio.create_task(self._reassign_orphans(orphaned)))
        elif orphaned:
            # No survivor can take the orphans: keep the assignment pointing
            # at the dead id.  Pinned dispatch already tolerates an absent
            # worker (requeue-with-delay), and a stable-id rejoin re-places
            # exactly this set (REGISTER handler); rebalance() also fixes it.
            log.warning(
                "no survivors for %s's shards %s; keeping assignment pending "
                "rejoin or rebalance", worker_id, orphaned,
            )
        for task in list(self.tasks.values()):
            if task.assigned_to == worker_id and not task.future.done():
                await self._retry(task, reason=f"worker {worker_id} evicted")

    async def _retry(self, task: Task, reason: str) -> None:
        task.assigned_to = None
        if task.attempts >= task.max_attempts:
            if not task.future.done():
                task.future.set_exception(
                    RuntimeError(f"task {task.task_id} failed after "
                                 f"{task.attempts} attempts: {reason}")
                )
            METRICS.inc("coordinator.tasks_failed")
            return
        METRICS.inc("coordinator.tasks_retried")
        await self.task_queue.put(task)

    # -- model lifecycle ---------------------------------------------------

    def _capacity(self, info: WorkerInfo) -> float:
        """Assignment weight from the worker's advertised capabilities.
        The reference recorded capabilities (:193-197) but never used them
        (SURVEY §2.2 'capacity-aware ... assignment' was plan-only)."""
        caps = info.capabilities or {}
        w = caps.get("capacity") or caps.get("memory_gb") or caps.get("num_devices") or 1
        return max(float(w), 1e-9)

    # graftlint: holds(event-loop)
    def _balanced_assign(
        self, shards: list[int], load: dict[str, float] | None = None
    ) -> dict[int, str]:
        """Greedy capacity-weighted balancing: each shard goes to the worker
        with the lowest (projected load / capacity) ratio."""
        workers = sorted(self.workers)
        load = dict(load or {w: 0.0 for w in workers})
        weight = {w: self._capacity(self.workers[w]) for w in workers}
        out: dict[int, str] = {}
        for s in shards:
            w = min(workers, key=lambda w_: ((load.get(w_, 0.0) + 1) / weight[w_], w_))
            out[s] = w
            load[w] = load.get(w, 0.0) + 1
        return out

    # graftlint: holds(event-loop)  (REPL/CLI callers run it via the loop)
    def plan_shards(
        self,
        num_shards: int,
        store_dir: str | None = None,
        policy: str = "capacity",
    ) -> dict[int, str]:
        """Assign store shards to registered workers.

        policy='round_robin' reproduces the reference's only strategy
        (src/master/node.py:93-102); 'capacity' (default) weights the
        per-worker shard count by advertised capacity — with equal
        capabilities it degenerates to the same balanced split."""
        if not self.workers:
            raise RuntimeError("no workers registered")
        self.num_shards = num_shards
        self.store_dir = store_dir
        workers = sorted(self.workers)
        if policy == "round_robin":
            self.shard_assignment = {
                s: workers[s % len(workers)] for s in range(num_shards)
            }
        elif policy == "capacity":
            self.shard_assignment = self._balanced_assign(list(range(num_shards)))
        else:
            raise ValueError(f"unknown policy {policy!r}; round_robin|capacity")
        return dict(self.shard_assignment)

    async def _place_on(
        self, wid: str, shards: list[int], timeout: float | None = None
    ) -> Any:
        """Tell one worker its (new) shard set — PLACE_SHARDS, or
        UNLOAD_SHARDS when it lost everything — and sync bookkeeping."""
        try:
            if shards:
                reply = await self.submit(
                    "PLACE_SHARDS",
                    {"store_dir": self.store_dir, "shards": sorted(shards)},
                    worker_id=wid,
                    timeout=timeout,
                )
            else:
                reply = await self.submit("UNLOAD_SHARDS", {}, worker_id=wid, timeout=timeout)
        except (RuntimeError, asyncio.TimeoutError) as e:
            log.warning("placement on %s failed: %s", wid, e)
            return {"error": str(e)}
        info = self.workers.get(wid)  # may have been evicted meanwhile
        if info is None:
            return {"error": f"worker {wid} evicted during placement"}
        info.shards = sorted(shards)
        return reply

    async def _reassign_orphans(self, orphaned: list[int]) -> None:
        """Dynamic reassignment (plan.md:423-428, never built in the
        reference): move an evicted worker's shards onto survivors —
        capacity-weighted against their current load — and re-place them
        from the store."""
        try:
            if not self.workers:
                # Last worker died before this task ran.  num_shards is
                # intact, so a later plan_shards/rebalance rebuilds the map.
                log.warning(
                    "no survivors to take orphaned shards %s; replan needed",
                    orphaned,
                )
                return
            load: dict[str, float] = {w: 0.0 for w in self.workers}
            for s, w in self.shard_assignment.items():
                if w in load:
                    load[w] += 1
            moved = self._balanced_assign(orphaned, load)
            self.shard_assignment.update(moved)
            METRICS.inc("coordinator.shards_reassigned", len(moved))
            log.info("reassigned orphaned shards %s", moved)
            if self.store_dir is None:
                return
            targets = sorted(set(moved.values()))
            await asyncio.gather(
                *(
                    self._place_on(
                        wid,
                        [s for s, w in self.shard_assignment.items() if w == wid],
                    )
                    for wid in targets
                )
            )
        except Exception:  # background task: never die silently
            log.exception("orphan reassignment failed")

    async def rebalance(self, policy: str = "capacity") -> dict[int, str]:
        """Recompute the whole assignment over the *current* pool (e.g. after
        workers joined) and re-place every worker whose shard set changed —
        including workers that lost all shards (they get UNLOAD_SHARDS)."""
        if not self.num_shards:
            raise RuntimeError("plan_shards first")
        old_sets: dict[str, list[int]] = {}
        for s, w in self.shard_assignment.items():
            old_sets.setdefault(w, []).append(s)
        self.plan_shards(self.num_shards, self.store_dir, policy)
        if self.store_dir is not None:
            new_sets: dict[str, list[int]] = {}
            for s, w in self.shard_assignment.items():
                new_sets.setdefault(w, []).append(s)
            changed = [
                w for w in set(old_sets) | set(new_sets)
                if w in self.workers
                and sorted(old_sets.get(w, [])) != sorted(new_sets.get(w, []))
            ]
            await asyncio.gather(
                *(self._place_on(wid, sorted(new_sets.get(wid, []))) for wid in changed)
            )
        return dict(self.shard_assignment)

    async def place_shards(self, timeout: float | None = None) -> dict[str, Any]:
        """Tell each worker which shards to load from the store (the worker
        reads from shared storage and device_puts; no tensor bytes here)."""
        if not self.shard_assignment:
            raise RuntimeError("plan_shards first")
        per_worker: dict[str, list[int]] = {}
        for shard, wid in self.shard_assignment.items():
            per_worker.setdefault(wid, []).append(shard)
        # Placements are independent — run them concurrently so N hosts
        # load/compile in ~1× wall-clock, not N×.
        replies = await asyncio.gather(
            *(self._place_on(w, s, timeout) for w, s in per_worker.items())
        )
        return dict(zip(per_worker, replies))

    # -- task submission ---------------------------------------------------

    async def submit(
        self,
        type_: str,
        payload: dict,
        worker_id: str | None = None,
        timeout: float | None = None,
    ) -> Any:
        """Submit a task; returns the worker's RESULT payload."""
        task = Task(
            task_id=uuid.uuid4().hex,
            payload={"type": type_, "body": payload, "worker_id": worker_id},
            future=asyncio.get_running_loop().create_future(),
        )
        self.tasks[task.task_id] = task
        await self.task_queue.put(task)
        try:
            return await asyncio.wait_for(
                task.future, timeout or self.cfg.task_timeout_s
            )
        finally:
            self.tasks.pop(task.task_id, None)

    async def generate(self, prompts: list[str], max_new_tokens: int | None = None,
                       timeout: float | None = None) -> Any:
        """The run_inference parity point: returns decoded text (not a raw
        partial, D9).  If the registered workers are controllers of one
        multi-process SPMD runtime, a single-worker dispatch would hang
        inside the first cross-process collective — route to generate_spmd.
        """
        if self._spmd_pool():
            return await self.generate_spmd(prompts, max_new_tokens, timeout)
        return await self.submit(
            "GENERATE", {"prompts": prompts, "max_new_tokens": max_new_tokens},
            timeout=timeout,
        )

    async def schedule_computation(
        self, payload: dict, worker_id: str | None = None,
        timeout: float | None = None,
    ) -> Any:
        """Dispatch one SCHEDULE_COMPUTATION task (the reference's generic
        compute verb, kept wire-compatible: workers serve it through the
        same engine path as GENERATE).  Declared-frame liveness is gated —
        graftflow's GF401 fails the tree when a MESSAGE_TYPES entry has
        handlers but no sender, which is exactly what this method closes."""
        return await self.submit("SCHEDULE_COMPUTATION", payload,
                                 worker_id=worker_id, timeout=timeout)

    async def shutdown_workers(self, timeout: float | None = None) -> dict:
        """Broadcast SHUTDOWN to every registered worker: each one answers
        ``{"ok": True}`` and stops its loops (graceful fleet retirement —
        the wire half the worker handler always implemented but nothing
        sent).  Returns {worker_id: reply-or-error-string}; a worker that
        died before answering reports its error instead of failing the
        whole broadcast."""
        wids = list(self.workers)
        results = await asyncio.gather(
            *(self.submit("SHUTDOWN", {}, worker_id=w, timeout=timeout)
              for w in wids),
            return_exceptions=True,
        )
        return {
            w: (f"{type(r).__name__}: {r}" if isinstance(r, BaseException)
                else r)
            for w, r in zip(wids, results)
        }

    # graftlint: holds(event-loop)
    def _spmd_pool(self) -> bool:
        """True when registered workers are controllers of one multi-process
        SPMD runtime (single-worker dispatch would hang in a collective)."""
        return any(
            w.capabilities.get("process_count", 1) > 1
            for w in self.workers.values()
        )

    async def generate_requests(
        self, requests: list[dict], timeout: float | None = None,
    ) -> Any:
        """Mixed-budget generation: each request is {"prompt": str,
        "max_new_tokens": int}.  Served with continuous batching
        (runtime/batcher.py) — per-request budgets, no head-of-line blocking
        — on single-device workers and on GSPMD data/tensor-parallel meshes,
        including multi-host SPMD pools (the batch is broadcast like
        generate_spmd and every process drives the same batcher in
        lockstep: scheduling state is host-mirrored numpy, identical
        everywhere).  Only pipelined / sequence-parallel meshes serve the
        grouped longest-budget fallback."""
        # Validate before dispatch so single-device (batcher) and mesh
        # (grouped) workers see only well-formed batches — the two engines
        # would otherwise diverge on how a bad request degrades.
        for i, r in enumerate(requests):
            prompt = r.get("prompt")
            if not isinstance(prompt, str) or not prompt:
                raise ValueError(
                    f"request {i}: prompt must be a non-empty string, got "
                    f"{prompt!r}"
                )
            n = r.get("max_new_tokens", 32)
            # bool is an int subclass: True would silently serve 1 token.
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                raise ValueError(
                    f"request {i}: max_new_tokens must be an int >= 1, got {n!r}"
                )
        payload = {"requests": requests}
        if self._spmd_pool():
            return await self._submit_spmd(payload, timeout)
        return await self.submit("GENERATE", payload, timeout=timeout)

    async def _submit_spmd(self, payload: dict, timeout: float | None) -> Any:
        wids = list(self.workers)
        unplaced = [w for w in wids if not self.workers[w].shards]
        if unplaced:
            raise RuntimeError(
                f"SPMD generate needs every worker placed; missing engine on "
                f"{unplaced} (run place_shards first)"
            )
        results = await asyncio.gather(
            *(
                self.submit("GENERATE", payload, worker_id=w, timeout=timeout)
                for w in wids
            ),
            return_exceptions=True,
        )
        errors = {
            w: r for w, r in zip(wids, results) if isinstance(r, BaseException)
        }
        if errors:
            raise RuntimeError(f"SPMD generate failed on {errors}")
        texts = {tuple(r["text"]) for r in results}
        if len(texts) != 1:
            raise RuntimeError(
                f"SPMD generate disagreement across {len(wids)} workers: {texts}"
            )
        return results[0]

    async def generate_spmd(
        self, prompts: list[str], max_new_tokens: int | None = None,
        timeout: float | None = None,
    ) -> Any:
        """Generate over a mesh that SPANS the worker processes (BASELINE
        config 5, multi-host).  SPMD semantics: every process participating
        in the global mesh must run the same jitted computation in lockstep,
        so the task is dispatched to ALL registered workers concurrently (a
        single-worker dispatch would deadlock inside the first collective).
        Each process computes — and returns — the identical full batch; the
        replies are consistency-checked and one is returned.

        Contrast with the reference's fan-out (src/master/node.py:256-269),
        where every worker also received the task, but each computed an
        unrelated partial on its own shard and no cross-worker reduction
        existed (defect D9 returned the first partial).
        """
        if not self.workers:
            raise RuntimeError("no workers registered")
        return await self._submit_spmd(
            {"prompts": prompts, "max_new_tokens": max_new_tokens}, timeout
        )

    async def _dispatch_loop(self) -> None:
        while True:
            task = await self.task_queue.get()
            if task.future.done():
                if task.future.cancelled():
                    # Submitter gave up (wait_for timeout) while the task was
                    # still queued — surface it as a failure, not silence.
                    METRICS.inc("coordinator.tasks_failed")
                continue
            wid = task.payload.get("worker_id")
            if wid and wid not in self.workers:
                # Pinned worker is absent — it may reconnect and re-register
                # under the same id (a heartbeat blip).  Requeue after a
                # delay *without* blocking this loop (other queued tasks keep
                # dispatching); the submitter's wait_for timeout bounds the
                # wait (a cancelled future is dropped at the top of this
                # loop).  Pin-waits are not dispatches, so they don't consume
                # task.attempts.
                loop = asyncio.get_running_loop()
                loop.call_later(0.2, self.task_queue.put_nowait, task)
                continue
            info = self.workers.get(wid) if wid else self._pick_worker()
            if info is None:
                # no worker (yet): brief backoff then requeue
                await asyncio.sleep(0.2)
                await self.task_queue.put(task)
                continue
            task.attempts += 1
            task.assigned_to = info.worker_id
            info.status = "busy"
            if self.faults is not None:
                # defer_stall: the dispatch loop runs ON the event loop —
                # a stall rule is awaited here, never slept (sleeping
                # would freeze heartbeat handling and every other task).
                rule = self.faults.fire("coordinator.dispatch",
                                        tag=task.payload["type"],
                                        defer_stall=True)
                if rule is not None and rule.action in ("delay", "stall"):
                    await asyncio.sleep(rule.arg or 0.0)
                if rule is not None and rule.action == "drop":
                    # The dispatch vanished in flight: task stays assigned
                    # and unanswered until the submitter's timeout fires.
                    continue
            try:
                await protocol.send_message(
                    info.writer,
                    protocol.message(
                        task.payload["type"], task.payload["body"], msg_id=task.task_id
                    ),
                )
                METRICS.inc("coordinator.tasks_dispatched")
            except (ConnectionError, OSError) as e:
                await self._evict(info.worker_id, reason=f"send failed: {e}")

    # graftlint: holds(event-loop)
    def _pick_worker(self) -> WorkerInfo | None:
        idle = [w for w in self.workers.values() if w.status == "idle"]
        if idle:
            return min(idle, key=lambda w: w.worker_id)
        alive = list(self.workers.values())
        return alive[0] if alive else None

    # -- introspection -----------------------------------------------------

    # graftlint: holds(event-loop)  (served by the asyncio MetricsServer)
    def status(self) -> dict:
        return {
            "workers": {
                wid: {
                    "capabilities": w.capabilities,
                    "status": w.status,
                    "shards": w.shards,
                    "heartbeat_age_s": round(time.monotonic() - w.last_heartbeat, 2),
                }
                for wid, w in self.workers.items()
            },
            "num_shards": self.num_shards,
            "shard_assignment": {str(k): v for k, v in self.shard_assignment.items()},
            "queued_tasks": self.task_queue.qsize(),
        }
