"""Host runner: control-plane successor of the reference's WorkerNode
(src/worker/node.py:34-301).

Parity map:
- register with device capabilities      (:101-121; capabilities here come
                                          from jax.devices, not torch.cuda)
- command handler                        (:189-261) — PLACE_SHARDS loads
                                          params from the shard store and
                                          device_puts them (LOAD_SHARD's
                                          role without tensor bytes on the
                                          socket), GENERATE runs the real
                                          decode loop (RUN_INFERENCE's role
                                          with an actual transformer)
- heartbeat loop                         (:263-276; single asyncio task, no
                                          REQ-socket write race, D7)
- connect retry with backoff             (:130-136)
"""

from __future__ import annotations

import asyncio
from typing import Any

import jax

from ..core.config import ClusterConfig, MeshConfig, RuntimeConfig
from ..core.observability import METRICS, get_logger
from . import protocol

log = get_logger("worker")


def device_capabilities() -> dict:
    devs = jax.devices()
    return {
        "platform": devs[0].platform,
        "num_devices": len(devs),
        "device_kind": getattr(devs[0], "device_kind", "unknown"),
        # >1 means this worker is one controller of a multi-process SPMD
        # runtime: GENERATE must then be dispatched to ALL workers at once
        # (Coordinator.generate routes to generate_spmd on this signal).
        "process_count": jax.process_count(),
    }


class WorkerHost:
    """Connects to the coordinator, executes control commands against the
    local engine."""

    def __init__(
        self,
        coordinator_host: str,
        coordinator_port: int,
        cfg: ClusterConfig | None = None,
        rt: RuntimeConfig | None = None,
        engine_factory: Any = None,  # (store_dir, shards, rt) -> engine-like
        mesh_cfg: MeshConfig | None = None,
        faults: Any = None,  # FaultPlane | None (runtime/faults.py): sites
        #   worker.heartbeat (drop a beat), worker.handle (crash a command
        #   handler), worker.result (drop/sever the reply) — deterministic
        #   stand-ins for process death in the cluster fault tests
    ) -> None:
        self.cfg = cfg or ClusterConfig()
        self.rt = rt or RuntimeConfig()
        self.mesh_cfg = mesh_cfg
        self.faults = faults
        self.host = coordinator_host
        self.port = coordinator_port
        self.engine_factory = engine_factory or self._default_engine_factory
        self.engine = None
        self.worker_id: str | None = None
        self.loaded_shards: list[int] = []
        self._tasks: list[asyncio.Task] = []
        self._stop = asyncio.Event()

    # -- default engine: shard store -> InferenceEngine --------------------

    def _default_engine_factory(self, store_dir: str, shards: list[int], rt: RuntimeConfig):
        """Engine over this host's local devices.  With a >1-device
        ``mesh_cfg`` (Config.mesh) the model serves mesh-parallel: weights
        are staged over 'pipe' / sharded over 'model' and placed by
        ``device_put`` — the reference's "split one model across workers"
        contract (src/master/node.py:84-115) realized as device placement.
        Otherwise the full model is reconstructed single-device; the shard
        assignment then expresses coordinator bookkeeping (which host
        answers for which shards), not residency."""
        from ..runtime.engine import InferenceEngine

        mesh_parallel = self.mesh_cfg is not None and self.mesh_cfg.num_devices > 1
        if not mesh_parallel:
            log.info(
                "assigned shards %s; single-device engine loads the full "
                "model regardless (mesh mode shards residency)", shards,
            )
        return InferenceEngine.from_store(
            store_dir, rt=rt, mesh_cfg=self.mesh_cfg if mesh_parallel else None
        )

    # -- lifecycle ---------------------------------------------------------

    async def run(self) -> None:
        """Connect (with retry), register, serve until stopped."""
        reader, writer = await self._connect_with_retry()
        try:
            await protocol.send_message(
                writer,
                protocol.message(
                    "REGISTER",
                    {"capabilities": device_capabilities(), "worker_id": self.worker_id},
                ),
            )
            ack = await protocol.receive_message(reader, timeout=10.0,
                                                 writer=writer)
            if ack["type"] != "REGISTER_ACK":
                raise protocol.ProtocolError(f"expected REGISTER_ACK, got {ack['type']}")
            self.worker_id = ack["payload"]["worker_id"]
            interval = ack["payload"].get(
                "heartbeat_interval_s", self.cfg.heartbeat_interval_s
            )
            log.info("registered as %s", self.worker_id)
            hb = asyncio.create_task(self._heartbeat_loop(writer, interval))
            self._tasks.append(hb)
            try:
                await self._serve(reader, writer)
            except (asyncio.IncompleteReadError, ConnectionError):
                log.info("coordinator connection closed")
        finally:
            for t in self._tasks:
                t.cancel()
            writer.close()

    def stop(self) -> None:
        self._stop.set()

    async def _connect_with_retry(self):
        last_err: Exception | None = None
        for attempt in range(self.cfg.connect_max_retries):
            try:
                return await asyncio.open_connection(self.host, self.port)
            except OSError as e:
                last_err = e
                log.warning(
                    "connect to %s:%s failed (%s); retry %d/%d in %.1fs",
                    self.host, self.port, e, attempt + 1,
                    self.cfg.connect_max_retries, self.cfg.connect_retry_s,
                )
                await asyncio.sleep(self.cfg.connect_retry_s)
        raise ConnectionError(
            f"could not reach coordinator at {self.host}:{self.port}"
        ) from last_err

    async def _heartbeat_loop(self, writer: asyncio.StreamWriter, interval: float) -> None:
        while not self._stop.is_set():
            await asyncio.sleep(interval)
            if self.faults is not None:
                # defer_stall: event-loop site — stall rules are awaited,
                # never slept (a blocking sleep would wedge every
                # coroutine this worker runs, the serve loop included).
                rule = self.faults.fire("worker.heartbeat", defer_stall=True)
                if rule is not None and rule.action in ("delay", "stall"):
                    await asyncio.sleep(rule.arg or 0.0)
                if rule is not None and rule.action == "drop":
                    # Deterministic liveness fault: the worker stays alive
                    # but its heartbeats stop — the coordinator's deadline
                    # eviction must fire (the path D10 left untested).
                    continue
            try:
                await protocol.send_message(writer, protocol.message("HEARTBEAT", {}))
            except (ConnectionError, OSError):
                return

    # -- command handling --------------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        while not self._stop.is_set():
            frame = await protocol.receive_message(reader, writer=writer)
            for msg in protocol.unbatch(frame):
                msg_id = msg.get("msg_id")
                try:
                    result = await self._handle(msg)
                    if msg_id is not None:
                        if self.faults is not None:
                            rule = self.faults.fire("worker.result",
                                                    tag=msg["type"],
                                                    defer_stall=True)
                            if rule is not None \
                                    and rule.action in ("delay", "stall"):
                                await asyncio.sleep(rule.arg or 0.0)
                            if rule is not None and rule.action == "drop":
                                continue  # reply lost in flight
                            if rule is not None and rule.action == "close":
                                # Die exactly at the answer: the coordinator
                                # sees EOF, evicts, and must retry the task
                                # on a survivor — deterministically.
                                writer.close()
                                raise ConnectionResetError(
                                    "fault injection: worker died before "
                                    "replying"
                                )
                        await protocol.send_message(
                            writer, protocol.message("RESULT", result, msg_id=msg_id)
                        )
                except ConnectionError:
                    # The stream is dead (peer gone or injected close) —
                    # an ERROR reply could never be delivered; let run()'s
                    # connection handling end this worker.
                    raise
                except Exception as e:  # report, don't die (coordinator retries)
                    log.exception("command %s failed", msg["type"])
                    if msg_id is not None:
                        # Counted refusal (graftflow GF402): the ERROR
                        # reply is the coordinator's retry trigger — it
                        # must leave a metric trail, not just a log line.
                        METRICS.inc("worker.errors")
                        await protocol.send_message(
                            writer,
                            protocol.message("ERROR", {"error": str(e)}, msg_id=msg_id),
                        )

    async def _handle(self, msg: dict) -> Any:
        mtype = msg["type"]
        payload = msg.get("payload") or {}
        if self.faults is not None:
            # "raise" here surfaces as an ERROR reply -> coordinator retry:
            # the deterministic task-failure fault.  defer_stall: this is
            # an event-loop site — a stall rule is awaited, not slept.
            rule = self.faults.fire("worker.handle", tag=mtype,
                                    defer_stall=True)
            if rule is not None and rule.action in ("delay", "stall"):
                await asyncio.sleep(rule.arg or 0.0)
        if mtype == "PLACE_SHARDS":
            store_dir = payload["store_dir"]
            shards = payload["shards"]
            # Blocking load + compile off the event loop.
            self.engine = await asyncio.to_thread(
                self.engine_factory, store_dir, shards, self.rt
            )
            self.loaded_shards = shards
            # Report what the built engine actually is, not what the config
            # asked for — a custom engine_factory may ignore mesh_cfg.
            pm = getattr(self.engine, "parallel", None)
            resident = (
                f"mesh({dict(pm.mesh.shape)})" if pm is not None else "full-model"
            )
            return {"loaded": shards, "resident": resident}
        if mtype == "UNLOAD_SHARDS":
            self.engine = None
            unloaded, self.loaded_shards = self.loaded_shards, []
            return {"unloaded": unloaded}
        if mtype in ("GENERATE", "SCHEDULE_COMPUTATION"):
            if self.engine is None:
                raise RuntimeError("no model placed (PLACE_SHARDS first)")
            if "requests" in payload:
                return await asyncio.to_thread(
                    self._generate_requests, payload["requests"]
                )
            prompts = payload["prompts"]
            res = await asyncio.to_thread(
                self.engine.generate_text, prompts, payload.get("max_new_tokens")
            )
            return {
                "text": res.text,
                "generated_tokens": res.generated_tokens,
                "seconds": res.seconds,
                "tokens_per_second": res.tokens_per_second,
            }
        if mtype == "SHUTDOWN":
            self.stop()
            return {"ok": True}
        raise protocol.ProtocolError(f"unhandled command {mtype}")

    def _generate_requests(self, requests: list[dict]) -> dict:
        """Mixed-budget batch (GENERATE with a ``requests`` list): served via
        continuous batching — per-request budgets, short replies don't wait
        for long ones — on single-device engines AND on GSPMD data/tensor-
        parallel meshes, multi-host included (runtime/batcher.py shards the
        KV cache and host-mirrors the scheduling state so every process
        stays in lockstep).  Only pipelined / sequence-parallel meshes,
        whose decode schedules manage their own batching, fall back to one
        grouped batch at the longest budget."""
        import time as _time

        t0 = _time.perf_counter()
        prompts = [r["prompt"] for r in requests]
        budgets = [int(r.get("max_new_tokens", 32)) for r in requests]
        pm = getattr(self.engine, "parallel", None)
        # Batcher: single-device engines and GSPMD dp/tp meshes — including
        # meshes SPANNING processes: the scheduling state lives as host
        # numpy mirrors fed to every process's jit as replicated inputs, so
        # all hosts drive identical admit/decode sequences (pinned by the
        # 2-process mixed-budget leg of tests/cluster/test_multihost.py).
        # Only pipelined / sequence-parallel meshes, whose decode schedules
        # manage their own batching, take the grouped fallback.
        batcher_ok = hasattr(self.engine, "continuous_batcher") and (
            pm is None or not (pm.pipelined or pm.seq_parallel)
        )
        if batcher_ok:
            # engine.continuous_batcher rounds the slot count up to divide
            # the mesh 'data' axis, so the default serves any dp shape.
            batcher = self.engine.continuous_batcher()
            rids = [
                batcher.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, budgets)
            ]
            results = batcher.run()
            tok = self.engine.tokenizer
            texts = [tok.decode(results[r]) for r in rids]
            n_gen = sum(len(results[r]) for r in rids)
        else:
            res = self.engine.generate_text(prompts, max(budgets))
            # Grouped fallback decodes max(budgets) for every row; honor each
            # request's own budget — and stop at the row's EOS, never the
            # post-EOS pad tail — so text AND the throughput accounting match
            # the batcher branch's basis exactly.
            tok = self.engine.tokenizer

            def _emitted(row, n):
                row = list(row[:n])
                eos = getattr(tok, "eos_id", None)
                if eos is not None and eos in row:
                    return row[: row.index(eos) + 1]
                return row

            rows = [_emitted(row, n) for row, n in zip(res.tokens, budgets)]
            texts = [tok.decode(row) for row in rows]
            n_gen = sum(len(row) for row in rows)
        dt = _time.perf_counter() - t0
        return {
            "text": texts,
            "generated_tokens": n_gen,
            "seconds": dt,
            "tokens_per_second": n_gen / max(dt, 1e-9),
        }
