"""Replica router: health-aware placement with EXACT failover over a
fleet of independent serving replicas (cluster/fleet.py).

PRs 2-3 made one engine crash-safe and overload-safe; this tier makes the
SERVICE replica-safe.  N full server/batcher stacks (each with its own
PR-2 supervisor, watchdog, and overload plane) sit behind one HTTP front
door that:

- **Places health-aware.**  Candidates are the replicas the fleet's
  ``/healthz`` probes currently call routable.  Among them, placement
  follows PREFIX AFFINITY first: the router hashes the request's prompt
  with the same chained page digests the automatic prefix cache uses
  (``PrefixCache.page_digests``), and a replica that recently served the
  longest matching page-run gets the request — its pool already holds
  those pages, so admission prefills only the suffix.  A sticky replica
  substantially hotter than the least-loaded one is skipped (affinity
  must not defeat load balancing); everything else goes LEAST COMMITTED
  first, by the router's own token-mass accounting (prompt + budget per
  in-flight request, the same estimate the server's cost gate uses).
- **Fails over EXACTLY.**  A replica dying (connection reset), wedging
  past its watchdog (probe 503 -> fleet aborts its in-flight proxies), or
  partitioning mid-request fails the upstream leg.  If ZERO payload bytes
  reached the client, the request is re-sent VERBATIM (same body bytes) to
  another healthy replica — at temperature 0 the re-decode is
  token-identical, the same recompute-is-exact contract the PR-2
  supervisor pinned in-process, now one level up.  Retries are bounded
  (``max_failover_retries``); exhaustion answers 503 + ``Retry-After``
  with a structured ``engine_error``.  If bytes HAD streamed, the deltas
  cannot be retracted: the stream ends with a structured ``engine_error``
  event — the mailbox contract, mirrored at the fleet tier.  (SSE
  responses hold the client's headers until the first upstream payload
  byte, so "zero-streamed" stays decidable per request.)
- **Sheds like the replicas do.**  A replica's own structured 429/503
  (cost gate, queue full, queue-deadline shed — type ``overloaded_error``)
  passes through untouched WITH its ``Retry-After``; an infrastructure 503
  (draining / unhealthy gate) is a placement mistake and fails over
  instead.  No routable replica at all answers 503 + ``Retry-After``.

Rolling drain/respawn and replica-scoped chaos (``replica.crash`` /
``replica.stall`` / ``replica.partition``) live with the fleet; the
router's own injection site is ``router.place`` (tag = chosen replica;
``drop`` vetoes the choice).  Everything here is event-loop confined —
the router owns no engine thread.
"""

from __future__ import annotations

import asyncio
import json
import time

from ..core.observability import METRICS, get_logger
from .batcher import PrefixCache
# One definition of the HTTP front-door limits/reasons/error shape for
# both tiers — the router must shed/parse exactly like the replicas do.
from .server import (
    _MAX_BODY, _MAX_HEADERS, _MAX_REQUEST_LINE, _REASONS, _err_body,
)

log = get_logger("router")


class _UpstreamFailed(Exception):
    """One upstream leg failed (connection error, abort, infrastructure
    503).  Whether the request may fail over is the caller's decision,
    keyed on how many payload bytes already reached the client."""


class _Inflight:
    """One proxied request's registration on a replica handle: the fleet
    sets ``abort`` when the replica stops being trustworthy; ``streamed``
    flips once payload bytes reached the client (the point of no return
    for failover)."""

    __slots__ = ("abort", "streamed")

    def __init__(self) -> None:
        self.abort = asyncio.Event()
        self.streamed = False


class ReplicaRouter:
    """HTTP front door over a :class:`cluster.fleet.ReplicaFleet`."""

    def __init__(
        self,
        fleet,
        host: str = "127.0.0.1",
        port: int = 0,
        tokenizer=None,  # for prompt hashing/cost on text prompts
        page_size: int = 64,  # affinity block size — match the replicas'
        max_failover_retries: int = 2,
        affinity_max: int = 4096,  # digest -> replica entries kept (LRU)
        # Affinity yields to load balance once the sticky replica's
        # committed mass exceeds spill_factor * least-loaded + request.
        spill_factor: float = 2.0,
        faults=None,
    ) -> None:
        self.fleet = fleet
        self.host = host
        self.port = port
        self.tokenizer = tokenizer
        self.page_size = page_size
        self.max_failover_retries = max_failover_retries
        self.affinity_max = affinity_max
        self.spill_factor = spill_factor
        self.faults = faults
        # digest -> replica name, most-recently-used last; event-loop
        # confined like every router/fleet structure (no engine thread
        # ever touches it).
        from collections import OrderedDict

        self._affinity: "OrderedDict[bytes, str]" = OrderedDict()
        self._server: asyncio.base_events.Server | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._conns: set[asyncio.StreamWriter] = set()

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        addr = self._server.sockets[0].getsockname()
        log.info("router fronting %d replica(s) on http://%s:%s",
                 len(self.fleet.replicas), addr[0], addr[1])
        return addr[0], addr[1]

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()

    # -- placement ---------------------------------------------------------

    def _digests(self, prompt_ids: list[int] | None) -> list[bytes]:
        """Chained page digests of the prompt's FULL pages, capped one
        page short (the replica-side cache caps hits the same way)."""
        if not prompt_ids or self.page_size <= 0:
            return []
        n = max(0, (len(prompt_ids) - 1) // self.page_size)
        return PrefixCache.page_digests(prompt_ids, self.page_size, n)

    def _place(self, digests: list[bytes], est_tokens: int,
               exclude: set) -> "object | None":
        """Pick a replica: prefix affinity on the longest known digest run,
        spilling to least-committed when the sticky replica runs hot; the
        ``router.place`` fault site (tag = choice) can veto a pick.
        Returns None when no routable replica remains."""
        now = self._loop.time()
        cands = [h for h in self.fleet.replicas
                 if h.routable(now) and h.name not in exclude]
        while cands:
            pick, hit = None, False
            for d in reversed(digests):  # longest cached run first
                name = self._affinity.get(d)
                if name is None:
                    continue
                h = next((c for c in cands if c.name == name), None)
                if h is not None:
                    pick, hit = h, True
                    break
            least = min(cands, key=lambda h: (h.committed_tokens, h.name))
            if pick is None:
                pick = least
            elif (pick.committed_tokens
                  > self.spill_factor * least.committed_tokens + est_tokens):
                pick, hit = least, False  # affinity must not defeat balance
            if self.faults is not None:
                rule = self.faults.fire("router.place", tag=pick.name)
                if rule is not None and rule.action == "drop":
                    cands = [c for c in cands if c.name != pick.name]
                    continue
            METRICS.inc("router.placements")
            if hit:
                METRICS.inc("router.affinity_hits")
            return pick
        return None

    def _record_affinity(self, digests: list[bytes], name: str) -> None:
        for d in digests:
            self._affinity[d] = name
            self._affinity.move_to_end(d)
        while len(self._affinity) > self.affinity_max:
            self._affinity.popitem(last=False)

    def _estimate(self, req: dict, chat: bool) -> tuple[list[int] | None, int]:
        """(prompt token ids or None, estimated prompt+budget token mass).
        Pure best-effort — bad fields fall back to coarse estimates and
        the replica's own validation answers the client."""
        ids: list[int] | None = None
        try:
            if chat:
                msgs = req.get("messages")
                text = " ".join(
                    m.get("content", "") for m in msgs
                ) if isinstance(msgs, list) else ""
                if self.tokenizer is not None and text:
                    ids = self.tokenizer.encode(text)
                n_prompt = len(ids) if ids is not None else len(text) // 4
            else:
                prompt = req.get("prompt")
                if isinstance(prompt, list):
                    ids = [t for t in prompt if isinstance(t, int)]
                    n_prompt = len(ids)
                elif isinstance(prompt, str) and self.tokenizer is not None:
                    ids = self.tokenizer.encode(prompt)
                    n_prompt = len(ids)
                else:
                    n_prompt = len(prompt) // 4 if isinstance(prompt, str) else 0
            budget = req.get(
                "max_completion_tokens" if chat else "max_tokens", 16)
            budget = budget if isinstance(budget, int) \
                and not isinstance(budget, bool) and budget > 0 else 16
        except (TypeError, AttributeError):
            return None, 16
        return ids, n_prompt + budget

    # -- the proxy core ----------------------------------------------------

    async def _proxy(self, writer, method: str, path: str, body: bytes,
                     chat: bool) -> None:
        try:
            req = json.loads(body or b"{}")
            req = req if isinstance(req, dict) else {}
        except json.JSONDecodeError:
            req = {}  # the replica answers the 400; placement needs no parse
        prompt_ids, est = self._estimate(req, chat)
        digests = self._digests(prompt_ids)
        payload = (
            f"{method} {path} HTTP/1.1\r\nHost: replica\r\n"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode() + body
        METRICS.inc("router.requests")
        tried: set[str] = set()
        attempts = 0
        t_fail: float | None = None
        while True:
            h = self._place(digests, est, exclude=tried)
            if h is None:
                if attempts:
                    # The request actually FAILED on a replica and no
                    # healthy candidate remains: that is an engine
                    # failure (the documented exhaustion contract), not
                    # ordinary overload.
                    await self._exhausted(
                        writer, attempts,
                        f"request failed on {attempts} replica(s) and no "
                        "healthy replica remains; retry later",
                    )
                else:
                    await self._shed(writer, "no healthy replica available")
                return
            rec = _Inflight()
            h.inflight.add(rec)
            h.committed_tokens += est
            METRICS.set_gauge(
                f"router.committed_tokens.{h.name}", h.committed_tokens
            )
            self._record_affinity(digests, h.name)
            try:
                await self._forward(writer, h, payload, rec)
                if t_fail is not None:
                    # Failover recovery latency: failure observed ->
                    # re-placed request fully answered.
                    METRICS.observe(
                        "router.failover_seconds",
                        time.perf_counter() - t_fail,
                    )
                return
            except _UpstreamFailed as e:
                if rec.streamed:
                    # Deltas already reached the client — the PR-2
                    # mailbox contract one level up: structured
                    # engine_error, never a silent truncation.
                    METRICS.inc("router.failed_streamed")
                    await self._stream_error(writer)
                    return
                tried.add(h.name)
                attempts += 1
                if t_fail is None:
                    t_fail = time.perf_counter()
                METRICS.inc("router.failovers")
                log.warning(
                    "replica %s failed zero-streamed request (%s); "
                    "failover attempt %d", h.name, e, attempts,
                )
                if attempts > self.max_failover_retries:
                    await self._exhausted(
                        writer, attempts,
                        f"request failed on {attempts} replica(s); "
                        "retry later",
                    )
                    return
            finally:
                h.inflight.discard(rec)
                h.committed_tokens -= est
                METRICS.set_gauge(
                    f"router.committed_tokens.{h.name}", h.committed_tokens
                )

    async def _up(self, awaitable, rec: _Inflight):
        """Await one upstream read, racing the replica's abort signal —
        the fleet sets it when the replica dies, wedges past the watchdog,
        partitions, or drains out from under us."""
        read_t = asyncio.ensure_future(awaitable)
        abort_t = asyncio.ensure_future(rec.abort.wait())
        try:
            done, _ = await asyncio.wait(
                {read_t, abort_t}, return_when=asyncio.FIRST_COMPLETED
            )
        finally:
            abort_t.cancel()
        if read_t not in done:
            read_t.cancel()
            try:
                await read_t
            except (Exception, asyncio.CancelledError):
                pass
            raise _UpstreamFailed("replica became unhealthy mid-request")
        try:
            return read_t.result()
        except (ConnectionError, OSError, EOFError,
                asyncio.IncompleteReadError) as e:
            raise _UpstreamFailed(f"{type(e).__name__}: {e}") from e

    async def _forward(self, writer, h, payload: bytes,
                       rec: _Inflight) -> None:
        """One upstream leg.  Raises :class:`_UpstreamFailed` when the
        replica failed us; client-side socket errors propagate as-is
        (they must never trigger a failover re-send)."""
        now = self._loop.time()
        if not h.reachable(now) or rec.abort.is_set():
            raise _UpstreamFailed("replica unreachable")
        try:
            up_r, up_w = await asyncio.open_connection(h.host, h.port)
        except (ConnectionError, OSError) as e:
            raise _UpstreamFailed(f"connect: {e}") from e
        try:
            up_w.write(payload)
            await self._up(up_w.drain(), rec)
            status_line = await self._up(up_r.readline(), rec)
            try:
                status = int(status_line.split()[1])
            except (IndexError, ValueError) as e:
                raise _UpstreamFailed("bad upstream status line") from e
            raw_head = [status_line]
            headers: dict[str, str] = {}
            for _ in range(_MAX_HEADERS):
                line = await self._up(up_r.readline(), rec)
                raw_head.append(line)
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1", "replace").partition(":")
                headers[name.strip().lower()] = value.strip()
            head = b"".join(raw_head)
            if "text/event-stream" in headers.get("content-type", ""):
                # SSE: forward incrementally.  The client's headers are
                # HELD until the first upstream payload byte, so a replica
                # dying pre-first-token still fails over exactly.
                first = True
                while True:
                    chunk = await self._up(up_r.read(65536), rec)
                    if not chunk:
                        if first:
                            raise _UpstreamFailed("stream died before data")
                        return
                    if first:
                        writer.write(head)
                        first = False
                    rec.streamed = True
                    writer.write(chunk)
                    await writer.drain()
            clen = headers.get("content-length")
            if clen is not None:
                body = await self._up(up_r.readexactly(int(clen)), rec)
            else:
                body = await self._up(up_r.read(), rec)
            if status == 503 and b"overloaded_error" not in body:
                # Infrastructure 503 (draining / unhealthy gate): a
                # placement mistake, not an answer — fail over.  A
                # structured shed IS the replica's answer and passes
                # through with its Retry-After.
                raise _UpstreamFailed("replica not ready (503)")
            if status == 500 and (b"engine_error" in body
                                  or b"shutting down" in body):
                # Dead supervisor / replica mid-shutdown: nothing streamed
                # (buffered path), so the request is safe to re-place.
                raise _UpstreamFailed("replica engine dead (500)")
            writer.write(head + body)
            await writer.drain()
            rec.streamed = True
        finally:
            up_w.close()

    async def _stream_error(self, writer) -> None:
        """Terminate a partially-forwarded SSE stream with the structured
        mid-stream error event (the replica server's own idiom)."""
        try:
            writer.write(
                b"data: " + json.dumps(_err_body(
                    "replica failed mid-stream; partial output could not "
                    "be resumed", "engine_error",
                )).encode() + b"\n\n"
            )
            await writer.drain()
        except (ConnectionError, OSError):
            pass

    def _retry_after_s(self) -> int:
        """Coarse back-off hint: one tick when replicas are merely busy,
        scaling with how much of the fleet is unavailable."""
        now = self._loop.time() if self._loop is not None else 0.0
        total = max(1, len(self.fleet.replicas))
        down = sum(1 for h in self.fleet.replicas if not h.routable(now))
        return int(min(30, max(1, 1 + 4 * down * down / total)))

    async def _shed(self, writer, msg: str) -> None:
        await self._json(
            writer, 503, _err_body(msg, "overloaded_error"),
            headers={"Retry-After": str(self._retry_after_s())},
        )

    async def _exhausted(self, writer, attempts: int, msg: str) -> None:
        """Failover budget (or candidate pool) exhausted on a request that
        actually FAILED on >= 1 replica: structured, retryable
        ``engine_error`` + Retry-After."""
        METRICS.inc("router.retries_exhausted")
        await self._json(
            writer, 503, _err_body(msg, "engine_error"),
            headers={"Retry-After": str(self._retry_after_s())},
        )

    # -- HTTP plumbing -----------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            parsed = await asyncio.wait_for(
                self._read_request(writer, reader), 30.0
            )
            if parsed is None:
                return
            method, path, body = parsed
            await self._route(writer, method, path, body)
        except (asyncio.TimeoutError, ConnectionError, OSError, ValueError,
                EOFError):
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    async def _read_request(self, writer, reader):
        line = await reader.readline()
        if len(line) > _MAX_REQUEST_LINE:
            await self._plain(writer, 431, "request line too long")
            return None
        parts = line.decode("latin-1", "replace").split()
        if len(parts) < 2:
            await self._plain(writer, 400, "bad request")
            return None
        method, path = parts[0], parts[1]
        content_len = 0
        for _ in range(_MAX_HEADERS):
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            name, _, value = h.decode("latin-1", "replace").partition(":")
            hname = name.strip().lower()
            if hname == "content-length":
                try:
                    content_len = int(value.strip())
                except ValueError:
                    await self._plain(writer, 400, "bad content-length")
                    return None
            elif hname == "transfer-encoding":
                # Only Content-Length bodies are read (the replica server
                # enforces the same): a chunked POST would forward an
                # EMPTY body and surface as a misleading replica-side 400.
                await self._plain(writer, 501, "chunked bodies not supported")
                return None
        else:
            await self._plain(writer, 431, "too many headers")
            return None
        if content_len > _MAX_BODY:
            await self._plain(writer, 413, "body too large")
            return None
        body = await reader.readexactly(content_len) if content_len else b""
        return method, path, body

    async def _route(self, writer, method: str, path: str,
                     body: bytes) -> None:
        if method == "GET" and path == "/healthz":
            report = self.fleet.report()
            code = 200 if report["healthy"] > 0 else 503
            report["status"] = "ok" if code == 200 else "unhealthy"
            await self._json(writer, code, report, headers=(
                None if code == 200
                else {"Retry-After": str(self._retry_after_s())}
            ))
        elif method == "GET" and path == "/metrics":
            await self._respond(
                writer, 200, "text/plain; version=0.0.4; charset=utf-8",
                METRICS.prometheus_text().encode(),
            )
        elif method == "GET" and path == "/v1/models":
            await self._proxy(writer, method, path, b"", chat=False)
        elif method == "POST" and path in ("/v1/completions",
                                           "/v1/chat/completions"):
            await self._proxy(writer, method, path, body,
                              chat="chat" in path)
        elif method not in ("GET", "POST"):
            await self._plain(writer, 405, "method not allowed")
        else:
            await self._plain(writer, 404, "not found")

    async def _plain(self, writer, code: int, body: str) -> None:
        await self._respond(writer, code, "text/plain", body.encode())

    async def _json(self, writer, code: int, obj: dict,
                    headers: dict[str, str] | None = None) -> None:
        await self._respond(
            writer, code, "application/json",
            (json.dumps(obj) + "\n").encode(), headers=headers,
        )

    async def _respond(self, writer, code: int, ctype: str, payload: bytes,
                       headers: dict[str, str] | None = None) -> None:
        extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
        writer.write(
            (
                f"HTTP/1.1 {code} {_REASONS.get(code, '')}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"{extra}"
                "Connection: close\r\n\r\n"
            ).encode()
            + payload
        )
        await writer.drain()
