"""Replica router: health-aware placement with EXACT failover over a
fleet of independent serving replicas (cluster/fleet.py).

PRs 2-3 made one engine crash-safe and overload-safe; this tier makes the
SERVICE replica-safe.  N full server/batcher stacks (each with its own
PR-2 supervisor, watchdog, and overload plane) sit behind one HTTP front
door that:

- **Forwards bodies VERBATIM.**  The proxy ships the request's exact
  bytes to the chosen replica, so every per-request serving field —
  sampling knobs, penalties, priorities, and the constrained-decoding
  surface (``response_format`` / ``logit_bias`` / ``banned_tokens``,
  runtime/constrain.py) — passes through untouched and is validated
  where it is served (the replica's own 400-before-admission gate).
- **Places health-aware.**  Candidates are the replicas the fleet's
  ``/healthz`` probes currently call routable.  Among them, placement
  follows PREFIX AFFINITY first: the router hashes the request's prompt
  with the same chained page digests the automatic prefix cache uses
  (``PrefixCache.page_digests``), and a replica that recently served the
  longest matching page-run gets the request — its pool already holds
  those pages, so admission prefills only the suffix.  A sticky replica
  substantially hotter than the least-loaded one is skipped (affinity
  must not defeat load balancing); everything else goes LEAST COMMITTED
  first, by the router's own token-mass accounting (prompt + budget per
  in-flight request, the same estimate the server's cost gate uses).
- **Fails over EXACTLY.**  A replica dying (connection reset), wedging
  past its watchdog (probe 503 -> fleet aborts its in-flight proxies), or
  partitioning mid-request fails the upstream leg.  If ZERO payload bytes
  reached the client, the request is re-sent VERBATIM (same body bytes) to
  another healthy replica — at temperature 0 the re-decode is
  token-identical, the same recompute-is-exact contract the PR-2
  supervisor pinned in-process, now one level up.  Retries are bounded
  (``max_failover_retries``); exhaustion answers 503 + ``Retry-After``
  with a structured ``engine_error``.  If bytes HAD streamed, the deltas
  cannot be retracted: the stream ends with a structured ``engine_error``
  event — the mailbox contract, mirrored at the fleet tier.  (SSE
  responses hold the client's headers until the first upstream payload
  byte, so "zero-streamed" stays decidable per request.)
- **Sheds like the replicas do.**  A replica's own structured 429/503
  (cost gate, queue full, queue-deadline shed — type ``overloaded_error``)
  passes through untouched WITH its ``Retry-After``; an infrastructure 503
  (draining / unhealthy gate) is a placement mistake and fails over
  instead.  No routable replica at all answers 503 + ``Retry-After``.

- **Disaggregates prefill from decode** (``handoff=True``).  With a
  prefill tier in the fleet (replicas of role ``"prefill"``), a request
  whose prompt spans at least one full page is first handed to the
  least-loaded prefill replica (``POST /v1/prefill``): that replica runs
  the prompt through its own admission, exports the finished KV pages,
  and ships them to the chosen DECODE replica's KV listener over
  ``cluster/kv_transfer.py`` (verified, deadline'd, retried).  The decode
  replica's admission then prefix-cache-hits the imported pages and
  decodes immediately — a long prompt never stalls another request's
  decode tokens on the decode tier.  The DEGRADATION LADDER makes the
  handoff safe: a prefill replica crash/stall/partition mid-handoff, a
  digest mismatch, transfer-retry exhaustion, a handoff deadline, or an
  empty prefill tier all fall back to COLOCATED prefill — the request is
  forwarded to the decode replica verbatim, which prefills it itself,
  byte-exact either way (imported pages hold exactly the content their
  digests commit to; a miss just recomputes it).  Completions never
  place on prefill-role replicas.

Rolling drain/respawn and replica-scoped chaos (``replica.crash`` /
``replica.stall`` / ``replica.partition``) live with the fleet; the
router's own injection site is ``router.place`` (tag = chosen replica;
``drop`` vetoes the choice).  Everything here is event-loop confined —
the router owns no engine thread.
"""

from __future__ import annotations

import asyncio
import json
import time

from ..core.observability import METRICS, get_logger
from .batcher import PrefixCache
# One definition of the HTTP front-door limits/reasons/error shape for
# both tiers — the router must shed/parse exactly like the replicas do.
from .server import (
    _MAX_BODY, _MAX_HEADERS, _MAX_REQUEST_LINE, _REASONS, _err_body,
    valid_tenant_id,
)

log = get_logger("router")


class _UpstreamFailed(Exception):
    """One upstream leg failed (connection error, abort, infrastructure
    503).  Whether the request may fail over is the caller's decision,
    keyed on how many payload bytes already reached the client."""


class _Inflight:
    """One proxied request's registration on a replica handle: the fleet
    sets ``abort`` when the replica stops being trustworthy; ``streamed``
    flips once payload bytes reached the client (the point of no return
    for failover)."""

    __slots__ = ("abort", "streamed")

    def __init__(self) -> None:
        self.abort = asyncio.Event()
        self.streamed = False


class ReplicaRouter:
    """HTTP front door over a :class:`cluster.fleet.ReplicaFleet`."""

    def __init__(
        self,
        fleet,
        host: str = "127.0.0.1",
        port: int = 0,
        tokenizer=None,  # for prompt hashing/cost on text prompts
        page_size: int = 64,  # affinity block size — match the replicas'
        max_failover_retries: int = 2,
        affinity_max: int = 4096,  # digest -> replica entries kept (LRU)
        # Affinity yields to load balance once the sticky replica's
        # committed mass exceeds spill_factor * least-loaded + request.
        spill_factor: float = 2.0,
        faults=None,
        # Disaggregated prefill/decode: hand prompts to the fleet's
        # prefill tier and ship finished KV pages to the decode replica
        # before forwarding (module docstring).  ``handoff_deadline_s``
        # bounds the WHOLE prefill+transfer leg — past it the request
        # degrades to colocated prefill.
        handoff: bool = False,
        handoff_deadline_s: float = 15.0,
        kv_bits: int = 16,  # the replicas' pool width — page digests are
        #   salted by it (PrefixCache.page_digests), and router-side
        #   affinity/handoff digests must match the fleet's
    ) -> None:
        self.fleet = fleet
        self.host = host
        self.port = port
        self.tokenizer = tokenizer
        self.page_size = page_size
        self.kv_bits = kv_bits
        self.max_failover_retries = max_failover_retries
        self.affinity_max = affinity_max
        self.spill_factor = spill_factor
        self.faults = faults
        self.handoff = handoff
        self.handoff_deadline_s = handoff_deadline_s
        # digest -> (replica name, replica epoch), most-recently-used
        # last; event-loop confined like every router/fleet structure (no
        # engine thread ever touches it).  The epoch pins the entry to
        # ONE cache lifetime: a drained/respawned replica comes back with
        # a cold pool under a bumped epoch, so its stale entries read as
        # misses instead of steering traffic at a cache that no longer
        # holds the pages.
        from collections import OrderedDict

        self._affinity: "OrderedDict[bytes, tuple[str, int]]" = OrderedDict()
        self._server: asyncio.base_events.Server | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._conns: set[asyncio.StreamWriter] = set()

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        addr = self._server.sockets[0].getsockname()
        log.info("router fronting %d replica(s) on http://%s:%s",
                 len(self.fleet.replicas), addr[0], addr[1])
        return addr[0], addr[1]

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()

    # -- placement ---------------------------------------------------------

    def _digests(self, prompt_ids: list[int] | None) -> list[bytes]:
        """Chained page digests of the prompt's FULL pages, capped one
        page short (the replica-side cache caps hits the same way)."""
        if not prompt_ids or self.page_size <= 0:
            return []
        n = max(0, (len(prompt_ids) - 1) // self.page_size)
        return PrefixCache.page_digests(prompt_ids, self.page_size, n,
                                        kv_bits=self.kv_bits)

    def _affinity_lookup(self, d: bytes) -> str | None:
        """The replica a digest is sticky to — IF that replica's cache
        lifetime still matches.  An entry recorded against an older epoch
        (the replica drained/respawned since: fresh pool, cold cache) is
        dropped here, so stale affinity can never beat least-loaded
        placement."""
        got = self._affinity.get(d)
        if got is None:
            return None
        name, epoch = got
        h = self.fleet._by_name.get(name)
        if h is None or h.epoch != epoch:
            del self._affinity[d]
            return None
        return name

    def _place(self, digests: list[bytes], est_tokens: int,
               exclude: set) -> "object | None":
        """Pick a DECODE-CAPABLE replica (prefill-role replicas never
        serve completions): prefix affinity on the longest known digest
        run, spilling to least-committed when the sticky replica runs
        hot; the ``router.place`` fault site (tag = choice) can veto a
        pick.  Returns None when no routable replica remains."""
        now = self._loop.time()
        cands = [h for h in self.fleet.replicas
                 if h.routable(now) and h.name not in exclude
                 and h.role != "prefill"]
        while cands:
            pick, hit = None, False
            for d in reversed(digests):  # longest cached run first
                name = self._affinity_lookup(d)
                if name is None:
                    continue
                h = next((c for c in cands if c.name == name), None)
                if h is not None:
                    pick, hit = h, True
                    break
            least = min(cands, key=lambda h: (h.committed_tokens, h.name))
            if pick is None:
                pick = least
            elif (pick.committed_tokens
                  > self.spill_factor * least.committed_tokens + est_tokens):
                pick, hit = least, False  # affinity must not defeat balance
            if self.faults is not None:
                # defer_stall: placement runs on the event loop (inside
                # _proxy).  The site's documented action is 'drop' (veto);
                # a stall/delay rule is returned un-slept and ignored here
                # — this sync helper cannot await, and blocking would
                # freeze routing and failure detection at once.
                rule = self.faults.fire("router.place", tag=pick.name,
                                        defer_stall=True)
                if rule is not None and rule.action == "drop":
                    cands = [c for c in cands if c.name != pick.name]
                    continue
            METRICS.inc("router.placements")
            if hit:
                METRICS.inc("router.affinity_hits")
            return pick
        return None

    def _record_affinity(self, digests: list[bytes], h) -> None:
        for d in digests:
            self._affinity[d] = (h.name, h.epoch)
            self._affinity.move_to_end(d)
        while len(self._affinity) > self.affinity_max:
            self._affinity.popitem(last=False)

    def _estimate(self, req: dict, chat: bool) -> tuple[list[int] | None, int]:
        """(prompt token ids or None, estimated prompt+budget token mass).
        Pure best-effort — bad fields fall back to coarse estimates and
        the replica's own validation answers the client."""
        ids: list[int] | None = None
        try:
            if chat:
                msgs = req.get("messages")
                text = " ".join(
                    m.get("content", "") for m in msgs
                ) if isinstance(msgs, list) else ""
                if self.tokenizer is not None and text:
                    ids = self.tokenizer.encode(text)
                n_prompt = len(ids) if ids is not None else len(text) // 4
            else:
                prompt = req.get("prompt")
                if isinstance(prompt, list):
                    ids = [t for t in prompt if isinstance(t, int)]
                    n_prompt = len(ids)
                elif isinstance(prompt, str) and self.tokenizer is not None:
                    ids = self.tokenizer.encode(prompt)
                    n_prompt = len(ids)
                else:
                    n_prompt = len(prompt) // 4 if isinstance(prompt, str) else 0
            budget = req.get(
                "max_completion_tokens" if chat else "max_tokens", 16)
            budget = budget if isinstance(budget, int) \
                and not isinstance(budget, bool) and budget > 0 else 16
        except (TypeError, AttributeError):
            return None, 16
        return ids, n_prompt + budget

    # -- disaggregated prefill handoff -------------------------------------

    def _pick_prefill(self, exclude: set) -> "object | None":
        """Least-committed routable prefill-role replica (None = the
        prefill tier is empty, dead, or partitioned — serve colocated)."""
        now = self._loop.time()
        cands = [h for h in self.fleet.replicas
                 if h.routable(now) and h.role == "prefill"
                 and h.name not in exclude]
        if not cands:
            return None
        return min(cands, key=lambda h: (h.committed_tokens, h.name))

    def _handoff_fallback(self, reason: str, detail: str) -> bool:
        METRICS.inc("router.handoff_fallbacks")
        METRICS.inc(f"router.handoff_fallbacks.{reason}")
        log.warning("prefill handoff degraded to colocated (%s): %s",
                    reason, detail)
        return False

    async def _handoff(self, decode_h, prompt_ids: list[int] | None,
                       digests: list[bytes]) -> bool:
        """One prefill handoff for the request about to be forwarded to
        ``decode_h``: pick a prefill replica, POST it /v1/prefill (the
        decode replica's KV listener coordinates as the transfer target),
        and verify END-TO-END that the digests it shipped are a prefix of
        the digests THIS router computed from the prompt — a prefill-tier
        hashing bug must not poison the decode cache.  Returns True when
        pages landed; every failure (crash, stall past the deadline,
        partition, digest mismatch, retry exhaustion, no prefill tier,
        no KV listener) returns False — the caller serves the request
        colocated on the decode replica, byte-exact regardless."""
        import uuid

        if prompt_ids is None or decode_h.kv_port is None:
            return self._handoff_fallback(
                "no_kv_target",
                f"decode replica {decode_h.name} has no KV listener"
                if decode_h.kv_port is None else "prompt not tokenizable",
            )
        p = self._pick_prefill(exclude={decode_h.name})
        if p is None:
            return self._handoff_fallback(
                "no_prefill_replica", "prefill tier empty or unhealthy"
            )
        METRICS.inc("router.handoffs")
        transfer_id = uuid.uuid4().hex[:16]
        body = json.dumps({
            "prompt": list(prompt_ids),
            "kv_host": decode_h.host,
            "kv_port": decode_h.kv_port,
            "transfer_id": transfer_id,
        }).encode()
        t0 = time.perf_counter()
        # The prefill tier does prompt + 1 token of work — charging the
        # request's full decode budget would let a huge max_tokens field
        # steer prefill placement away from the replica doing the LEAST
        # prefill work.
        charge = len(prompt_ids) + 1
        p.committed_tokens += charge
        METRICS.set_gauge(
            f"router.committed_tokens.{p.name}", p.committed_tokens
        )
        try:
            out = await asyncio.wait_for(
                self._prefill_rpc(p, body), self.handoff_deadline_s
            )
        except asyncio.TimeoutError:
            return self._handoff_fallback(
                "timeout",
                f"prefill replica {p.name} exceeded the "
                f"{self.handoff_deadline_s:g}s handoff deadline",
            )
        except (ConnectionError, OSError, EOFError, ValueError, IndexError,
                asyncio.IncompleteReadError) as e:
            # Crash / partition / kill mid-handoff all surface here as a
            # severed or unreachable connection (an empty status line
            # from a half-dead socket parses as IndexError/ValueError).
            return self._handoff_fallback(
                "error", f"prefill replica {p.name}: "
                f"{type(e).__name__}: {e}",
            )
        finally:
            p.committed_tokens -= charge
            METRICS.set_gauge(
                f"router.committed_tokens.{p.name}", p.committed_tokens
            )
        status, resp = out
        if status != 200 or not isinstance(resp, dict):
            return self._handoff_fallback(
                "rejected", f"prefill replica {p.name} answered {status}"
            )
        if not resp.get("ok"):
            return self._handoff_fallback(
                "rejected",
                f"prefill replica {p.name}: "
                f"{resp.get('reason') or resp.get('error', 'rejected')}",
            )
        shipped = resp.get("digests") or []
        want = [d.hex() for d in digests[: len(shipped)]]
        if not shipped or shipped != want:
            # The transfer itself verified on the decode side, but it does
            # not commit to the prompt THIS router hashed: stale pages
            # under our digests would be worse than no pages.
            return self._handoff_fallback(
                "digest_mismatch",
                f"prefill replica {p.name} shipped {len(shipped)} page(s) "
                "whose digests diverge from the request's",
            )
        el = time.perf_counter() - t0
        METRICS.observe("router.handoff_seconds", el)
        METRICS.inc("router.handoff_bytes", int(resp.get("bytes", 0)))
        log.info(
            "handoff %s: %d page(s), %d token(s) prefilled on %s -> %s "
            "in %.1f ms (%d transfer attempt(s))", transfer_id,
            int(resp.get("pages", 0)), int(resp.get("tokens", 0)),
            p.name, decode_h.name, el * 1e3, int(resp.get("attempts", 1)),
        )
        return True

    async def _prefill_rpc(self, p, body: bytes) -> tuple[int, dict]:
        """POST /v1/prefill to a prefill replica; returns (status, JSON)."""
        reader, writer = await asyncio.open_connection(p.host, p.port)
        try:
            writer.write(
                f"POST /v1/prefill HTTP/1.1\r\nHost: router\r\n"
                f"Content-Length: {len(body)}\r\n\r\n".encode() + body
            )
            await writer.drain()
            status = int((await reader.readline()).split()[1])
            clen = 0
            for _ in range(_MAX_HEADERS):
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1", "replace").partition(":")
                if name.strip().lower() == "content-length":
                    clen = int(value.strip())
            raw = await reader.readexactly(clen) if clen else b""
            try:
                resp = json.loads(raw) if raw else {}
            except json.JSONDecodeError:
                resp = {}
            return status, resp if isinstance(resp, dict) else {}
        finally:
            writer.close()

    # -- the proxy core ----------------------------------------------------

    async def _proxy(self, writer, method: str, path: str, body: bytes,
                     chat: bool, tenant: str | None = None) -> None:
        try:
            req = json.loads(body or b"{}")
            req = req if isinstance(req, dict) else {}
        except json.JSONDecodeError:
            req = {}  # the replica answers the 400; placement needs no parse
        prompt_ids, est = self._estimate(req, chat)
        digests = self._digests(prompt_ids)
        # The X-Tenant header rides the re-built upstream request (bodies
        # forward verbatim, headers do not): the replica's tenant gate and
        # weighted-fair scheduler must see the same identity the client
        # sent.  A malformed id 400s HERE with the replica's own message —
        # rewriting it could collapse onto (and bill) a DIFFERENT tenant,
        # and the shared charset is header-safe by construction, so the
        # router cannot become a header-injection vector either way.
        tenant_line = ""
        if tenant:
            if not valid_tenant_id(tenant):
                await self._json(writer, 400, _err_body(
                    "'tenant' must be 1-64 chars of [A-Za-z0-9._-] "
                    "(X-Tenant header or body field)"
                ))
                return
            tenant_line = f"X-Tenant: {tenant}\r\n"
        payload = (
            f"{method} {path} HTTP/1.1\r\nHost: replica\r\n"
            f"{tenant_line}"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode() + body
        METRICS.inc("router.requests")
        tried: set[str] = set()
        attempts = 0
        t_fail: float | None = None
        while True:
            h = self._place(digests, est, exclude=tried)
            if h is None:
                if attempts:
                    # The request actually FAILED on a replica and no
                    # healthy candidate remains: that is an engine
                    # failure (the documented exhaustion contract), not
                    # ordinary overload.
                    await self._exhausted(
                        writer, attempts,
                        f"request failed on {attempts} replica(s) and no "
                        "healthy replica remains; retry later",
                    )
                else:
                    await self._shed(writer, "no healthy replica available")
                return
            rec = _Inflight()
            h.inflight.add(rec)
            h.committed_tokens += est
            METRICS.set_gauge(
                f"router.committed_tokens.{h.name}", h.committed_tokens
            )
            # Does the chosen replica already hold this prompt's full
            # page run (epoch-valid affinity — recorded whether the pages
            # arrived by handoff OR by a colocated prefill there)?  Then
            # shipping it again would only earn a "duplicate" ack for a
            # multi-MB transfer.  Read BEFORE recording this placement,
            # which would trivially satisfy the check.
            warm = bool(digests) and \
                self._affinity_lookup(digests[-1]) == h.name
            self._record_affinity(digests, h)
            try:
                if self.handoff and digests and method == "POST" \
                        and not chat:
                    # Disaggregated prefill: best-effort BY DESIGN — every
                    # failure mode inside degrades to colocated prefill on
                    # the decode replica; the verbatim forward below is
                    # identical either way (byte-exact both paths).  Chat
                    # requests skip the plane: the replica tokenizes them
                    # through its chat template, so router-side ids (and
                    # therefore the shipped digests) would never match
                    # the admission's — pages would import dead.
                    if warm:
                        METRICS.inc("router.handoff_skips")
                    else:
                        await self._handoff(h, prompt_ids, digests)
                await self._forward(writer, h, payload, rec)
                if t_fail is not None:
                    # Failover recovery latency: failure observed ->
                    # re-placed request fully answered.
                    METRICS.observe(
                        "router.failover_seconds",
                        time.perf_counter() - t_fail,
                    )
                return
            except _UpstreamFailed as e:
                if rec.streamed:
                    # Deltas already reached the client — the PR-2
                    # mailbox contract one level up: structured
                    # engine_error, never a silent truncation.
                    METRICS.inc("router.failed_streamed")
                    await self._stream_error(writer)
                    return
                tried.add(h.name)
                attempts += 1
                if t_fail is None:
                    t_fail = time.perf_counter()
                METRICS.inc("router.failovers")
                log.warning(
                    "replica %s failed zero-streamed request (%s); "
                    "failover attempt %d", h.name, e, attempts,
                )
                if attempts > self.max_failover_retries:
                    await self._exhausted(
                        writer, attempts,
                        f"request failed on {attempts} replica(s); "
                        "retry later",
                    )
                    return
            finally:
                h.inflight.discard(rec)
                h.committed_tokens -= est
                METRICS.set_gauge(
                    f"router.committed_tokens.{h.name}", h.committed_tokens
                )

    async def _up(self, awaitable, rec: _Inflight):
        """Await one upstream read, racing the replica's abort signal —
        the fleet sets it when the replica dies, wedges past the watchdog,
        partitions, or drains out from under us."""
        read_t = asyncio.ensure_future(awaitable)
        abort_t = asyncio.ensure_future(rec.abort.wait())
        try:
            done, _ = await asyncio.wait(
                {read_t, abort_t}, return_when=asyncio.FIRST_COMPLETED
            )
        finally:
            abort_t.cancel()
        if read_t not in done:
            read_t.cancel()
            try:
                await read_t
            except (Exception, asyncio.CancelledError):
                pass
            raise _UpstreamFailed("replica became unhealthy mid-request")
        try:
            return read_t.result()
        except (ConnectionError, OSError, EOFError,
                asyncio.IncompleteReadError) as e:
            raise _UpstreamFailed(f"{type(e).__name__}: {e}") from e

    async def _forward(self, writer, h, payload: bytes,
                       rec: _Inflight) -> None:
        """One upstream leg.  Raises :class:`_UpstreamFailed` when the
        replica failed us; client-side socket errors propagate as-is
        (they must never trigger a failover re-send)."""
        now = self._loop.time()
        if not h.reachable(now) or rec.abort.is_set():
            raise _UpstreamFailed("replica unreachable")
        try:
            up_r, up_w = await asyncio.open_connection(h.host, h.port)
        except (ConnectionError, OSError) as e:
            raise _UpstreamFailed(f"connect: {e}") from e
        try:
            up_w.write(payload)
            await self._up(up_w.drain(), rec)
            status_line = await self._up(up_r.readline(), rec)
            try:
                status = int(status_line.split()[1])
            except (IndexError, ValueError) as e:
                raise _UpstreamFailed("bad upstream status line") from e
            raw_head = [status_line]
            headers: dict[str, str] = {}
            for _ in range(_MAX_HEADERS):
                line = await self._up(up_r.readline(), rec)
                raw_head.append(line)
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1", "replace").partition(":")
                headers[name.strip().lower()] = value.strip()
            head = b"".join(raw_head)
            if "text/event-stream" in headers.get("content-type", ""):
                # SSE: forward incrementally.  The client's headers are
                # HELD until the first upstream payload byte, so a replica
                # dying pre-first-token still fails over exactly.
                first = True
                while True:
                    chunk = await self._up(up_r.read(65536), rec)
                    if not chunk:
                        if first:
                            raise _UpstreamFailed("stream died before data")
                        return
                    if first:
                        writer.write(head)
                        first = False
                    rec.streamed = True
                    writer.write(chunk)
                    await writer.drain()
            clen = headers.get("content-length")
            if clen is not None:
                body = await self._up(up_r.readexactly(int(clen)), rec)
            else:
                body = await self._up(up_r.read(), rec)
            if status == 503 and b"overloaded_error" not in body:
                # Infrastructure 503 (draining / unhealthy gate): a
                # placement mistake, not an answer — fail over.  A
                # structured shed IS the replica's answer and passes
                # through with its Retry-After.
                raise _UpstreamFailed("replica not ready (503)")
            if status == 500 and (b"engine_error" in body
                                  or b"shutting down" in body):
                # Dead supervisor / replica mid-shutdown: nothing streamed
                # (buffered path), so the request is safe to re-place.
                raise _UpstreamFailed("replica engine dead (500)")
            writer.write(head + body)
            await writer.drain()
            rec.streamed = True
        finally:
            up_w.close()

    async def _stream_error(self, writer) -> None:
        """Terminate a partially-forwarded SSE stream with the structured
        mid-stream error event (the replica server's own idiom)."""
        try:
            writer.write(
                b"data: " + json.dumps(_err_body(
                    "replica failed mid-stream; partial output could not "
                    "be resumed", "engine_error",
                )).encode() + b"\n\n"
            )
            await writer.drain()
        except (ConnectionError, OSError):
            pass

    def _retry_after_s(self) -> int:
        """Coarse back-off hint: one tick when replicas are merely busy,
        scaling with how much of the fleet is unavailable."""
        now = self._loop.time() if self._loop is not None else 0.0
        total = max(1, len(self.fleet.replicas))
        down = sum(1 for h in self.fleet.replicas if not h.routable(now))
        return int(min(30, max(1, 1 + 4 * down * down / total)))

    async def _shed(self, writer, msg: str) -> None:
        await self._json(
            writer, 503, _err_body(msg, "overloaded_error"),
            headers={"Retry-After": str(self._retry_after_s())},
        )

    async def _exhausted(self, writer, attempts: int, msg: str) -> None:
        """Failover budget (or candidate pool) exhausted on a request that
        actually FAILED on >= 1 replica: structured, retryable
        ``engine_error`` + Retry-After."""
        METRICS.inc("router.retries_exhausted")
        await self._json(
            writer, 503, _err_body(msg, "engine_error"),
            headers={"Retry-After": str(self._retry_after_s())},
        )

    # -- HTTP plumbing -----------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            parsed = await asyncio.wait_for(
                self._read_request(writer, reader), 30.0
            )
            if parsed is None:
                return
            method, path, body, tenant = parsed
            await self._route(writer, method, path, body, tenant)
        except (asyncio.TimeoutError, ConnectionError, OSError, ValueError,
                EOFError):
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    async def _read_request(self, writer, reader):
        line = await reader.readline()
        if len(line) > _MAX_REQUEST_LINE:
            await self._plain(writer, 431, "request line too long")
            return None
        parts = line.decode("latin-1", "replace").split()
        if len(parts) < 2:
            await self._plain(writer, 400, "bad request")
            return None
        method, path = parts[0], parts[1]
        content_len = 0
        tenant: str | None = None
        for _ in range(_MAX_HEADERS):
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            name, _, value = h.decode("latin-1", "replace").partition(":")
            hname = name.strip().lower()
            if hname == "content-length":
                try:
                    content_len = int(value.strip())
                except ValueError:
                    await self._plain(writer, 400, "bad content-length")
                    return None
            elif hname == "transfer-encoding":
                # Only Content-Length bodies are read (the replica server
                # enforces the same): a chunked POST would forward an
                # EMPTY body and surface as a misleading replica-side 400.
                await self._plain(writer, 501, "chunked bodies not supported")
                return None
            elif hname == "x-tenant":
                # Forwarded to the chosen replica (bodies are verbatim;
                # headers are re-built) — tenant QoS is decided there.
                tenant = value.strip()
        else:
            await self._plain(writer, 431, "too many headers")
            return None
        if content_len > _MAX_BODY:
            await self._plain(writer, 413, "body too large")
            return None
        body = await reader.readexactly(content_len) if content_len else b""
        return method, path, body, tenant

    async def _route(self, writer, method: str, path: str,
                     body: bytes, tenant: str | None = None) -> None:
        if method == "GET" and path == "/healthz":
            report = self.fleet.report()
            code = 200 if report["healthy"] > 0 else 503
            report["status"] = "ok" if code == 200 else "unhealthy"
            await self._json(writer, code, report, headers=(
                None if code == 200
                else {"Retry-After": str(self._retry_after_s())}
            ))
        elif method == "GET" and path == "/metrics":
            await self._respond(
                writer, 200, "text/plain; version=0.0.4; charset=utf-8",
                METRICS.prometheus_text().encode(),
            )
        elif method == "GET" and path == "/v1/models":
            await self._proxy(writer, method, path, b"", chat=False)
        elif method == "POST" and path in ("/v1/completions",
                                           "/v1/chat/completions"):
            await self._proxy(writer, method, path, body,
                              chat="chat" in path, tenant=tenant)
        elif method not in ("GET", "POST"):
            await self._plain(writer, 405, "method not allowed")
        else:
            await self._plain(writer, 404, "not found")

    async def _plain(self, writer, code: int, body: str) -> None:
        await self._respond(writer, code, "text/plain", body.encode())

    async def _json(self, writer, code: int, obj: dict,
                    headers: dict[str, str] | None = None) -> None:
        await self._respond(
            writer, code, "application/json",
            (json.dumps(obj) + "\n").encode(), headers=headers,
        )

    async def _respond(self, writer, code: int, ctype: str, payload: bytes,
                       headers: dict[str, str] | None = None) -> None:
        extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
        writer.write(
            (
                f"HTTP/1.1 {code} {_REASONS.get(code, '')}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"{extra}"
                "Connection: close\r\n\r\n"
            ).encode()
            + payload
        )
        await writer.drain()
