"""Replica router: health-aware placement with EXACT failover over a
fleet of independent serving replicas (cluster/fleet.py).

PRs 2-3 made one engine crash-safe and overload-safe; this tier makes the
SERVICE replica-safe.  N full server/batcher stacks (each with its own
PR-2 supervisor, watchdog, and overload plane) sit behind one HTTP front
door that:

- **Forwards bodies VERBATIM.**  The proxy ships the request's exact
  bytes to the chosen replica, so every per-request serving field —
  sampling knobs, penalties, priorities, and the constrained-decoding
  surface (``response_format`` / ``logit_bias`` / ``banned_tokens``,
  runtime/constrain.py) — passes through untouched and is validated
  where it is served (the replica's own 400-before-admission gate).
- **Places health-aware.**  Candidates are the replicas the fleet's
  ``/healthz`` probes currently call routable.  Among them, placement
  follows PREFIX AFFINITY first: the router hashes the request's prompt
  with the same chained page digests the automatic prefix cache uses
  (``PrefixCache.page_digests``), and a replica that recently served the
  longest matching page-run gets the request — its pool already holds
  those pages, so admission prefills only the suffix.  A sticky replica
  substantially hotter than the least-loaded one is skipped (affinity
  must not defeat load balancing); everything else goes LEAST COMMITTED
  first, by the router's own token-mass accounting (prompt + budget per
  in-flight request, the same estimate the server's cost gate uses).
- **Fails over EXACTLY.**  A replica dying (connection reset), wedging
  past its watchdog (probe 503 -> fleet aborts its in-flight proxies), or
  partitioning mid-request fails the upstream leg.  If ZERO payload bytes
  reached the client, the request is re-sent VERBATIM (same body bytes) to
  another healthy replica — at temperature 0 the re-decode is
  token-identical, the same recompute-is-exact contract the PR-2
  supervisor pinned in-process, now one level up.  Retries are bounded
  (``max_failover_retries``); exhaustion answers 503 + ``Retry-After``
  with a structured ``engine_error``.  If bytes HAD streamed, the deltas
  cannot be retracted: the stream ends with a structured ``engine_error``
  event — the mailbox contract, mirrored at the fleet tier.  (SSE
  responses hold the client's headers until the first upstream payload
  byte, so "zero-streamed" stays decidable per request.)
- **Sheds like the replicas do.**  A replica's own structured 429/503
  (cost gate, queue full, queue-deadline shed — type ``overloaded_error``)
  passes through untouched WITH its ``Retry-After``; an infrastructure 503
  (draining / unhealthy gate) is a placement mistake and fails over
  instead.  No routable replica at all answers 503 + ``Retry-After``.

- **Disaggregates prefill from decode** (``handoff=True``).  With a
  prefill tier in the fleet (replicas of role ``"prefill"``), a request
  whose prompt spans at least one full page is first handed to the
  least-loaded prefill replica (``POST /v1/prefill``): that replica runs
  the prompt through its own admission, exports the finished KV pages,
  and ships them to the chosen DECODE replica's KV listener over
  ``cluster/kv_transfer.py`` (verified, deadline'd, retried).  The decode
  replica's admission then prefix-cache-hits the imported pages and
  decodes immediately — a long prompt never stalls another request's
  decode tokens on the decode tier.  The DEGRADATION LADDER makes the
  handoff safe: a prefill replica crash/stall/partition mid-handoff, a
  digest mismatch, transfer-retry exhaustion, a handoff deadline, or an
  empty prefill tier all fall back to COLOCATED prefill — the request is
  forwarded to the decode replica verbatim, which prefills it itself,
  byte-exact either way (imported pages hold exactly the content their
  digests commit to; a miss just recomputes it).  Completions never
  place on prefill-role replicas.

Rolling drain/respawn and replica-scoped chaos (``replica.crash`` /
``replica.stall`` / ``replica.partition``) live with the fleet; the
router's own injection site is ``router.place`` (tag = chosen replica;
``drop`` vetoes the choice).  Everything here is event-loop confined —
the router owns no engine thread.
"""

from __future__ import annotations

import asyncio
import json
import time

from ..core.observability import METRICS, get_logger
from .batcher import PrefixCache
# One definition of the HTTP front-door limits/reasons/error shape for
# both tiers — the router must shed/parse exactly like the replicas do.
from .server import (
    _MAX_BODY, _MAX_HEADERS, _MAX_REQUEST_LINE, _REASONS,
    _TENANT_LEDGER_CAP, ANON_TENANT, _err_body, valid_tenant_id,
)

log = get_logger("router")


class _UpstreamFailed(Exception):
    """One upstream leg failed (connection error, abort, infrastructure
    503).  Whether the request may fail over is the caller's decision,
    keyed on how many payload bytes already reached the client."""


class _Inflight:
    """One proxied request's registration on a replica handle: the fleet
    sets ``abort`` when the replica stops being trustworthy; ``streamed``
    flips once payload bytes reached the client (the point of no return
    for failover)."""

    __slots__ = ("abort", "streamed")

    def __init__(self) -> None:
        self.abort = asyncio.Event()
        self.streamed = False


# Machine-readable transition system for the fleet tenant ledger — the
# protocol contract ``_ledger_retry_after`` / ``_ledger_charge`` /
# ``_ledger_refund`` implement, declared next to the code it models
# (PROTOCOL_MODELS["router.ledger"], runtime/faults.py).  ``python -m
# tools.graftmodel`` exhaustively explores every interleaving of four
# concurrent admissions composed with the declared router.ledger fault
# actions (exhaust / stall / drop) and checks the GM1 accounting laws on
# every reachable state: a charge on placement and only there, a refund
# on every failure edge and only there, the gated window never over
# quota, and a gate bypass ALWAYS metered by the replica backstop —
# never a silent unmetered path.  Request slot phases: 0 arrived,
# 1 placed+charged, 2 served (charge retained — tokens were consumed),
# 3 failed+refunded (no-replica / upstream >= 400 / failover
# exhaustion), 4 bypassed in flight (router.ledger:drop), 5 shed 429
# (never charged), 6 bypassed + served + backstop-metered.
LEDGER_MODEL = {
    "name": "router.ledger",
    "doc": "fleet tenant ledger: charge on placement, refund on failure, "
           "shed pre-placement, bypass metered by the gateway backstop",
    "params": {"QUOTA": 2},
    "state": {"r0": 0, "r1": 0, "r2": 0, "r3": 0,
              "charged": 0, "refunded": 0, "served": 0, "shed": 0,
              "bypassed": 0, "backstopped": 0, "stalled": 0},
    "actions": [
        {"name": "place0", "guard": "r0 == 0 and charged - refunded < QUOTA",
         "update": {"r0": "1", "charged": "charged + 1"}},
        {"name": "place1", "guard": "r1 == 0 and charged - refunded < QUOTA",
         "update": {"r1": "1", "charged": "charged + 1"}},
        {"name": "place2", "guard": "r2 == 0 and charged - refunded < QUOTA",
         "update": {"r2": "1", "charged": "charged + 1"}},
        {"name": "place3", "guard": "r3 == 0 and charged - refunded < QUOTA",
         "update": {"r3": "1", "charged": "charged + 1"}},
        {"name": "serve0", "guard": "r0 == 1",
         "update": {"r0": "2", "served": "served + 1"}},
        {"name": "serve1", "guard": "r1 == 1",
         "update": {"r1": "2", "served": "served + 1"}},
        {"name": "serve2", "guard": "r2 == 1",
         "update": {"r2": "2", "served": "served + 1"}},
        {"name": "serve3", "guard": "r3 == 1",
         "update": {"r3": "2", "served": "served + 1"}},
        {"name": "fail_refund0", "guard": "r0 == 1",
         "update": {"r0": "3", "refunded": "refunded + 1"}},
        {"name": "fail_refund1", "guard": "r1 == 1",
         "update": {"r1": "3", "refunded": "refunded + 1"}},
        {"name": "fail_refund2", "guard": "r2 == 1",
         "update": {"r2": "3", "refunded": "refunded + 1"}},
        {"name": "fail_refund3", "guard": "r3 == 1",
         "update": {"r3": "3", "refunded": "refunded + 1"}},
        {"name": "backstop_meter0", "guard": "r0 == 4",
         "update": {"r0": "6", "served": "served + 1",
                    "backstopped": "backstopped + 1"}},
        {"name": "backstop_meter1", "guard": "r1 == 4",
         "update": {"r1": "6", "served": "served + 1",
                    "backstopped": "backstopped + 1"}},
        {"name": "gate_resume", "guard": "stalled == 1",
         "update": {"stalled": "0"}},
    ],
    "faults": [
        {"name": "shed0", "site": "router.ledger", "action": "exhaust",
         "metric": "router.ledger.sheds",
         "guard": "r0 == 0", "update": {"r0": "5", "shed": "shed + 1"}},
        {"name": "shed1", "site": "router.ledger", "action": "exhaust",
         "metric": "router.ledger.sheds",
         "guard": "r1 == 0", "update": {"r1": "5", "shed": "shed + 1"}},
        {"name": "bypass0", "site": "router.ledger", "action": "drop",
         "metric": "router.ledger.bypasses",
         "guard": "r0 == 0",
         "update": {"r0": "4", "bypassed": "bypassed + 1"}},
        {"name": "bypass1", "site": "router.ledger", "action": "drop",
         "metric": "router.ledger.bypasses",
         "guard": "r1 == 0",
         "update": {"r1": "4", "bypassed": "bypassed + 1"}},
        {"name": "gate_stall", "site": "router.ledger", "action": "stall",
         "metric": "faults.fired.stall",
         "guard": "stalled == 0", "update": {"stalled": "1"}},
    ],
    "invariants": [
        {"rule": "GM1", "name": "charge-iff-placed",
         "expr": "charged == (1 <= r0 <= 3) + (1 <= r1 <= 3) "
                 "+ (1 <= r2 <= 3) + (1 <= r3 <= 3)"},
        {"rule": "GM1", "name": "refund-iff-failed",
         "expr": "refunded == (r0 == 3) + (r1 == 3) + (r2 == 3) "
                 "+ (r3 == 3)"},
        {"rule": "GM1", "name": "no-lost-refund",
         "expr": "refunded <= charged"},
        {"rule": "GM1", "name": "gated-window-bounded",
         "expr": "charged - refunded <= QUOTA"},
        {"rule": "GM1", "name": "bypass-always-backstopped",
         "expr": "backstopped == (r0 == 6) + (r1 == 6)"},
        {"rule": "GM1", "name": "served-counted-once",
         "expr": "served == (r0 == 2) + (r1 == 2) + (r2 == 2) + (r3 == 2) "
                 "+ (r0 == 6) + (r1 == 6)"},
    ],
    # Stuck only when every request reached a settled phase — a bypassed
    # request parked at 4 forever would be the silent unmetered path.
    "terminal": "r0 in (2, 3, 5, 6) and r1 in (2, 3, 5, 6) "
                "and r2 in (2, 3, 5, 6) and r3 in (2, 3, 5, 6)",
}


class ReplicaRouter:
    """HTTP front door over a :class:`cluster.fleet.ReplicaFleet`."""

    def __init__(
        self,
        fleet,
        host: str = "127.0.0.1",
        port: int = 0,
        tokenizer=None,  # for prompt hashing/cost on text prompts
        page_size: int = 64,  # affinity block size — match the replicas'
        max_failover_retries: int = 2,
        affinity_max: int = 4096,  # digest -> replica entries kept (LRU)
        # Affinity yields to load balance once the sticky replica's
        # committed mass exceeds spill_factor * least-loaded + request.
        spill_factor: float = 2.0,
        faults=None,
        # Disaggregated prefill/decode: hand prompts to the fleet's
        # prefill tier and ship finished KV pages to the decode replica
        # before forwarding (module docstring).  ``handoff_deadline_s``
        # bounds the WHOLE prefill+transfer leg — past it the request
        # degrades to colocated prefill.
        handoff: bool = False,
        handoff_deadline_s: float = 15.0,
        kv_bits: int = 16,  # the replicas' pool width — page digests are
        #   salted by it (PrefixCache.page_digests), and router-side
        #   affinity/handoff digests must match the fleet's
        # Fleet-wide tenant ledger: the router is the ONE admission-commit
        # point, so a tenant's token-rate quota holds at any fleet size
        # (elastic scale-up must not multiply it).  Same knobs and
        # semantics as the replica gateway's rate gate — which, behind
        # this ledger, should run as a LOOSE BACKSTOP (the server's
        # tenant_backstop_x) so a bypassed or drilled router gate still
        # never yields a silent unmetered path.  None disables the gate.
        tenant_weights: "dict[str, float] | None" = None,
        tenant_quota_tps: float | None = None,
        tenant_rate_window_s: float = 10.0,
        # Cross-replica KV reuse: on an affinity miss, pull the prompt's
        # cached page run from the sibling the digest directory says
        # holds it (over the checksummed KV_PAGES plane) instead of
        # re-prefilling; every failure degrades to local recompute.
        pull: bool = True,
        pull_deadline_s: float = 5.0,
    ) -> None:
        self.fleet = fleet
        self.host = host
        self.port = port
        self.tokenizer = tokenizer
        self.page_size = page_size
        self.kv_bits = kv_bits
        self.max_failover_retries = max_failover_retries
        self.affinity_max = affinity_max
        self.spill_factor = spill_factor
        self.faults = faults
        self.handoff = handoff
        self.handoff_deadline_s = handoff_deadline_s
        if tenant_quota_tps is not None and tenant_quota_tps <= 0:
            tenant_quota_tps = None  # the CLI/config "disable" spelling
        if tenant_rate_window_s <= 0:
            raise ValueError(
                f"tenant_rate_window_s must be > 0, got {tenant_rate_window_s}"
            )
        self.tenant_weights = dict(tenant_weights or {})
        self.tenant_default_weight = self.tenant_weights.pop("*", 1.0)
        self.tenant_quota_tps = tenant_quota_tps
        self.tenant_rate_window_s = tenant_rate_window_s
        self.pull = pull
        self.pull_deadline_s = pull_deadline_s
        # digest -> (replica name, replica epoch), most-recently-used
        # last; event-loop confined like every router/fleet structure (no
        # engine thread ever touches it).  The epoch pins the entry to
        # ONE cache lifetime: a drained/respawned replica comes back with
        # a cold pool under a bumped epoch, so its stale entries read as
        # misses instead of steering traffic at a cache that no longer
        # holds the pages.
        from collections import OrderedDict

        self._affinity: "OrderedDict[bytes, tuple[str, int]]" = OrderedDict()
        # The FLEET tenant ledger: trailing-window (ts, est) charges per
        # tenant, the same shape as the replica gateway's — but there is
        # exactly ONE of these per fleet, so what it admits is what the
        # fleet admits.  Charged after the gate passes, REFUNDED when the
        # request ends shed/failed without service (a shed must not burn
        # the tenant's window).  Cardinality-capped like the replica's
        # (_TENANT_LEDGER_CAP): ids are client-minted.
        self._tenant_window: "dict[str, object]" = {}  # guarded-by: event-loop
        self._server: asyncio.base_events.Server | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._conns: set[asyncio.StreamWriter] = set()

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        addr = self._server.sockets[0].getsockname()
        log.info("router fronting %d replica(s) on http://%s:%s",
                 len(self.fleet.replicas), addr[0], addr[1])
        return addr[0], addr[1]

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()

    # -- placement ---------------------------------------------------------

    def _digests(self, prompt_ids: list[int] | None) -> list[bytes]:
        """Chained page digests of the prompt's FULL pages, capped one
        page short (the replica-side cache caps hits the same way)."""
        if not prompt_ids or self.page_size <= 0:
            return []
        n = max(0, (len(prompt_ids) - 1) // self.page_size)
        return PrefixCache.page_digests(prompt_ids, self.page_size, n,
                                        kv_bits=self.kv_bits)

    def _affinity_lookup(self, d: bytes) -> str | None:
        """The replica a digest is sticky to — IF that replica's cache
        lifetime still matches.  An entry recorded against an older epoch
        (the replica drained/respawned since: fresh pool, cold cache) is
        dropped here, so stale affinity can never beat least-loaded
        placement."""
        got = self._affinity.get(d)
        if got is None:
            return None
        name, epoch = got
        h = self.fleet._by_name.get(name)
        if h is None or h.epoch != epoch:
            # Epoch mismatch = the replica drained/respawned since this
            # entry was recorded: its pool is cold, the entry is a lie.
            # This is also the digest DIRECTORY's self-invalidation (the
            # cross-replica pull plane reads the same map).
            del self._affinity[d]
            METRICS.inc("directory.stale_drops")
            return None
        return name

    def _place(self, digests: list[bytes], est_tokens: int,
               exclude: set) -> "object | None":
        """Pick a DECODE-CAPABLE replica (prefill-role replicas never
        serve completions): prefix affinity on the longest known digest
        run, spilling to least-committed when the sticky replica runs
        hot; the ``router.place`` fault site (tag = choice) can veto a
        pick.  Returns None when no routable replica remains."""
        now = self._loop.time()
        cands = [h for h in self.fleet.replicas
                 if h.routable(now) and h.name not in exclude
                 and h.role != "prefill"]
        while cands:
            pick, hit = None, False
            for d in reversed(digests):  # longest cached run first
                name = self._affinity_lookup(d)
                if name is None:
                    continue
                h = next((c for c in cands if c.name == name), None)
                if h is not None:
                    pick, hit = h, True
                    break
            least = min(cands, key=lambda h: (h.committed_tokens, h.name))
            if pick is None:
                pick = least
            elif (pick.committed_tokens
                  > self.spill_factor * least.committed_tokens + est_tokens):
                pick, hit = least, False  # affinity must not defeat balance
            if self.faults is not None:
                # defer_stall: placement runs on the event loop (inside
                # _proxy).  The site's documented action is 'drop' (veto);
                # a stall/delay rule is returned un-slept and ignored here
                # — this sync helper cannot await, and blocking would
                # freeze routing and failure detection at once.
                rule = self.faults.fire("router.place", tag=pick.name,
                                        defer_stall=True)
                if rule is not None and rule.action == "drop":
                    cands = [c for c in cands if c.name != pick.name]
                    continue
            METRICS.inc("router.placements")
            if hit:
                METRICS.inc("router.affinity_hits")
            return pick
        return None

    def _record_affinity(self, digests: list[bytes], h) -> None:
        for d in digests:
            self._affinity[d] = (h.name, h.epoch)
            self._affinity.move_to_end(d)
        while len(self._affinity) > self.affinity_max:
            self._affinity.popitem(last=False)

    def _estimate(self, req: dict, chat: bool) -> tuple[list[int] | None, int]:
        """(prompt token ids or None, estimated prompt+budget token mass).
        Pure best-effort — bad fields fall back to coarse estimates and
        the replica's own validation answers the client."""
        ids: list[int] | None = None
        try:
            if chat:
                msgs = req.get("messages")
                text = " ".join(
                    m.get("content", "") for m in msgs
                ) if isinstance(msgs, list) else ""
                if self.tokenizer is not None and text:
                    ids = self.tokenizer.encode(text)
                n_prompt = len(ids) if ids is not None else len(text) // 4
            else:
                prompt = req.get("prompt")
                if isinstance(prompt, list):
                    ids = [t for t in prompt if isinstance(t, int)]
                    n_prompt = len(ids)
                elif isinstance(prompt, str) and self.tokenizer is not None:
                    ids = self.tokenizer.encode(prompt)
                    n_prompt = len(ids)
                else:
                    n_prompt = len(prompt) // 4 if isinstance(prompt, str) else 0
            budget = req.get(
                "max_completion_tokens" if chat else "max_tokens", 16)
            budget = budget if isinstance(budget, int) \
                and not isinstance(budget, bool) and budget > 0 else 16
        except (TypeError, AttributeError):
            return None, 16
        return ids, n_prompt + budget

    # -- the fleet tenant ledger (the one admission-commit point) ----------

    def _tenant_weight(self, tenant: str) -> float:
        return self.tenant_weights.get(tenant, self.tenant_default_weight)

    def _tenant_allowance(self, tenant: str) -> float:
        """Token mass the tenant's trailing window may hold, FLEET-WIDE —
        the same weight x quota x window product the replica gateways
        compute, held once for all of them."""
        return (self._tenant_weight(tenant) * self.tenant_quota_tps
                * self.tenant_rate_window_s)

    # graftlint: holds(event-loop)
    def _ledger_retry_after(self, tenant: str, est: int,
                            forced: bool = False) -> int | None:
        """The fleet-ledger rate gate (loop thread only).  None = ``est``
        more tokens fit the tenant's window; else the PER-TENANT
        Retry-After walked off the FLEET ledger oldest-first — a promise
        about when this tenant's own fleet-wide charges age out, not a
        load guess.  ``forced`` is the ``router.ledger:exhaust`` drill."""
        import math

        win = self.tenant_rate_window_s
        allowed = self._tenant_allowance(tenant)
        now = time.perf_counter()
        ledger = self._tenant_window.get(tenant)
        if ledger:
            while ledger and ledger[0][0] <= now - win:
                ledger.popleft()
            if not ledger:  # fully aged out: drop the deque itself too
                del self._tenant_window[tenant]
                ledger = None
        used = sum(n for _, n in ledger) if ledger else 0
        if not forced and used + est <= allowed:
            return None
        room_needed = used + est - allowed
        freed = 0.0
        hint = win
        for ts, n in (ledger or ()):
            freed += n
            if freed >= room_needed:
                hint = ts + win - now
                break
        return int(min(60, max(1, math.ceil(hint))))

    # graftlint: holds(event-loop)
    def _ledger_charge(self, tenant: str, est: int) -> None:
        """Commit an admitted request's token mass to the fleet ledger
        (loop thread only) — charged once placement is about to happen,
        refunded by ``_ledger_refund`` if the request ends shed or failed
        without service."""
        from collections import deque

        if tenant not in self._tenant_window \
                and len(self._tenant_window) >= _TENANT_LEDGER_CAP:
            # Cardinality bound, exactly like the replica gateway's: age
            # every ledger first; ids still inside their window are
            # genuine concurrent tenants and stay.
            cutoff = time.perf_counter() - self.tenant_rate_window_s
            for t in list(self._tenant_window):
                d = self._tenant_window[t]
                while d and d[0][0] <= cutoff:
                    d.popleft()
                if not d:
                    del self._tenant_window[t]
        self._tenant_window.setdefault(tenant, deque()).append(
            (time.perf_counter(), est)
        )
        METRICS.inc("router.ledger.charges")
        METRICS.inc("router.ledger.charged_tokens", est)
        METRICS.set_gauge("router.ledger.tenants", len(self._tenant_window))

    # graftlint: holds(event-loop)
    def _ledger_refund(self, tenant: str, est: int) -> None:
        """Give a charge back (loop thread only): the request was shed or
        failed before any service — billed tokens that bought nothing
        would silently shrink the tenant's real quota.  Walks the
        tenant's ledger NEWEST-first (the refund undoes the charge just
        taken, not some hours-old admission)."""
        ledger = self._tenant_window.get(tenant)
        remaining = est
        while ledger and remaining > 0:
            ts, n = ledger.pop()
            if n > remaining:
                ledger.append((ts, n - remaining))
                remaining = 0
            else:
                remaining -= n
        if ledger is not None and not ledger:
            del self._tenant_window[tenant]
        METRICS.inc("router.ledger.refunds")
        METRICS.set_gauge("router.ledger.tenants", len(self._tenant_window))

    # -- disaggregated prefill handoff -------------------------------------

    def _pick_prefill(self, exclude: set) -> "object | None":
        """Least-committed routable prefill-role replica (None = the
        prefill tier is empty, dead, or partitioned — serve colocated)."""
        now = self._loop.time()
        cands = [h for h in self.fleet.replicas
                 if h.routable(now) and h.role == "prefill"
                 and h.name not in exclude]
        if not cands:
            return None
        return min(cands, key=lambda h: (h.committed_tokens, h.name))

    def _handoff_fallback(self, reason: str, detail: str) -> bool:
        METRICS.inc("router.handoff_fallbacks")
        METRICS.inc(f"router.handoff_fallbacks.{reason}")
        log.warning("prefill handoff degraded to colocated (%s): %s",
                    reason, detail)
        return False

    async def _handoff(self, decode_h, prompt_ids: list[int] | None,
                       digests: list[bytes]) -> bool:
        """One prefill handoff for the request about to be forwarded to
        ``decode_h``: pick a prefill replica, POST it /v1/prefill (the
        decode replica's KV listener coordinates as the transfer target),
        and verify END-TO-END that the digests it shipped are a prefix of
        the digests THIS router computed from the prompt — a prefill-tier
        hashing bug must not poison the decode cache.  Returns True when
        pages landed; every failure (crash, stall past the deadline,
        partition, digest mismatch, retry exhaustion, no prefill tier,
        no KV listener) returns False — the caller serves the request
        colocated on the decode replica, byte-exact regardless."""
        import uuid

        if prompt_ids is None or decode_h.kv_port is None:
            return self._handoff_fallback(
                "no_kv_target",
                f"decode replica {decode_h.name} has no KV listener"
                if decode_h.kv_port is None else "prompt not tokenizable",
            )
        p = self._pick_prefill(exclude={decode_h.name})
        if p is None:
            return self._handoff_fallback(
                "no_prefill_replica", "prefill tier empty or unhealthy"
            )
        METRICS.inc("router.handoffs")
        transfer_id = uuid.uuid4().hex[:16]
        body = json.dumps({
            "prompt": list(prompt_ids),
            "kv_host": decode_h.host,
            "kv_port": decode_h.kv_port,
            "transfer_id": transfer_id,
        }).encode()
        t0 = time.perf_counter()
        # The prefill tier does prompt + 1 token of work — charging the
        # request's full decode budget would let a huge max_tokens field
        # steer prefill placement away from the replica doing the LEAST
        # prefill work.
        charge = len(prompt_ids) + 1
        p.committed_tokens += charge
        # Handoff count doubles as the prefill tier's queue-depth signal
        # (cluster/autoscale.py TieredAutoscaler reads it off the handle).
        p.handoffs += 1
        METRICS.set_gauge(
            f"router.committed_tokens.{p.name}", p.committed_tokens
        )
        try:
            out = await asyncio.wait_for(
                self._rpc(p, "/v1/prefill", body), self.handoff_deadline_s
            )
        except asyncio.TimeoutError:
            return self._handoff_fallback(
                "timeout",
                f"prefill replica {p.name} exceeded the "
                f"{self.handoff_deadline_s:g}s handoff deadline",
            )
        except (ConnectionError, OSError, EOFError, ValueError, IndexError,
                asyncio.IncompleteReadError) as e:
            # Crash / partition / kill mid-handoff all surface here as a
            # severed or unreachable connection (an empty status line
            # from a half-dead socket parses as IndexError/ValueError).
            return self._handoff_fallback(
                "error", f"prefill replica {p.name}: "
                f"{type(e).__name__}: {e}",
            )
        finally:
            p.committed_tokens -= charge
            p.handoffs -= 1
            METRICS.set_gauge(
                f"router.committed_tokens.{p.name}", p.committed_tokens
            )
        status, resp = out
        if status != 200 or not isinstance(resp, dict):
            return self._handoff_fallback(
                "rejected", f"prefill replica {p.name} answered {status}"
            )
        if not resp.get("ok"):
            return self._handoff_fallback(
                "rejected",
                f"prefill replica {p.name}: "
                f"{resp.get('reason') or resp.get('error', 'rejected')}",
            )
        shipped = resp.get("digests") or []
        want = [d.hex() for d in digests[: len(shipped)]]
        if not shipped or shipped != want:
            # The transfer itself verified on the decode side, but it does
            # not commit to the prompt THIS router hashed: stale pages
            # under our digests would be worse than no pages.
            return self._handoff_fallback(
                "digest_mismatch",
                f"prefill replica {p.name} shipped {len(shipped)} page(s) "
                "whose digests diverge from the request's",
            )
        el = time.perf_counter() - t0
        METRICS.observe("router.handoff_seconds", el)
        METRICS.inc("router.handoff_bytes", int(resp.get("bytes", 0)))
        log.info(
            "handoff %s: %d page(s), %d token(s) prefilled on %s -> %s "
            "in %.1f ms (%d transfer attempt(s))", transfer_id,
            int(resp.get("pages", 0)), int(resp.get("tokens", 0)),
            p.name, decode_h.name, el * 1e3, int(resp.get("attempts", 1)),
        )
        return True

    async def _rpc(self, p, path: str, body: bytes) -> tuple[int, dict]:
        """POST one control-plane JSON RPC (/v1/prefill, /v1/kv_export)
        to a replica; returns (status, JSON)."""
        reader, writer = await asyncio.open_connection(p.host, p.port)
        try:
            writer.write(
                f"POST {path} HTTP/1.1\r\nHost: router\r\n"
                f"Content-Length: {len(body)}\r\n\r\n".encode() + body
            )
            await writer.drain()
            status = int((await reader.readline()).split()[1])
            clen = 0
            for _ in range(_MAX_HEADERS):
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1", "replace").partition(":")
                if name.strip().lower() == "content-length":
                    clen = int(value.strip())
            raw = await reader.readexactly(clen) if clen else b""
            try:
                resp = json.loads(raw) if raw else {}
            except json.JSONDecodeError:
                resp = {}
            return status, resp if isinstance(resp, dict) else {}
        finally:
            writer.close()

    # -- cross-replica KV reuse (the fleet prefix-digest directory) --------

    def _pull_fallback(self, reason: str, detail: str) -> bool:
        METRICS.inc("directory.pull_fallbacks")
        METRICS.inc(f"directory.pull_fallbacks.{reason}")
        log.warning("cross-replica pull degraded to local recompute "
                    "(%s): %s", reason, detail)
        return False

    async def _directory_pull(self, decode_h, prompt_ids: list[int],
                              digests: list[bytes]) -> bool:
        """Cross-replica KV reuse for a request about to land COLD on
        ``decode_h``: ask the fleet-wide prefix-digest directory (the
        affinity map itself — epoch-keyed, so a drained/respawned sibling
        self-invalidates into a miss) which SIBLING already holds the
        prompt's cached page run, and have that sibling ship the pages to
        ``decode_h``'s KV listener (``POST /v1/kv_export`` -> the
        checksummed KV_PAGES plane) instead of re-prefilling content the
        fleet already computed.  The shipped digests must be a prefix of
        the digests THIS router hashed from the prompt, exactly like the
        prefill handoff — a mis-steered or lying source must not poison
        the decode cache.  Returns True when pages landed; EVERY failure
        — stale directory answer (``directory.lookup:drop``), mis-steer
        (``:corrupt``), nothing cached, corrupt frame, sender crash
        mid-pull, deadline — returns False and the caller forwards the
        request unchanged: local recompute, byte-exact either way."""
        import uuid

        METRICS.inc("directory.lookups")
        now = self._loop.time()
        src = None
        for i in range(len(digests) - 1, -1, -1):  # longest cached run first
            name = self._affinity_lookup(digests[i])
            if name is not None and name != decode_h.name:
                h = self.fleet._by_name.get(name)
                if h is not None and h.reachable(now):
                    src = h
                    break
        if src is None:
            return False  # a plain miss: nothing to pull, nothing to count
        METRICS.inc("directory.hits")
        if self.faults is not None:
            # defer_stall: this plane runs on the router's event loop; a
            # stall rule is applied as an awaited delay below, never a
            # blocking sleep.
            rule = self.faults.fire("directory.lookup", tag=src.name,
                                    defer_stall=True)
            if rule is not None and rule.action == "drop":
                METRICS.inc("directory.stale_drops")
                return self._pull_fallback(
                    "stale",
                    f"directory answer for {src.name} read stale (drill)",
                )
            if rule is not None and rule.action == "corrupt":
                # Mis-steer: the lookup answers a sibling that does NOT
                # hold the pages — its export finds nothing (or ships a
                # run whose digests diverge) and the pull degrades.
                wrong = [h for h in self.fleet.replicas
                         if h.name not in (src.name, decode_h.name)
                         and h.reachable(now)]
                if not wrong:
                    METRICS.inc("directory.stale_drops")
                    return self._pull_fallback(
                        "stale", "mis-steer drill found no other replica"
                    )
                src = min(wrong, key=lambda h: h.name)
            if rule is not None and rule.action in ("delay", "stall"):
                await asyncio.sleep(rule.arg or 0.0)
        if decode_h.kv_port is None:
            return self._pull_fallback(
                "no_kv_target",
                f"decode replica {decode_h.name} has no KV listener",
            )
        METRICS.inc("directory.pulls")
        transfer_id = uuid.uuid4().hex[:16]
        body = json.dumps({
            "prompt": list(prompt_ids),
            "kv_host": decode_h.host,
            "kv_port": decode_h.kv_port,
            "transfer_id": transfer_id,
        }).encode()
        t0 = time.perf_counter()
        try:
            status, resp = await asyncio.wait_for(
                self._rpc(src, "/v1/kv_export", body), self.pull_deadline_s
            )
        except asyncio.TimeoutError:
            return self._pull_fallback(
                "timeout",
                f"source replica {src.name} exceeded the "
                f"{self.pull_deadline_s:g}s pull deadline",
            )
        except (ConnectionError, OSError, EOFError, ValueError, IndexError,
                asyncio.IncompleteReadError) as e:
            # Sender crash / partition mid-pull surfaces as a severed or
            # unreachable connection (an empty status line from a
            # half-dead socket parses as IndexError/ValueError).
            return self._pull_fallback(
                "error",
                f"source replica {src.name}: {type(e).__name__}: {e}",
            )
        if status != 200 or not isinstance(resp, dict) or not resp.get("ok"):
            why = resp.get("reason") if isinstance(resp, dict) else None
            reason = ("not_cached" if why == "nothing to export"
                      else "rejected")
            return self._pull_fallback(
                reason, f"source replica {src.name}: {why or status}"
            )
        shipped = resp.get("digests") or []
        want = [d.hex() for d in digests[: len(shipped)]]
        if not shipped or shipped != want:
            return self._pull_fallback(
                "rejected",
                f"source replica {src.name} shipped {len(shipped)} page(s) "
                "whose digests diverge from the request's",
            )
        el = time.perf_counter() - t0
        METRICS.observe("directory.pull_seconds", el)
        METRICS.inc("directory.pulled_pages", int(resp.get("pages", 0)))
        METRICS.inc("directory.pull_bytes", int(resp.get("bytes", 0)))
        log.info(
            "pull %s: %d page(s), %d token(s) shipped %s -> %s in %.1f ms "
            "(%d transfer attempt(s))", transfer_id,
            int(resp.get("pages", 0)), int(resp.get("tokens", 0)),
            src.name, decode_h.name, el * 1e3, int(resp.get("attempts", 1)),
        )
        return True

    # -- the proxy core ----------------------------------------------------

    async def _proxy(self, writer, method: str, path: str, body: bytes,
                     chat: bool, tenant: str | None = None) -> None:
        try:
            req = json.loads(body or b"{}")
            req = req if isinstance(req, dict) else {}
        except json.JSONDecodeError:
            req = {}  # the replica answers the 400; placement needs no parse
        prompt_ids, est = self._estimate(req, chat)
        digests = self._digests(prompt_ids)
        # The X-Tenant header rides the re-built upstream request (bodies
        # forward verbatim, headers do not): the replica's tenant gate and
        # weighted-fair scheduler must see the same identity the client
        # sent.  A malformed id 400s HERE with the replica's own message —
        # rewriting it could collapse onto (and bill) a DIFFERENT tenant,
        # and the shared charset is header-safe by construction, so the
        # router cannot become a header-injection vector either way.
        tenant_line = ""
        if tenant:
            if not valid_tenant_id(tenant):
                await self._json(writer, 400, _err_body(
                    "'tenant' must be 1-64 chars of [A-Za-z0-9._-] "
                    "(X-Tenant header or body field)"
                ))
                return
            tenant_line = f"X-Tenant: {tenant}\r\n"
        payload = (
            f"{method} {path} HTTP/1.1\r\nHost: replica\r\n"
            f"{tenant_line}"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode() + body
        METRICS.inc("router.requests")
        # The FLEET tenant-ledger gate — the one admission-commit point.
        # Charged here (before placement), refunded on every outcome that
        # served the tenant nothing; the replica gateways behind it run
        # their own ledgers as a LOOSE backstop only.
        key = tenant if tenant else ANON_TENANT
        charged = False
        if self.tenant_quota_tps is not None and method == "POST":
            rule = None
            if self.faults is not None:
                # defer_stall: the gate runs on the router's event loop —
                # a stall rule slows THIS admission as an awaited delay,
                # never the loop (probes and other tenants keep moving).
                rule = self.faults.fire("router.ledger", tag=key,
                                       defer_stall=True)
            if rule is not None and rule.action in ("delay", "stall"):
                await asyncio.sleep(rule.arg or 0.0)
            if rule is not None and rule.action == "drop":
                # The drill that bypasses the gate AND its charge: the
                # replica gateways' backstop is now the only meter — the
                # ladder's "never a silent unmetered path" leg.
                METRICS.inc("router.ledger.bypasses")
                log.warning(
                    "fleet ledger gate bypassed for tenant %r (drill); "
                    "replica backstop still meters", key,
                )
            else:
                forced = rule is not None and rule.action == "exhaust"
                allowed = self._tenant_allowance(key)
                if est > allowed:
                    # Bigger than the tenant's ENTIRE fleet window: no
                    # Retry-After could come true — malformed for this
                    # tenant, not load (the replica gate's own contract).
                    await self._json(writer, 400, _err_body(
                        f"request needs {est} admission tokens but tenant "
                        f"{key!r}'s fleet quota window holds at most "
                        f"{int(allowed)}"
                    ))
                    return
                hint = self._ledger_retry_after(key, est, forced=forced)
                if hint is not None:
                    METRICS.inc("router.ledger.sheds")
                    METRICS.inc(f"router.ledger.shed.{key}")
                    shed = _err_body(
                        f"tenant {key!r} over its fleet token-rate quota "
                        f"({est} tokens would exceed the "
                        f"{self.tenant_rate_window_s:g}s window)",
                        "overloaded_error",
                    )
                    shed["error"]["reason"] = "tenant_quota"
                    await self._json(writer, 429, shed,
                                     headers={"Retry-After": str(hint)})
                    return
                self._ledger_charge(key, est)
                charged = True
        tried: set[str] = set()
        attempts = 0
        t_fail: float | None = None
        while True:
            h = self._place(digests, est, exclude=tried)
            if h is None:
                if charged:
                    charged = False
                    self._ledger_refund(key, est)
                if attempts:
                    # The request actually FAILED on a replica and no
                    # healthy candidate remains: that is an engine
                    # failure (the documented exhaustion contract), not
                    # ordinary overload.
                    await self._exhausted(
                        writer, attempts,
                        f"request failed on {attempts} replica(s) and no "
                        "healthy replica remains; retry later",
                    )
                else:
                    await self._shed(writer, "no healthy replica available")
                return
            rec = _Inflight()
            h.inflight.add(rec)
            h.committed_tokens += est
            METRICS.set_gauge(
                f"router.committed_tokens.{h.name}", h.committed_tokens
            )
            # Does the chosen replica already hold this prompt's full
            # page run (epoch-valid affinity — recorded whether the pages
            # arrived by handoff OR by a colocated prefill there)?  Then
            # shipping it again would only earn a "duplicate" ack for a
            # multi-MB transfer.  Read BEFORE recording this placement,
            # which would trivially satisfy the check.
            warm = bool(digests) and \
                self._affinity_lookup(digests[-1]) == h.name
            try:
                if digests and method == "POST" and not chat and not warm:
                    # The request lands COLD here.  Cheapest source of its
                    # pages first: a SIBLING's cache via the fleet digest
                    # directory (cross-replica pull), then the prefill
                    # tier (disaggregated handoff).  Both are best-effort
                    # BY DESIGN — every failure mode inside degrades to
                    # colocated prefill on this replica; the verbatim
                    # forward below is identical either way (byte-exact
                    # all three paths).  Chat requests skip both planes:
                    # the replica tokenizes them through its chat
                    # template, so router-side ids (and therefore the
                    # shipped digests) would never match the admission's
                    # — pages would import dead.
                    pulled = False
                    if self.pull and prompt_ids is not None:
                        pulled = await self._directory_pull(
                            h, prompt_ids, digests
                        )
                    if not pulled and self.handoff:
                        await self._handoff(h, prompt_ids, digests)
                elif warm and self.handoff and digests \
                        and method == "POST" and not chat:
                    METRICS.inc("router.handoff_skips")
                # Record AFTER sourcing: the directory lookup above must
                # see who held the pages BEFORE this placement — writing
                # first would overwrite the source entry with the cold
                # replica and turn every pull into a self-referential
                # miss.
                self._record_affinity(digests, h)
                status = await self._forward(writer, h, payload, rec)
                if charged and status >= 400:
                    # The replica answered but served nothing (its own
                    # structured shed passing through, or a 400): the
                    # fleet ledger must not bill tokens that bought no
                    # service.
                    charged = False
                    self._ledger_refund(key, est)
                if t_fail is not None:
                    # Failover recovery latency: failure observed ->
                    # re-placed request fully answered.
                    METRICS.observe(
                        "router.failover_seconds",
                        time.perf_counter() - t_fail,
                    )
                return
            except _UpstreamFailed as e:
                if rec.streamed:
                    # Deltas already reached the client — the PR-2
                    # mailbox contract one level up: structured
                    # engine_error, never a silent truncation.
                    METRICS.inc("router.failed_streamed")
                    await self._stream_error(writer)
                    return
                tried.add(h.name)
                attempts += 1
                if t_fail is None:
                    t_fail = time.perf_counter()
                METRICS.inc("router.failovers")
                log.warning(
                    "replica %s failed zero-streamed request (%s); "
                    "failover attempt %d", h.name, e, attempts,
                )
                if attempts > self.max_failover_retries:
                    if charged:
                        charged = False
                        self._ledger_refund(key, est)
                    await self._exhausted(
                        writer, attempts,
                        f"request failed on {attempts} replica(s); "
                        "retry later",
                    )
                    return
            finally:
                h.inflight.discard(rec)
                h.committed_tokens -= est
                METRICS.set_gauge(
                    f"router.committed_tokens.{h.name}", h.committed_tokens
                )

    async def _up(self, awaitable, rec: _Inflight):
        """Await one upstream read, racing the replica's abort signal —
        the fleet sets it when the replica dies, wedges past the watchdog,
        partitions, or drains out from under us."""
        read_t = asyncio.ensure_future(awaitable)
        abort_t = asyncio.ensure_future(rec.abort.wait())
        try:
            done, _ = await asyncio.wait(
                {read_t, abort_t}, return_when=asyncio.FIRST_COMPLETED
            )
        finally:
            abort_t.cancel()
        if read_t not in done:
            read_t.cancel()
            try:
                await read_t
            except (Exception, asyncio.CancelledError):
                pass
            raise _UpstreamFailed("replica became unhealthy mid-request")
        try:
            return read_t.result()
        except (ConnectionError, OSError, EOFError,
                asyncio.IncompleteReadError) as e:
            raise _UpstreamFailed(f"{type(e).__name__}: {e}") from e

    async def _forward(self, writer, h, payload: bytes,
                       rec: _Inflight) -> int:
        """One upstream leg; returns the upstream HTTP status (the fleet
        ledger refunds on >= 400 — the replica served nothing).  Raises
        :class:`_UpstreamFailed` when the replica failed us; client-side
        socket errors propagate as-is (they must never trigger a failover
        re-send)."""
        now = self._loop.time()
        if not h.reachable(now) or rec.abort.is_set():
            raise _UpstreamFailed("replica unreachable")
        try:
            up_r, up_w = await asyncio.open_connection(h.host, h.port)
        except (ConnectionError, OSError) as e:
            raise _UpstreamFailed(f"connect: {e}") from e
        try:
            up_w.write(payload)
            await self._up(up_w.drain(), rec)
            status_line = await self._up(up_r.readline(), rec)
            try:
                status = int(status_line.split()[1])
            except (IndexError, ValueError) as e:
                raise _UpstreamFailed("bad upstream status line") from e
            raw_head = [status_line]
            headers: dict[str, str] = {}
            for _ in range(_MAX_HEADERS):
                line = await self._up(up_r.readline(), rec)
                raw_head.append(line)
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1", "replace").partition(":")
                headers[name.strip().lower()] = value.strip()
            head = b"".join(raw_head)
            if "text/event-stream" in headers.get("content-type", ""):
                # SSE: forward incrementally.  The client's headers are
                # HELD until the first upstream payload byte, so a replica
                # dying pre-first-token still fails over exactly.
                first = True
                while True:
                    chunk = await self._up(up_r.read(65536), rec)
                    if not chunk:
                        if first:
                            raise _UpstreamFailed("stream died before data")
                        return status
                    if first:
                        writer.write(head)
                        first = False
                    rec.streamed = True
                    writer.write(chunk)
                    await writer.drain()
            clen = headers.get("content-length")
            if clen is not None:
                body = await self._up(up_r.readexactly(int(clen)), rec)
            else:
                body = await self._up(up_r.read(), rec)
            if status == 503 and b"overloaded_error" not in body:
                # Infrastructure 503 (draining / unhealthy gate): a
                # placement mistake, not an answer — fail over.  A
                # structured shed IS the replica's answer and passes
                # through with its Retry-After.
                raise _UpstreamFailed("replica not ready (503)")
            if status == 500 and (b"engine_error" in body
                                  or b"shutting down" in body):
                # Dead supervisor / replica mid-shutdown: nothing streamed
                # (buffered path), so the request is safe to re-place.
                raise _UpstreamFailed("replica engine dead (500)")
            writer.write(head + body)
            await writer.drain()
            rec.streamed = True
            return status
        finally:
            up_w.close()

    async def _stream_error(self, writer) -> None:
        """Terminate a partially-forwarded SSE stream with the structured
        mid-stream error event (the replica server's own idiom)."""
        try:
            writer.write(
                b"data: " + json.dumps(_err_body(
                    "replica failed mid-stream; partial output could not "
                    "be resumed", "engine_error",
                )).encode() + b"\n\n"
            )
            await writer.drain()
        except (ConnectionError, OSError):
            pass

    def _retry_after_s(self) -> int:
        """Coarse back-off hint: one tick when replicas are merely busy,
        scaling with how much of the fleet is unavailable."""
        now = self._loop.time() if self._loop is not None else 0.0
        total = max(1, len(self.fleet.replicas))
        down = sum(1 for h in self.fleet.replicas if not h.routable(now))
        return int(min(30, max(1, 1 + 4 * down * down / total)))

    async def _shed(self, writer, msg: str) -> None:
        await self._json(
            writer, 503, _err_body(msg, "overloaded_error"),
            headers={"Retry-After": str(self._retry_after_s())},
        )

    async def _exhausted(self, writer, attempts: int, msg: str) -> None:
        """Failover budget (or candidate pool) exhausted on a request that
        actually FAILED on >= 1 replica: structured, retryable
        ``engine_error`` + Retry-After."""
        METRICS.inc("router.retries_exhausted")
        await self._json(
            writer, 503, _err_body(msg, "engine_error"),
            headers={"Retry-After": str(self._retry_after_s())},
        )

    # -- HTTP plumbing -----------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            parsed = await asyncio.wait_for(
                self._read_request(writer, reader), 30.0
            )
            if parsed is None:
                return
            method, path, body, tenant = parsed
            await self._route(writer, method, path, body, tenant)
        except (asyncio.TimeoutError, ConnectionError, OSError, ValueError,
                EOFError):
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    async def _read_request(self, writer, reader):
        line = await reader.readline()
        if len(line) > _MAX_REQUEST_LINE:
            await self._plain(writer, 431, "request line too long")
            return None
        parts = line.decode("latin-1", "replace").split()
        if len(parts) < 2:
            await self._plain(writer, 400, "bad request")
            return None
        method, path = parts[0], parts[1]
        content_len = 0
        tenant: str | None = None
        for _ in range(_MAX_HEADERS):
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            name, _, value = h.decode("latin-1", "replace").partition(":")
            hname = name.strip().lower()
            if hname == "content-length":
                try:
                    content_len = int(value.strip())
                except ValueError:
                    await self._plain(writer, 400, "bad content-length")
                    return None
            elif hname == "transfer-encoding":
                # Only Content-Length bodies are read (the replica server
                # enforces the same): a chunked POST would forward an
                # EMPTY body and surface as a misleading replica-side 400.
                await self._plain(writer, 501, "chunked bodies not supported")
                return None
            elif hname == "x-tenant":
                # Forwarded to the chosen replica (bodies are verbatim;
                # headers are re-built) — tenant QoS is decided there.
                tenant = value.strip()
        else:
            await self._plain(writer, 431, "too many headers")
            return None
        if content_len > _MAX_BODY:
            await self._plain(writer, 413, "body too large")
            return None
        body = await reader.readexactly(content_len) if content_len else b""
        return method, path, body, tenant

    async def _route(self, writer, method: str, path: str,
                     body: bytes, tenant: str | None = None) -> None:
        if method == "GET" and path == "/healthz":
            report = self.fleet.report()
            code = 200 if report["healthy"] > 0 else 503
            report["status"] = "ok" if code == 200 else "unhealthy"
            await self._json(writer, code, report, headers=(
                None if code == 200
                else {"Retry-After": str(self._retry_after_s())}
            ))
        elif method == "GET" and path == "/metrics":
            await self._respond(
                writer, 200, "text/plain; version=0.0.4; charset=utf-8",
                METRICS.prometheus_text().encode(),
            )
        elif method == "GET" and path == "/v1/models":
            await self._proxy(writer, method, path, b"", chat=False)
        elif method == "POST" and path in ("/v1/completions",
                                           "/v1/chat/completions"):
            await self._proxy(writer, method, path, body,
                              chat="chat" in path, tenant=tenant)
        elif method not in ("GET", "POST"):
            await self._plain(writer, 405, "method not allowed")
        else:
            await self._plain(writer, 404, "not found")

    async def _plain(self, writer, code: int, body: str) -> None:
        await self._respond(writer, code, "text/plain", body.encode())

    async def _json(self, writer, code: int, obj: dict,
                    headers: dict[str, str] | None = None) -> None:
        await self._respond(
            writer, code, "application/json",
            (json.dumps(obj) + "\n").encode(), headers=headers,
        )

    async def _respond(self, writer, code: int, ctype: str, payload: bytes,
                       headers: dict[str, str] | None = None) -> None:
        extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
        writer.write(
            (
                f"HTTP/1.1 {code} {_REASONS.get(code, '')}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"{extra}"
                "Connection: close\r\n\r\n"
            ).encode()
            + payload
        )
        await writer.drain()
