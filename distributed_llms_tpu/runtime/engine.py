"""Inference engine: ties config + params + mesh + decode loop together.

Functional successor of the reference's MasterNode inference surface
(initialize_model / run_inference, src/master/node.py:54-138) minus the
socket runtime: model placement is ``device_put`` onto a mesh, inference is a
jit-compiled generate, results are decoded text (the reference returned raw
pickled partials, defect D9).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

import contextlib

from ..core.config import Config, ModelConfig, RuntimeConfig
from ..core.observability import METRICS, get_logger
from ..core import profiling
from ..models import model as model_lib
from ..models.presets import get_preset
from . import generate as gen_lib
from . import shapes as shapes_lib
from .tokenizer import get_tokenizer, pad_batch

log = get_logger("engine")


def _to_host(out) -> np.ndarray:
    """Device->host for generation outputs.  On a mesh spanning multiple
    processes (BASELINE config 5) the output array is not fully addressable
    from any one process; allgather the tiles first (every process then
    holds — and returns — the same full batch)."""
    out = jax.block_until_ready(out)
    if not getattr(out, "is_fully_addressable", True):
        from jax.experimental import multihost_utils

        out = multihost_utils.process_allgather(out, tiled=True)
    return np.asarray(out)


@dataclass
class GenerationResult:
    text: list[str]
    tokens: np.ndarray  # [B, N]
    prompt_tokens: int
    generated_tokens: int
    seconds: float

    @property
    def tokens_per_second(self) -> float:
        return self.generated_tokens / max(self.seconds, 1e-9)


class InferenceEngine:
    """Inference engine, single-device or mesh-parallel.

    `params` may come from the checkpoint converter (real weights) or
    ``init_params`` (random, for benchmarks) — the engine is agnostic.
    With ``parallel`` (a parallel.api.ParallelModel) the params are placed
    onto the mesh (``device_put`` per NamedSharding — the reference's
    "distribute" without tensor bytes on a socket) and generation runs the
    pipelined / tensor-parallel forward.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        rt: RuntimeConfig,
        params: Any,
        tokenizer=None,
        parallel: Any = None,  # parallel.api.ParallelModel
    ) -> None:
        self.cfg = cfg
        self.rt = rt
        self.parallel = parallel
        if rt.compilation_cache_dir:
            # Persistent compile cache: a restarted server skips the
            # first-compile wait.  Only the dir is set here — JAX's own
            # min-compile-time/threshold knobs stay whatever the operator
            # configured.  Note JAX initializes the cache once per process:
            # the first engine's dir wins; later different values are
            # ignored by JAX, not errored.
            jax.config.update("jax_compilation_cache_dir", rt.compilation_cache_dir)
        self.tokenizer = tokenizer or get_tokenizer(None)
        # Out-of-vocab ids silently become NaN embeddings (jnp.take fills
        # OOB gathers) — reject the mismatch loudly instead.
        tok_vocab = getattr(self.tokenizer, "vocab_size", None)
        if tok_vocab is not None and tok_vocab > cfg.vocab_size:
            raise ValueError(
                f"tokenizer vocab ({tok_vocab}, incl. specials) exceeds model "
                f"vocab ({cfg.vocab_size}); token ids would be out of range"
            )
        if parallel is not None:
            self.params = parallel.shard_params(params)
            self._forward_fn = parallel.as_forward_fn()
            self._make_cache = parallel.as_make_cache()
            self._decode_fn = parallel.as_decode_fn()  # fused pipelined decode
        else:
            self.params = params
            self._forward_fn = None  # generate_tokens' single-device default
            self._decode_fn = None
            # KV-cache dtype knob: bound once so the jitted decode sees a
            # stable (identity-hashed) make_cache and caches the compilation.
            kv_dtype = jnp.dtype(rt.kv_cache_dtype)
            self._make_cache = lambda cfg_, b, s, prompt_len=None: model_lib.init_cache(
                cfg_, b, s, dtype=kv_dtype
            )
        self._timer = profiling.StepTimer("engine.generate")
        if rt.spec_decode:
            # CONFIG-DRIVEN knob policy (same as runtime.paged_pages on a
            # mesh engine): one shared cluster config with spec_decode on
            # must never brick a worker whose engine can't self-speculate —
            # mesh engines and quantized-store engines DEGRADE to plain
            # serving with a loud warning (an explicit
            # attach_draft(draft_cfg, draft_params) still works on the
            # latter).  Genuinely malformed configs still raise.
            if cfg.ragged_decode:
                # speculative_generate_tokens rejects ragged_decode (the
                # prefix-read kernel cannot serve its masks); surface the
                # conflict at construction, not on the first request.
                raise ValueError(
                    "runtime.spec_decode is incompatible with "
                    "model.ragged_decode; unset one"
                )
            if rt.spec_k < 1:
                # Fail at construction, not on the first routed request.
                raise ValueError(f"runtime.spec_k must be >= 1, got {rt.spec_k}")
            if parallel is not None:
                log.warning(
                    "runtime.spec_decode is single-device; this mesh engine "
                    "serves PLAIN (generate_text / continuous_batcher keep "
                    "working, just without speculation)"
                )
            elif self._serves_quantized():
                log.warning(
                    "spec_decode requested but the engine serves quantized "
                    "weights; serving PLAIN (no self-draft to quantize). "
                    "Attach an explicit draft for speculative serving."
                )
            else:
                # Self-speculation: the draft is this engine's own blocks
                # weight-only quantized.
                self.attach_draft(quantize_bits=rt.spec_draft_quantize)
        # Session store: caches persist across turns; with kv_host_spill only
        # the most recent max_resident_sessions stay in device memory.
        from .session import SessionManager

        self.sessions = SessionManager(
            max_resident=rt.max_resident_sessions if rt.kv_host_spill else (1 << 30)
        )

    @classmethod
    def from_preset(
        cls, name: str, rt: RuntimeConfig | None = None, rng_seed: int = 0, **overrides
    ) -> "InferenceEngine":
        cfg = get_preset(name, **overrides)
        params = model_lib.init_params(jax.random.key(rng_seed), cfg)
        return cls(cfg, rt or RuntimeConfig(), params)

    @classmethod
    def from_store(
        cls,
        store_dir: str,
        rt: RuntimeConfig | None = None,
        mesh_cfg: Any = None,  # core.config.MeshConfig
        tokenizer=None,
    ) -> "InferenceEngine":
        """Build from a shard store, optionally mesh-parallel.

        This is the product path the reference promised (split one model
        across workers, src/master/node.py:84-115) done TPU-native: the mesh
        comes from ``Config.mesh``, microbatches from
        ``RuntimeConfig.microbatches``, placement is ``device_put``.
        """
        from ..checkpoint import store as store_lib
        from ..core.config import ModelConfig

        rt = rt or RuntimeConfig()
        manifest = store_lib.load_manifest(store_dir)
        if manifest.get("model_config") is None:
            raise ValueError(f"store {store_dir} has no embedded model_config")
        cfg = ModelConfig(**manifest["model_config"])
        if tokenizer is None:
            # The store records the model's own tokenizer (save_shards copies
            # the HF files in — the reference's master-side HF tokenizer,
            # src/master/node.py:235-245).  Serving a real checkpoint through
            # byte-level ids produces gibberish; warn loudly if that is about
            # to happen.
            import os

            from .tokenizer import ByteTokenizer

            tok_rel = manifest.get("tokenizer")
            if tok_rel:
                tokenizer = get_tokenizer(os.path.join(store_dir, tok_rel))
            if tokenizer is None or isinstance(tokenizer, ByteTokenizer):
                if cfg.vocab_size > ByteTokenizer.vocab_size:
                    log.warning(
                        "store %s has no usable tokenizer (manifest tokenizer=%r) "
                        "but the model vocab is %d; falling back to byte-level "
                        "ids — decoded text will be wrong for a real checkpoint. "
                        "Re-save the store with tokenizer_src=<checkpoint dir>.",
                        store_dir, tok_rel, cfg.vocab_size,
                    )
        if rt.serve_quantized:
            # Weight-only quantized serving: decoder-block weights stay
            # int8/int4 in HBM; QuantizedTensor leaves flow through the block
            # scan into layers._contract, which feeds the fused dequant-matmul
            # Pallas kernel on TPU (ops/quant_matmul.py) or dequantize+einsum
            # elsewhere.  Embedding/unembedding tables are rehydrated —
            # gathers can't consume QuantizedTensor leaves.
            if not manifest.get("quantization"):
                raise ValueError(
                    f"serve_quantized=True but store {store_dir} is not "
                    "quantized; save it with quantization='int8'|'int4'"
                )
            from ..checkpoint import quantize as quant_lib

            params = store_lib.load_shards(store_dir, dequantize=False)
            params = {
                k: (v if k == "blocks" else quant_lib.dequantize_tree(v, cfg.dtype))
                for k, v in params.items()
            }
        else:
            params = store_lib.reconstruct(store_dir, dtype=cfg.dtype)
        parallel = None
        if mesh_cfg is not None and mesh_cfg.num_devices > 1:
            from ..parallel.api import make_parallel_model

            parallel = make_parallel_model(
                cfg, mesh_cfg,
                num_microbatches=max(rt.microbatches, 1),
                kv_dtype=rt.kv_cache_dtype,
            )
        return cls(cfg, rt, params, tokenizer=tokenizer, parallel=parallel)

    def _batch_multiple(self) -> int:
        """Batch rows must divide evenly over the data axis, times the
        microbatch count when the pipeline schedule splits the batch."""
        if self.parallel is None:
            return 1
        data = self.parallel.mesh.shape.get("data", 1)
        mb = self.parallel.num_microbatches if self.parallel.pipelined else 1
        return max(mb, 1) * data

    def generate_text(
        self, prompts: list[str], max_new_tokens: int | None = None, seed: int | None = None
    ) -> GenerationResult:
        tok = self.tokenizer
        prompt_arr, lens, n_real = self._encode_rows(prompts, batch=None)
        n_new = self.rt.max_decode_steps if max_new_tokens is None else max_new_tokens
        gen_lib.check_sequence_budget(prompt_arr.shape[1], n_new, self.rt, self.cfg)
        limit = min(self.rt.max_seq_len, self.cfg.max_seq_len)
        if (
            self.rt.spec_decode
            and self.rt.temperature == 0.0
            and self.parallel is None
            and getattr(self, "draft_params", None) is not None
            and n_new >= 1
            # The verify pass overwrites up to k+1 slots past the budget;
            # near the sequence cap the plain loop still fits — fall through
            # there (transparent means never erroring where plain succeeds).
            and prompt_arr.shape[1] + self.rt.spec_k + 1 + n_new <= limit
        ):
            # Transparent routing: greedy speculative output is bit-identical
            # to the plain loop's, so callers (cluster workers, CLI) get the
            # speedup without an API change.
            return self._speculative_result(
                prompt_arr, lens, n_real, n_new, self.rt.spec_k
            )
        # Bucket only on the PLAIN path, after the budget check (which must
        # see the raw width, as before) and the spec-decode gate (whose
        # near-cap predicate on the raw width must keep routing prompts the
        # speculative loop can still fit).
        prompt_arr = self._bucket_prompt(prompt_arr, n_new)
        rng = jax.random.key(seed if seed is not None else self.rt.seed)

        profile_ctx = (
            profiling.trace(self.rt.profile_dir)
            if self.rt.profile_dir
            else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        with profile_ctx, self._timer.step(tokens=n_real * n_new):
            out = gen_lib.generate_tokens(
                self.params, self.cfg,
                jnp.asarray(prompt_arr), jnp.asarray(lens), rng,
                max_new_tokens=n_new,
                temperature=self.rt.temperature, top_k=self.rt.top_k, top_p=self.rt.top_p,
                eos_id=tok.eos_id, pad_id=tok.pad_id,
                forward_fn=self._forward_fn, make_cache=self._make_cache,
                decode_fn=self._decode_fn,
            )
            out = _to_host(out)[:n_real]
        dt = time.perf_counter() - t0
        profiling.record_memory_stats()

        texts = [tok.decode(row) for row in out]
        gen_count = int(out.shape[0] * out.shape[1])
        METRICS.inc("engine.generated_tokens", gen_count)
        METRICS.observe("engine.generate_seconds", dt)
        return GenerationResult(
            text=texts, tokens=out,
            prompt_tokens=int(lens[:n_real].sum()), generated_tokens=gen_count,
            seconds=dt,
        )

    def _bucket_prompt(self, prompt_arr, n_new: int):
        """Pad the prompt width up the shared bucket ladder
        (runtime/shapes.py) so generate_tokens compiles once per bucket
        instead of once per distinct batch-max prompt length — the
        "recompile every new seq length" serving bug tools.graftcheck's GC4
        gate pins closed.  Exact by construction: pad slots carry pad_id,
        sit to the RIGHT of every real token (causal prefill queries never
        see them), and the decode mask admits only real prompt slots +
        generated slots.  Skipped when the bucket would not fit the
        sequence budget (keeps the pre-bucket error behavior) and under
        seq-parallelism (T must stay a multiple of the seq axis)."""
        if self.parallel is not None and self.parallel.seq_parallel:
            return prompt_arr
        t = int(prompt_arr.shape[1])
        limit = min(self.rt.max_seq_len, self.cfg.max_seq_len)
        target = shapes_lib.generate_pad_len(t, n_new, limit)
        if target <= t:
            return prompt_arr
        return jnp.pad(
            prompt_arr, ((0, 0), (0, target - t)),
            constant_values=self.tokenizer.pad_id,
        )

    # -- sessions: KV persists across turns; host spill under kv_host_spill --

    def _session_max_len(self) -> int:
        return min(self.rt.max_seq_len, self.cfg.max_seq_len)

    def _encode_rows(self, prompts: list[str], batch: int | None) -> tuple:
        """Encode + pad rows.  ``batch=None``: new session — pad the row count
        up to the mesh multiple.  Otherwise: continuation — row count must
        match the session's real rows; mesh-padding rows repeat row 0."""
        tok = self.tokenizer
        seqs = [tok.encode(p) for p in prompts]
        n_real = len(seqs)
        if batch is None:
            mult = self._batch_multiple()
            while len(seqs) % mult:
                seqs.append(seqs[0])
        else:
            while len(seqs) < batch:
                seqs.append(seqs[0])
        arr, lens = pad_batch(seqs, tok.pad_id)
        if self.parallel is not None and self.parallel.seq_parallel:
            # The seq-sharded prefill splits the prompt over the 'seq' axis;
            # right-pad T up to the mesh multiple (pad slots are masked out
            # of decode attention via prompt_lens, like any padding).
            seq_ax = self.parallel.mesh.shape["seq"]
            t = arr.shape[1]
            if t % seq_ax:
                pad = seq_ax - t % seq_ax
                arr = np.pad(arr, ((0, 0), (0, pad)), constant_values=tok.pad_id)
        return jnp.asarray(arr), jnp.asarray(lens), n_real

    def _session_turn(self, sess, chunk, lens, n_new: int, seed: int | None) -> GenerationResult:
        from . import session as session_lib

        t = int(chunk.shape[1])
        if sess.base + t + n_new > sess.max_len:
            raise ValueError(
                f"session {sess.sid}: {sess.base} used + {t} chunk + {n_new} "
                f"new tokens exceeds session max_len {sess.max_len}"
            )
        tok = self.tokenizer
        rng = jax.random.key(seed if seed is not None else self.rt.seed)
        t0 = time.perf_counter()
        with self._timer.step(tokens=sess.n_real * n_new):
            toks, cache, valid, real, spos = session_lib.session_step(
                self.params, self.cfg, chunk, lens,
                sess.real_lens, sess.valid_mask, sess.cache,
                jnp.int32(sess.base), rng,
                max_new_tokens=n_new,
                temperature=self.rt.temperature, top_k=self.rt.top_k,
                top_p=self.rt.top_p, eos_id=tok.eos_id, pad_id=tok.pad_id,
                forward_fn=self._forward_fn,
                slot_positions=sess.slot_positions,
            )
            out = _to_host(toks)[: sess.n_real]
        dt = time.perf_counter() - t0
        sess.cache, sess.valid_mask, sess.real_lens = cache, valid, real
        sess.slot_positions = spos
        sess.base += t + n_new
        texts = [tok.decode(row) for row in out]
        gen_count = int(out.shape[0] * out.shape[1])
        METRICS.inc("engine.generated_tokens", gen_count)
        METRICS.observe("engine.generate_seconds", dt)
        return GenerationResult(
            text=texts, tokens=out,
            prompt_tokens=int(np.asarray(lens)[: sess.n_real].sum()),
            generated_tokens=gen_count, seconds=dt,
        )

    def start_session(
        self, prompts: list[str], max_new_tokens: int | None = None,
        seed: int | None = None,
    ) -> tuple[str, GenerationResult]:
        """Open a session: prefill + decode, keeping the KV cache for
        continuation turns.  Returns (session_id, result)."""
        n_new = self.rt.max_decode_steps if max_new_tokens is None else max_new_tokens
        max_len = self._session_max_len()
        chunk, lens, n_real = self._encode_rows(prompts, batch=None)
        b, t = int(chunk.shape[0]), int(chunk.shape[1])
        if t + n_new > max_len:  # validate BEFORE allocating/registering
            raise ValueError(
                f"prompt ({t} padded tokens) + {n_new} new tokens exceeds "
                f"session max_len {max_len}"
            )
        self.sessions.make_room()  # evict an LRU cache before allocating ours
        cache = self._make_cache(self.cfg, b, max_len)
        valid = jnp.zeros((b, max_len), dtype=bool)
        real = jnp.zeros((b,), jnp.int32)
        sess = self.sessions.new_session(cache, valid, real, base=0, max_len=max_len)
        sess.n_real = n_real
        if self.cfg.sliding_window is not None:
            # Sliding-window session state: the padded multi-turn layout
            # makes slot != position, and the window mask compares positions
            # (session_step maintains the map turn by turn).
            sess.slot_positions = jnp.zeros((b, max_len), jnp.int32)
        try:
            res = self._session_turn(sess, chunk, lens, n_new, seed)
        except Exception:
            self.sessions.drop(sess.sid)  # no orphaned HBM cache on failure
            raise
        return sess.sid, res

    def continue_session(
        self, sid: str, prompts: list[str], max_new_tokens: int | None = None,
        seed: int | None = None,
    ) -> GenerationResult:
        """Append a turn to an existing session (restoring its cache from
        host DRAM first if it was spilled)."""
        sess = self.sessions.get(sid)
        if len(prompts) != sess.n_real:
            raise ValueError(
                f"session {sid} has {sess.n_real} rows; got {len(prompts)} prompts"
            )
        self.sessions.ensure_resident(sess)
        self.sessions.touch(sess)
        n_new = self.rt.max_decode_steps if max_new_tokens is None else max_new_tokens
        batch = int(sess.valid_mask.shape[0])
        chunk, lens, _ = self._encode_rows(prompts, batch=batch)
        return self._session_turn(sess, chunk, lens, n_new, seed)

    def end_session(self, sid: str) -> None:
        self.sessions.drop(sid)

    # -- continuous batching ------------------------------------------------

    def continuous_batcher(
        self, batch_slots: int = 8, max_len: int | None = None,
        chunk_steps: int = 8, paged_pages: int | None = None,
        page_size: int | None = None,
        prefix_cache: bool | None = None,  # None -> rt.prefix_cache;
        #   automatic hash-block KV reuse over the paged pool (needs paged
        #   mode — a config-inherited flag degrades with a warning where
        #   paged itself does)
        speculative: bool | None = None,  # None -> rt.spec_decode; needs an
        #   attached draft + greedy + a single-device engine (contiguous
        #   OR paged — the target's KV rides the shared page pool and the
        #   draft/verify window writes through the page tables; prefix
        #   cache, int8 pages, the swap tier and mixed budgets all
        #   compose).  Mesh engines serve plain
        prefill_chunk: int | None = None,  # chunked prefill: admit at most
        #   this many prompt tokens per scheduling round PER PENDING
        #   prefill (contiguous or paged, single-device or dp/tp mesh —
        #   paged finishes allocate pool pages on demand at the splice;
        #   see ContinuousBatcher.  Not with a speculative draft)
        prefill_concurrency: int = 2,  # chunked prefills in flight at once
        #   (1 restores the old one-at-a-time head-of-line behavior)
        faults: Any = None,  # FaultPlane | None; None -> parse rt.faults —
        #   deterministic fault injection into the batcher's hot paths
        #   (runtime/faults.py), the lever behind `dlt-serve --fault`
        kv_bits: int | None = None,  # None -> rt.kv_bits; 8 = int8 KV
        #   pages in the paged pool (blockwise absmax scales, dequant
        #   fused into the decode read) — needs paged mode, like the
        #   prefix cache: explicit conflicts error, config-inherited ones
        #   degrade with a warning
        host_pages: int | None = None,  # None -> rt.host_pages; > 0 arms
        #   the host-RAM tier behind the pool (swap-preemption + prefix-
        #   cache spill) — same paged-mode degradation policy
        overlap: bool | None = None,  # None -> rt.overlap; dispatch-ahead
        #   engine loop: chunk N+1 dispatches from the device-resident
        #   carry while chunk N's host work overlaps on the CPU (temp-0
        #   bytes identical either way; mesh-legal — the carry is
        #   replicated scheduling state)
        schedule: str | None = None,  # None -> rt.schedule; "mixed"
        #   (default) fuses pending prefill-chunk bites into the decode
        #   step as one token-budget program (runtime/scheduler.py —
        #   decode rows never stall for a serialized prefill forward);
        #   "alternate" keeps the classic serialized rounds.  Temp-0
        #   bytes identical either way.
        token_budget: int | None = None,  # None -> rt.token_budget; the
        #   per-step token budget the mixed policy sizes prefill bites
        #   against (decode legs claim n_active of it first).  0/None =
        #   prefill_chunk-sized bites.
        tenant_weights: "str | dict | None" = None,  # None ->
        #   rt.tenant_weights; "gold:4,free:1"-style weights turn the
        #   mixed policy into per-tenant weighted-fair admission
        #   (runtime/scheduler.py TenantScheduler) — submit(tenant=)
        #   bills each request's virtual token counter.  "" disables.
        tenant_max_rows: int | None = None,  # None -> rt.tenant_max_rows;
        #   per-tenant resident-row cap (0 = uncapped).
    ):
        """A ContinuousBatcher over this engine's model: requests admit into
        an in-flight decode batch as rows free up (runtime/batcher.py) —
        no head-of-line blocking on mixed-length traffic.  Single-device
        engines and GSPMD data/tensor-parallel meshes — paged mode
        included (the pool shards KV heads over 'model'; per-chip
        capacity multiplies by the mesh); pipelined and sequence-parallel
        meshes keep their own decode schedules (the batcher constructor
        rejects them).  Paged mode is overload-safe:
        rows admit with prompt + one decode page, grow on demand at chunk
        boundaries, and a dry pool preempts the lowest-priority /
        most-recently-admitted row for recompute (temp-0 exact) instead of
        wedging — see submit(priority=, deadline=).
        """
        if self.parallel is not None and (
            self.parallel.pipelined or self.parallel.seq_parallel
        ):
            raise ValueError(
                "continuous batching requires a single-device engine or a "
                "pure data/tensor-parallel mesh (no pipe/seq axes)"
            )
        from .batcher import ContinuousBatcher

        # RuntimeConfig knobs are the defaults so the cluster worker's
        # mixed-budget endpoint serves paged when the config says to;
        # explicit arguments win (paged_pages=0 explicitly requests
        # contiguous even on a paged-configured engine).
        explicit = paged_pages is not None
        if paged_pages is None:
            paged_pages = self.rt.paged_pages
        if paged_pages == 0:
            paged_pages = None
        if page_size is None:
            page_size = self.rt.page_size
        explicit_cache = prefix_cache is not None
        if prefix_cache is None:
            prefix_cache = self.rt.prefix_cache
        if paged_pages is not None and self.parallel is not None:
            # Mesh-native paged serving: the pool shards its KV-head axis
            # over 'model' (batcher + parallel.specs.page_pool_specs), so
            # the head count must divide.  Explicit requests that cannot
            # shard error loudly; a config-inherited paged_pages on a
            # mesh whose head count doesn't divide degrades to contiguous
            # with a warning (the shared cluster-config policy every
            # paged knob follows).
            tp = self.parallel.mesh.shape.get("model", 1)
            if tp > 1 and self.cfg.num_kv_heads % tp:
                if explicit:
                    raise ValueError(
                        f"paged KV on this mesh shards the pool on the "
                        f"KV-head axis: num_kv_heads "
                        f"{self.cfg.num_kv_heads} does not divide over "
                        f"'model' ({tp}); pass paged_pages=0 or reshape "
                        f"the mesh"
                    )
                log.warning(
                    "runtime.paged_pages=%d ignored: num_kv_heads %d does "
                    "not divide over the mesh 'model' axis (%d); serving "
                    "contiguous", paged_pages, self.cfg.num_kv_heads, tp,
                )
                paged_pages = None
        if prefix_cache and paged_pages is None:
            if explicit_cache:
                raise ValueError(
                    "automatic prefix caching needs the paged KV pool; "
                    "pass paged_pages (or set runtime.paged_pages)"
                )
            # Config-inherited flag on an engine that serves contiguous
            # (e.g. a mesh worker sharing a paged cluster config): degrade
            # instead of erroring, like paged itself does above.
            log.warning(
                "runtime.prefix_cache ignored: this engine serves "
                "contiguous KV (no paged pool to cache pages in)"
            )
            prefix_cache = False
        # KV memory tiering: int8 pages and the host-RAM tier both live
        # behind the paged pool — explicit requests on a non-paged engine
        # error; config-inherited ones degrade with a warning (the shared
        # cluster-config policy every paged knob above follows).
        explicit_bits = kv_bits is not None
        if kv_bits is None:
            kv_bits = self.rt.kv_bits
        if kv_bits not in (16, 8):
            raise ValueError(f"kv_bits must be 16 or 8, got {kv_bits}")
        if kv_bits == 8 and paged_pages is None:
            if explicit_bits:
                raise ValueError(
                    "int8 KV pages live in the paged pool; pass "
                    "paged_pages (or set runtime.paged_pages)"
                )
            log.warning(
                "runtime.kv_bits=8 ignored: this engine serves contiguous "
                "KV (full-width cache)"
            )
            kv_bits = 16
        explicit_host = host_pages is not None
        if host_pages is None:
            host_pages = self.rt.host_pages
        if host_pages and paged_pages is None:
            if explicit_host:
                raise ValueError(
                    "the host-RAM KV tier backs the paged pool; pass "
                    "paged_pages (or set runtime.paged_pages)"
                )
            log.warning(
                "runtime.host_pages ignored: this engine serves "
                "contiguous KV (no paged pool to tier)"
            )
            host_pages = 0
        if overlap is None:
            overlap = self.rt.overlap
        if schedule is None:
            schedule = self.rt.schedule
        if token_budget is None:
            token_budget = self.rt.token_budget
        if token_budget == 0:  # the CLI/config "disable" spelling
            token_budget = None
        if tenant_weights is None:
            tenant_weights = self.rt.tenant_weights
        if tenant_weights == "":  # the CLI/config "disable" spelling
            tenant_weights = None
        if tenant_max_rows is None:
            tenant_max_rows = self.rt.tenant_max_rows
        if tenant_max_rows == 0:
            tenant_max_rows = None
        if self.parallel is not None:
            # The shared cache shards its batch over 'data'; round the slot
            # count up so every mesh shape serves (extra slots are harmless
            # capacity — the constructor would otherwise reject e.g. the
            # default 8 on a data=16 mesh).
            dp = self.parallel.mesh.shape.get("data", 1)
            batch_slots = -(-batch_slots // dp) * dp
        explicit_spec = speculative is not None
        if speculative is None:
            # Config-driven default mirrors generate_text's routing: only
            # when every precondition holds (never erroring where the plain
            # batcher works).  temperature == 0 keeps the flip-on-spec
            # bit-exactness contract; sampled speculation (distribution-
            # preserving, different RNG stream) is available by passing
            # speculative=True explicitly.  Paged pools compose since
            # round 17 (the draft/verify window writes through the page
            # tables), so paged engines speculate by default too.
            speculative = (
                self.rt.spec_decode
                and self.rt.temperature == 0.0
                and self.parallel is None
                and getattr(self, "draft_params", None) is not None
            )
        if speculative and prefill_chunk is not None and not explicit_spec:
            # Config-inherited degrade (the shared cluster-config policy
            # every paged knob follows): a config with spec_decode on must
            # not brick a server that also chunks prefills — the draft
            # admission prefills monolithically, so speculation turns off
            # with a warning.  An explicit speculative=True still errors
            # loudly in the batcher constructor.
            log.warning(
                "runtime.spec_decode ignored: chunked prefill is "
                "configured (prefill_chunk=%d) and the speculative draft "
                "admission prefills monolithically; serving plain",
                prefill_chunk,
            )
            speculative = False
        if speculative and (tenant_weights or tenant_max_rows) \
                and not explicit_spec:
            # Same config-inherited degrade: tenant weighted-fair
            # scheduling and the speculative round ledger do not compose
            # yet (make_scheduler rejects the pair loudly when
            # speculative=True is explicit).
            log.warning(
                "runtime.spec_decode ignored: tenant weighted-fair "
                "scheduling is configured and does not compose with "
                "speculative rounds yet; serving plain",
            )
            speculative = False
        spec_kwargs = {}
        if speculative:
            if getattr(self, "draft_params", None) is None:
                raise ValueError(
                    "speculative batching needs a draft: call "
                    "attach_draft(...) first"
                )
            spec_kwargs = dict(
                draft_params=self.draft_params, draft_cfg=self.draft_cfg,
                spec_k=self.rt.spec_k,
                spec_adaptive_k=self.rt.spec_adaptive_k,
            )
        if faults is None and self.rt.faults:
            # Config-driven fault plane (operator drills / CI): each batcher
            # gets its OWN plane so once-only rules stay once-only per
            # serving lifetime, not per respawn (respawn() shares the
            # instance by reference, preserving already-fired counters).
            from .faults import FaultPlane

            faults = FaultPlane.parse(self.rt.faults)
        tok = self.tokenizer
        return ContinuousBatcher(
            self.cfg, self.params, tokenizer=tok,
            **spec_kwargs,
            batch_slots=batch_slots,
            max_len=min(max_len or self.rt.max_seq_len, self.cfg.max_seq_len),
            chunk_steps=chunk_steps,
            temperature=self.rt.temperature, top_k=self.rt.top_k,
            top_p=self.rt.top_p, eos_id=tok.eos_id, pad_id=tok.pad_id,
            kv_dtype=self.rt.kv_cache_dtype,
            parallel=self.parallel,
            paged_pages=paged_pages, page_size=page_size,
            prefix_cache=bool(prefix_cache),
            prefill_chunk=prefill_chunk,
            prefill_concurrency=prefill_concurrency,
            faults=faults,
            kv_bits=kv_bits, host_pages=int(host_pages),
            overlap=bool(overlap),
            schedule=schedule, token_budget=token_budget,
            tenant_weights=tenant_weights, tenant_max_rows=tenant_max_rows,
        )

    # -- speculative decoding (runtime/speculative.py): greedy-exact at
    # temperature 0, distribution-preserving sampling above it ----------

    def _serves_quantized(self) -> bool:
        """Whether the decoder-block weights are resident as QuantizedTensor
        leaves (serve_quantized stores) — such params cannot be re-quantized
        into a self-draft."""
        from ..checkpoint.quantize import QuantizedTensor

        leaves = jax.tree_util.tree_leaves(
            self.params.get("blocks", {}),
            is_leaf=lambda x: isinstance(x, QuantizedTensor),
        )
        return any(isinstance(x, QuantizedTensor) for x in leaves)

    def attach_draft(
        self, draft_cfg: Any = None, draft_params: Any = None,
        quantize_bits: int | None = None,
    ) -> None:
        """Attach a draft model for ``generate_text_speculative``.

        Either pass an explicit ``(draft_cfg, draft_params)`` pair (any
        model sharing this engine's vocabulary — a smaller family member is
        the classic choice), or ``quantize_bits=4|8`` for self-speculation:
        the draft is this engine's own decoder blocks weight-only quantized
        (reads a fraction of the weight bytes per draft step, agrees with
        the target often — and exactness never depends on how often).
        """
        if quantize_bits is not None:
            if draft_cfg is not None or draft_params is not None:
                raise ValueError("pass draft_cfg/draft_params OR quantize_bits")
            from ..checkpoint.quantize import quantize_tree

            if self._serves_quantized():
                raise ValueError(
                    "engine already serves quantized weights; build the "
                    "draft explicitly (attach_draft(draft_cfg, draft_params))"
                )
            draft_cfg = self.cfg
            draft_params = {
                **self.params,
                "blocks": quantize_tree(self.params["blocks"], bits=quantize_bits),
            }
        if draft_cfg is None or draft_params is None:
            raise ValueError("need draft_cfg + draft_params (or quantize_bits)")
        if draft_cfg.vocab_size != self.cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab_size} != target vocab "
                f"{self.cfg.vocab_size}"
            )
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params

    def generate_text_speculative(
        self, prompts: list[str], max_new_tokens: int | None = None,
        k: int = 4, seed: int | None = None,
    ) -> GenerationResult:
        """Generation through the speculative decode loop — at temperature 0
        emits exactly ``generate_text``'s tokens; at temperature > 0 draws
        an exact sample from the same warped target distribution (rejection
        sampling — per-seed tokens differ from generate_text's because the
        RNG stream differs, the distribution does not).  Faster whenever the
        attached draft's acceptance covers its cost.  Single-device engines
        only (the loop drives models.model.forward directly)."""
        if getattr(self, "draft_params", None) is None:
            raise ValueError("no draft attached; call attach_draft(...) first")
        if self.parallel is not None:
            raise ValueError(
                "speculative decoding is single-device for now (mesh engines "
                "serve via generate_text / continuous_batcher)"
            )
        prompt_arr, lens, n_real = self._encode_rows(prompts, batch=None)
        n_new = self.rt.max_decode_steps if max_new_tokens is None else max_new_tokens
        gen_lib.check_sequence_budget(
            prompt_arr.shape[1] + k + 1, n_new, self.rt, self.cfg
        )
        return self._speculative_result(prompt_arr, lens, n_real, n_new, k, seed)

    def _speculative_result(
        self, prompt_arr, lens, n_real: int, n_new: int, k: int,
        seed: int | None = None,
    ) -> GenerationResult:
        """Shared tail of generate_text (spec_decode routing) and
        generate_text_speculative: inputs are pre-encoded and budget-checked.
        Mirrors the plain path's observability (profile trace,
        generate_seconds, memory stats) — flipping spec_decode on must not
        flatline a latency dashboard."""
        from .speculative import speculative_generate_tokens

        tok = self.tokenizer
        rng = (
            jax.random.key(seed if seed is not None else self.rt.seed)
            if self.rt.temperature > 0.0 else None
        )
        profile_ctx = (
            profiling.trace(self.rt.profile_dir)
            if self.rt.profile_dir
            else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        with profile_ctx, self._timer.step(tokens=n_real * n_new):
            out, stats = speculative_generate_tokens(
                self.params, self.cfg, self.draft_params, self.draft_cfg,
                jnp.asarray(prompt_arr), jnp.asarray(lens),
                k=k, max_new_tokens=n_new,
                eos_id=tok.eos_id, pad_id=tok.pad_id, return_stats=True,
                temperature=self.rt.temperature, top_k=self.rt.top_k,
                top_p=self.rt.top_p, rng=rng,
            )
            out = _to_host(out)[:n_real]
        dt = time.perf_counter() - t0
        profiling.record_memory_stats()
        drafted = max(int(stats["drafted"]), 1)
        METRICS.inc("engine.generated_tokens", int(out.shape[0] * out.shape[1]))
        METRICS.observe("engine.generate_seconds", dt)
        METRICS.observe("engine.spec_acceptance",
                        int(stats["accepted"]) / drafted)
        return GenerationResult(
            text=[tok.decode(row) for row in out], tokens=out,
            prompt_tokens=int(np.asarray(lens)[:n_real].sum()),
            generated_tokens=int(out.shape[0] * out.shape[1]), seconds=dt,
        )
