"""Inference engine: ties config + params + mesh + decode loop together.

Functional successor of the reference's MasterNode inference surface
(initialize_model / run_inference, src/master/node.py:54-138) minus the
socket runtime: model placement is ``device_put`` onto a mesh, inference is a
jit-compiled generate, results are decoded text (the reference returned raw
pickled partials, defect D9).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

import contextlib

from ..core.config import Config, ModelConfig, RuntimeConfig
from ..core.observability import METRICS, get_logger
from ..core import profiling
from ..models import model as model_lib
from ..models.presets import get_preset
from . import generate as gen_lib
from .tokenizer import get_tokenizer, pad_batch

log = get_logger("engine")


@dataclass
class GenerationResult:
    text: list[str]
    tokens: np.ndarray  # [B, N]
    prompt_tokens: int
    generated_tokens: int
    seconds: float

    @property
    def tokens_per_second(self) -> float:
        return self.generated_tokens / max(self.seconds, 1e-9)


class InferenceEngine:
    """Inference engine, single-device or mesh-parallel.

    `params` may come from the checkpoint converter (real weights) or
    ``init_params`` (random, for benchmarks) — the engine is agnostic.
    With ``parallel`` (a parallel.api.ParallelModel) the params are placed
    onto the mesh (``device_put`` per NamedSharding — the reference's
    "distribute" without tensor bytes on a socket) and generation runs the
    pipelined / tensor-parallel forward.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        rt: RuntimeConfig,
        params: Any,
        tokenizer=None,
        parallel: Any = None,  # parallel.api.ParallelModel
    ) -> None:
        self.cfg = cfg
        self.rt = rt
        self.parallel = parallel
        self.tokenizer = tokenizer or get_tokenizer(None)
        # Out-of-vocab ids silently become NaN embeddings (jnp.take fills
        # OOB gathers) — reject the mismatch loudly instead.
        tok_vocab = getattr(self.tokenizer, "vocab_size", None)
        if tok_vocab is not None and tok_vocab > cfg.vocab_size:
            raise ValueError(
                f"tokenizer vocab ({tok_vocab}, incl. specials) exceeds model "
                f"vocab ({cfg.vocab_size}); token ids would be out of range"
            )
        if parallel is not None:
            self.params = parallel.shard_params(params)
            self._forward_fn = parallel.as_forward_fn()
            self._make_cache = parallel.as_make_cache()
        else:
            self.params = params
            self._forward_fn = None  # generate_tokens' single-device default
            # KV-cache dtype knob: bound once so the jitted decode sees a
            # stable (identity-hashed) make_cache and caches the compilation.
            kv_dtype = jnp.dtype(rt.kv_cache_dtype)
            self._make_cache = lambda cfg_, b, s: model_lib.init_cache(
                cfg_, b, s, dtype=kv_dtype
            )
        self._timer = profiling.StepTimer("engine.generate")

    @classmethod
    def from_preset(
        cls, name: str, rt: RuntimeConfig | None = None, rng_seed: int = 0, **overrides
    ) -> "InferenceEngine":
        cfg = get_preset(name, **overrides)
        params = model_lib.init_params(jax.random.key(rng_seed), cfg)
        return cls(cfg, rt or RuntimeConfig(), params)

    @classmethod
    def from_store(
        cls,
        store_dir: str,
        rt: RuntimeConfig | None = None,
        mesh_cfg: Any = None,  # core.config.MeshConfig
        tokenizer=None,
    ) -> "InferenceEngine":
        """Build from a shard store, optionally mesh-parallel.

        This is the product path the reference promised (split one model
        across workers, src/master/node.py:84-115) done TPU-native: the mesh
        comes from ``Config.mesh``, microbatches from
        ``RuntimeConfig.microbatches``, placement is ``device_put``.
        """
        from ..checkpoint import store as store_lib
        from ..core.config import ModelConfig

        rt = rt or RuntimeConfig()
        manifest = store_lib.load_manifest(store_dir)
        if manifest.get("model_config") is None:
            raise ValueError(f"store {store_dir} has no embedded model_config")
        cfg = ModelConfig(**manifest["model_config"])
        params = store_lib.reconstruct(store_dir, dtype=cfg.dtype)
        parallel = None
        if mesh_cfg is not None and mesh_cfg.num_devices > 1:
            from ..parallel.api import make_parallel_model

            parallel = make_parallel_model(
                cfg, mesh_cfg,
                num_microbatches=max(rt.microbatches, 1),
                kv_dtype=rt.kv_cache_dtype,
            )
        return cls(cfg, rt, params, tokenizer=tokenizer, parallel=parallel)

    def _batch_multiple(self) -> int:
        """Batch rows must divide evenly over the data axis, times the
        microbatch count when the pipeline schedule splits the batch."""
        if self.parallel is None:
            return 1
        data = self.parallel.mesh.shape.get("data", 1)
        mb = self.parallel.num_microbatches if self.parallel.pipelined else 1
        return max(mb, 1) * data

    def generate_text(
        self, prompts: list[str], max_new_tokens: int | None = None, seed: int | None = None
    ) -> GenerationResult:
        tok = self.tokenizer
        seqs = [tok.encode(p) for p in prompts]
        # Pad the batch up to the mesh's divisibility requirement with dummy
        # rows (dropped after decode) so a single REPL prompt still serves
        # through a microbatched pipeline.
        n_real = len(seqs)
        mult = self._batch_multiple()
        while len(seqs) % mult:
            seqs.append(seqs[0])
        prompt_arr, lens = pad_batch(seqs, tok.pad_id)
        n_new = self.rt.max_decode_steps if max_new_tokens is None else max_new_tokens
        gen_lib.check_sequence_budget(prompt_arr.shape[1], n_new, self.rt, self.cfg)
        rng = jax.random.key(seed if seed is not None else self.rt.seed)

        profile_ctx = (
            profiling.trace(self.rt.profile_dir)
            if self.rt.profile_dir
            else contextlib.nullcontext()
        )
        t0 = time.perf_counter()
        with profile_ctx, self._timer.step(tokens=n_real * n_new):
            out = gen_lib.generate_tokens(
                self.params, self.cfg,
                jnp.asarray(prompt_arr), jnp.asarray(lens), rng,
                max_new_tokens=n_new,
                temperature=self.rt.temperature, top_k=self.rt.top_k, top_p=self.rt.top_p,
                eos_id=tok.eos_id, pad_id=tok.pad_id,
                forward_fn=self._forward_fn, make_cache=self._make_cache,
            )
            out = np.asarray(jax.block_until_ready(out))[:n_real]
        dt = time.perf_counter() - t0
        profiling.record_memory_stats()

        texts = [tok.decode(row) for row in out]
        gen_count = int(out.shape[0] * out.shape[1])
        METRICS.inc("engine.generated_tokens", gen_count)
        METRICS.observe("engine.generate_seconds", dt)
        return GenerationResult(
            text=texts, tokens=out,
            prompt_tokens=int(lens[:n_real].sum()), generated_tokens=gen_count,
            seconds=dt,
        )
