"""Host-RAM KV page tier (``--host-pages``) behind the paged pool.

Extracted from runtime/batcher.py (PR 9 introduced it inline; the round-16
scheduler extraction moved it here): the tier is STORAGE mechanism — swap
parcels for preemption victims and spilled prefix-cache pages, with a
single-worker D2H pipeline and checksum verification — while batcher.py
keeps the batching mechanism and runtime/scheduler.py the policy.  See
:class:`HostTier` for the contract; tests/runtime/test_kv_tiering.py pins
it (imports re-exported through runtime.batcher stay valid).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any

import numpy as np

from ..core.observability import METRICS, get_logger

log = get_logger("kv_tier")


# Machine-readable transition system for host-tier parcel ownership —
# the contract ``park_swap`` / ``take_swap`` / ``drop_swap`` and the
# spill plane implement, declared next to the code it models
# (PROTOCOL_MODELS["kv.parcels"], runtime/faults.py).  ``python -m
# tools.graftmodel`` explores every interleaving of three swap parcels
# and two spill slots over a shared page budget under the declared
# kv.swap_out / kv.swap_in / kv.spill fault actions, checking GM2 on
# every reachable state: a parked parcel is owned by exactly one queued
# resume, a settled parcel by none, and the budget equals the parked
# bytes exactly (released even when verification fails).  Swap phases:
# 0 victim about to swap, 1 parked (owned), 2 restored byte-exact,
# 3 degraded to exact recompute, 4 dropped (cancel/shed).  Spill
# phases: 0 cold pages ahead of eviction, 1 spilled, 2 restored,
# 3 evicted (plain eviction — correct, just slower).
PARCEL_MODEL = {
    "name": "kv.parcels",
    "doc": "host-tier swap/spill parcels: exactly-one-owner while "
           "parked, budget conserved, verify failure degrades to "
           "exact recompute",
    "params": {"PAGES": 2},
    "state": {"w0": 0, "w1": 0, "w2": 0, "own0": 0, "own1": 0, "own2": 0,
              "bad0": 0, "bad1": 0,
              "s0": 0, "s1": 0, "sbad0": 0, "sbad1": 0, "used": 0},
    "actions": [
        {"name": "park0", "guard": "w0 == 0 and used < PAGES",
         "update": {"w0": "1", "own0": "own0 + 1", "used": "used + 1"}},
        {"name": "park1", "guard": "w1 == 0 and used < PAGES",
         "update": {"w1": "1", "own1": "own1 + 1", "used": "used + 1"}},
        {"name": "park2", "guard": "w2 == 0 and used < PAGES",
         "update": {"w2": "1", "own2": "own2 + 1", "used": "used + 1"}},
        # Budget dry: park_swap returns None and the victim recomputes.
        {"name": "park_dry0", "guard": "w0 == 0 and used >= PAGES",
         "update": {"w0": "3"}},
        {"name": "park_dry1", "guard": "w1 == 0 and used >= PAGES",
         "update": {"w1": "3"}},
        {"name": "park_dry2", "guard": "w2 == 0 and used >= PAGES",
         "update": {"w2": "3"}},
        {"name": "take_ok0", "guard": "w0 == 1 and own0 == 1 and bad0 == 0",
         "update": {"w0": "2", "own0": "own0 - 1", "used": "used - 1"}},
        {"name": "take_ok1", "guard": "w1 == 1 and own1 == 1 and bad1 == 0",
         "update": {"w1": "2", "own1": "own1 - 1", "used": "used - 1"}},
        # Parcel 2 carries no fault edges: the plain path, kept in the
        # composition so faulted and clean parcels interleave.
        {"name": "take_ok2", "guard": "w2 == 1 and own2 == 1",
         "update": {"w2": "2", "own2": "own2 - 1", "used": "used - 1"}},
        # Checksum verify fails at take time: budget released anyway,
        # the request recomputes exactly.
        {"name": "take_bad0", "guard": "w0 == 1 and own0 == 1 and bad0 == 1",
         "update": {"w0": "3", "own0": "own0 - 1", "used": "used - 1",
                    "bad0": "0"}},
        {"name": "take_bad1", "guard": "w1 == 1 and own1 == 1 and bad1 == 1",
         "update": {"w1": "3", "own1": "own1 - 1", "used": "used - 1",
                    "bad1": "0"}},
        # Cancel/shed: drop_swap frees the parcel and its budget.
        {"name": "cancel0", "guard": "w0 == 1 and own0 == 1",
         "update": {"w0": "4", "own0": "own0 - 1", "used": "used - 1"}},
        {"name": "cancel1", "guard": "w1 == 1 and own1 == 1",
         "update": {"w1": "4", "own1": "own1 - 1", "used": "used - 1"}},
        {"name": "spill0", "guard": "s0 == 0 and used < PAGES",
         "update": {"s0": "1", "used": "used + 1"}},
        {"name": "spill1", "guard": "s1 == 0 and used < PAGES",
         "update": {"s1": "1", "used": "used + 1"}},
        # Spills are best-effort cache: evictable any time swaps need
        # room (oldest-first in code; order-free here).
        {"name": "spill_evict0", "guard": "s0 == 1 and sbad0 == 0",
         "update": {"s0": "3", "used": "used - 1"}},
        {"name": "spill_evict1", "guard": "s1 == 1 and sbad1 == 0",
         "update": {"s1": "3", "used": "used - 1"}},
        {"name": "spill_restore0", "guard": "s0 == 1 and sbad0 == 0",
         "update": {"s0": "2", "used": "used - 1"}},
        {"name": "spill_restore1", "guard": "s1 == 1 and sbad1 == 0",
         "update": {"s1": "2", "used": "used - 1"}},
        # Restore verification rejects a corrupt spill: cold prefill.
        {"name": "spill_restore_bad0", "guard": "s0 == 1 and sbad0 == 1",
         "update": {"s0": "3", "used": "used - 1", "sbad0": "0"}},
        {"name": "spill_restore_bad1", "guard": "s1 == 1 and sbad1 == 1",
         "update": {"s1": "3", "used": "used - 1", "sbad1": "0"}},
    ],
    "faults": [
        {"name": "swapout_drop0", "site": "kv.swap_out", "action": "drop",
         "metric": "batcher.kv_swaps.fallback",
         "guard": "w0 == 0", "update": {"w0": "3"}},
        {"name": "swapout_drop1", "site": "kv.swap_out", "action": "drop",
         "metric": "batcher.kv_swaps.fallback",
         "guard": "w1 == 0", "update": {"w1": "3"}},
        {"name": "swapout_corrupt0", "site": "kv.swap_out",
         "action": "corrupt", "metric": "batcher.kv_swaps.fallback",
         "guard": "w0 == 1 and bad0 == 0", "update": {"bad0": "1"}},
        {"name": "swapout_corrupt1", "site": "kv.swap_out",
         "action": "corrupt", "metric": "batcher.kv_swaps.fallback",
         "guard": "w1 == 1 and bad1 == 0", "update": {"bad1": "1"}},
        {"name": "swapin_drop0", "site": "kv.swap_in", "action": "drop",
         "metric": "batcher.kv_swaps.fallback",
         "guard": "w0 == 1 and own0 == 1",
         "update": {"w0": "3", "own0": "own0 - 1", "used": "used - 1"}},
        {"name": "swapin_drop1", "site": "kv.swap_in", "action": "drop",
         "metric": "batcher.kv_swaps.fallback",
         "guard": "w1 == 1 and own1 == 1",
         "update": {"w1": "3", "own1": "own1 - 1", "used": "used - 1"}},
        {"name": "swapin_corrupt0", "site": "kv.swap_in", "action": "corrupt",
         "metric": "batcher.kv_swaps.fallback",
         "guard": "w0 == 1 and bad0 == 0", "update": {"bad0": "1"}},
        {"name": "swapin_corrupt1", "site": "kv.swap_in", "action": "corrupt",
         "metric": "batcher.kv_swaps.fallback",
         "guard": "w1 == 1 and bad1 == 0", "update": {"bad1": "1"}},
        {"name": "spill_drop0", "site": "kv.spill", "action": "drop",
         "metric": "batcher.host_tier.spill_evictions",
         "guard": "s0 == 0", "update": {"s0": "3"}},
        {"name": "spill_drop1", "site": "kv.spill", "action": "drop",
         "metric": "batcher.host_tier.spill_evictions",
         "guard": "s1 == 0", "update": {"s1": "3"}},
        {"name": "spill_corrupt0", "site": "kv.spill", "action": "corrupt",
         "metric": "batcher.kv_swaps.fallback",
         "guard": "s0 == 1 and sbad0 == 0", "update": {"sbad0": "1"}},
        {"name": "spill_corrupt1", "site": "kv.spill", "action": "corrupt",
         "metric": "batcher.kv_swaps.fallback",
         "guard": "s1 == 1 and sbad1 == 0", "update": {"sbad1": "1"}},
    ],
    "invariants": [
        {"rule": "GM2", "name": "parked-implies-exactly-one-owner",
         "expr": "(w0 != 1 or own0 == 1) and (w1 != 1 or own1 == 1) "
                 "and (w2 != 1 or own2 == 1)"},
        {"rule": "GM2", "name": "settled-implies-zero-owners",
         "expr": "(w0 == 1 or own0 == 0) and (w1 == 1 or own1 == 0) "
                 "and (w2 == 1 or own2 == 0)"},
        {"rule": "GM2", "name": "never-multi-owned",
         "expr": "own0 <= 1 and own1 <= 1 and own2 <= 1"},
        {"rule": "GM2", "name": "budget-equals-parked-bytes",
         "expr": "used == (w0 == 1) + (w1 == 1) + (w2 == 1) "
                 "+ (s0 == 1) + (s1 == 1)"},
        {"rule": "GM2", "name": "budget-never-oversubscribed",
         "expr": "0 <= used <= PAGES"},
    ],
    # Stuck only once every parcel settled (restored / recomputed /
    # dropped) and every spill slot resolved (restored / evicted) —
    # a parcel parked forever with no owner is a stranded parcel.
    "terminal": "w0 in (2, 3, 4) and w1 in (2, 3, 4) and w2 in (2, 3, 4) "
                "and s0 in (2, 3) and s1 in (2, 3)",
}


@dataclass
class _HostEntry:
    """One host-tier parcel: ``future`` resolves (on the tier's worker
    thread) to ``(arrays, checksum)`` — an INDEPENDENT host-numpy copy of
    a raw page export plus its blake2b checksum.  Swap parcels hold a
    whole row (``index`` None); a spill entry holds exactly one page
    (``index`` records which slice of the gathered stack it copied out —
    every entry owns its own bytes, so eviction frees them)."""

    n_pages: int
    future: Any
    index: int | None = None


class HostTier:
    """Host-RAM KV page tier behind the :class:`PagePool` (``--host-pages``).

    Two kinds of parcels, one page budget:

    - **swap parcels**: a preempted row's pages, raw pool bytes, keyed by
      an opaque handle carried on the requeued request — restore scatters
      them back instead of recomputing the prefix;
    - **spilled pages**: cold prefix-cache pages captured just before LRU
      eviction, keyed by content digest — a later cache hit restores them
      instead of re-prefilling.

    Swaps outrank spills: parking a swap may evict spilled pages (they are
    only a cache), never the other way.  Device-to-host copies and
    checksumming run on a single worker thread (``park_*`` merely submits
    the already-dispatched device gather), so the engine loop never blocks
    on a D2H transfer at preemption time; ``take_*`` joins the future and
    VERIFIES the checksum — a corrupted parcel degrades to exact recompute
    / cold prefill rather than poisoning the cache.

    Thread contract: park/take/drop run under ``_lock`` (engine thread,
    plus the serving thread's cancel path); the worker thread touches only
    its own future's payload."""

    def __init__(self, pages: int) -> None:
        if pages < 1:
            raise ValueError(f"host tier needs >= 1 page, got {pages}")
        self.pages = pages
        self._lock = threading.Lock()
        # graftflow: cleanup-required
        self._swaps: dict[int, _HostEntry] = {}  # guarded-by: self._lock
        self._spills: OrderedDict[bytes, _HostEntry] = OrderedDict()  # guarded-by: self._lock
        self.used = 0  # guarded-by: self._lock
        self._next_handle = 0  # guarded-by: self._lock
        self._workers = None  # lazy single-thread executor

    # graftlint: holds(self._lock)
    def _executor(self):
        if self._workers is None:
            import concurrent.futures

            self._workers = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="kv-host-tier"
            )
        return self._workers

    @staticmethod
    def _checksum(arrays) -> bytes:
        h = hashlib.blake2b(digest_size=16)
        for a in arrays:
            h.update(np.ascontiguousarray(a).tobytes())
        return h.digest()

    @staticmethod
    def _flip_byte(arrays) -> tuple:
        """Corrupt a parcel in host storage (the ``corrupt`` fault drill):
        flip the first byte of the first array — checksum verification at
        take time must catch it."""
        raw = bytearray(np.ascontiguousarray(arrays[0]).tobytes())
        raw[0] ^= 0xFF
        bad = np.frombuffer(bytes(raw), dtype=arrays[0].dtype).reshape(
            arrays[0].shape
        )
        return (bad,) + tuple(arrays[1:])

    @classmethod
    def _to_host(cls, payload, corrupt: bool):
        """WORKER THREAD: device arrays -> host numpy + checksum.  The
        np.asarray calls are the actual D2H transfers."""
        arrays = tuple(np.asarray(a) for a in payload)
        checksum = cls._checksum(arrays)
        if corrupt:
            arrays = cls._flip_byte(arrays)
        return arrays, checksum

    @classmethod
    def _to_host_page(cls, payload, i: int, corrupt: bool):
        """WORKER THREAD: spill variant — ONE page's slices copied out
        independently (np.ascontiguousarray detaches from the stacked
        gather), so each spill entry owns exactly its own bytes: evicting
        it frees them, and the `pages` budget really bounds host RAM."""
        arrays = tuple(
            np.ascontiguousarray(np.asarray(a[:, i])) for a in payload
        )
        checksum = cls._checksum(arrays)
        if corrupt:
            arrays = cls._flip_byte(arrays)
        return arrays, checksum

    # graftlint: holds(self._lock)
    def _fit_locked(self, n: int) -> bool:
        """Make room for ``n`` pages, evicting spilled pages (oldest
        first) if needed — spills are only a cache.  Swap parcels are
        never evicted: their content is the ONLY copy of a live request's
        KV."""
        while self.pages - self.used < n and self._spills:
            self._spills.popitem(last=False)
            self.used -= 1
            METRICS.inc("batcher.host_tier.spill_evictions")
        return self.pages - self.used >= n

    def can_fit(self, n: int) -> bool:
        """Whether ``n`` pages could be parked right now (spills count as
        evictable).  Engine-thread advisory — the authoritative check is
        park's own."""
        with self._lock:
            return self.pages - self.used + len(self._spills) >= n

    def park_swap(self, payload, n_pages: int,
                  corrupt: bool = False) -> int | None:
        """Park a preempted row's raw page export; returns the handle the
        resume request carries, or None when the budget cannot fit it
        (the caller falls back to exact recompute)."""
        with self._lock:
            if not self._fit_locked(n_pages):
                return None
            fut = self._executor().submit(self._to_host, payload, corrupt)
            handle = self._next_handle
            self._next_handle += 1
            self.used += n_pages
            self._swaps[handle] = _HostEntry(n_pages, fut)
        return handle

    def take_swap(self, handle: int, corrupt: bool = False):
        """Resolve and REMOVE a swap parcel: returns the raw page arrays,
        or None when the handle is unknown or the checksum fails (the
        caller falls back to exact recompute either way).  Budget is
        released even on verification failure — the parcel is gone."""
        with self._lock:
            entry = self._swaps.pop(handle, None)
            if entry is None:
                return None
            self.used -= entry.n_pages
        try:
            arrays, checksum = entry.future.result()
        except Exception:
            # A failed D2H (host OOM, device error surfacing on the copy)
            # must degrade to exact recompute, not crash the engine —
            # the same contract as a checksum mismatch.
            log.exception("host-tier swap parcel %d copy failed", handle)
            return None
        if corrupt:
            arrays = self._flip_byte(arrays)
        if self._checksum(arrays) != checksum:
            log.warning("host-tier swap parcel %d failed verification", handle)
            return None
        return arrays

    def drop_swap(self, handle: int) -> None:
        """Free a swap parcel whose request will never resume (cancelled
        or shed while queued)."""
        with self._lock:
            entry = self._swaps.pop(handle, None)
            if entry is not None:
                self.used -= entry.n_pages

    def park_spill(self, digests: list[bytes], payload,
                   corrupt: bool = False) -> int:
        """Park soon-to-be-evicted cached pages (stacked raw export, one
        digest per page).  Best-effort: parks the prefix that fits after
        evicting older spills; returns how many pages were parked.  Each
        page gets its OWN worker task and host copy (never a shared
        stack), so the budget bounds actual host bytes: evicting an
        entry frees its pages."""
        with self._lock:
            room = 0
            for _ in digests:
                if not self._fit_locked(1):
                    break
                self.used += 1
                room += 1
            for i, d in enumerate(digests[:room]):
                fut = self._executor().submit(
                    self._to_host_page, payload, i, corrupt and i == 0
                )
                # Re-spilling content already parked would double-count
                # its budget page: drop the stale entry (its budget page
                # transfers to the fresh one reserved above).
                if d in self._spills:
                    self._spills.pop(d)
                    self.used -= 1
                self._spills[d] = _HostEntry(1, fut, index=i)
        return room

    def has_spill(self, digest: bytes) -> bool:
        with self._lock:
            return digest in self._spills

    def take_spill(self, digest: bytes):
        """Resolve and REMOVE one spilled page: returns its raw arrays
        ([L, BLK, ...] slices), or None when absent or corrupted (the
        caller prefillls cold — correct, just slower)."""
        with self._lock:
            entry = self._spills.pop(digest, None)
            if entry is None:
                return None
            self.used -= 1
        try:
            page, checksum = entry.future.result()
        except Exception:
            log.exception("host-tier spilled page copy failed")
            return None
        if self._checksum(page) != checksum:
            log.warning("host-tier spilled page failed verification")
            return None
        return page

    def stats(self) -> dict[str, int]:
        # Key names become batcher.host_tier.* GAUGES on /metrics
        # (publish_gauges): none may collide with a same-named counter —
        # "spill_entries" here vs the "spilled_pages" cumulative counter,
        # or the exposition renders one series under two TYPEs and the
        # whole scrape fails to parse.
        with self._lock:
            return {
                "pages": self.pages,
                "used": self.used,
                "swap_parcels": len(self._swaps),
                "spill_entries": len(self._spills),
            }

    def assert_consistent(self, swap_handles=()) -> None:
        """Audit the tier: budget accounting must equal the parcels held,
        and every parked swap handle must be owned by exactly one queued
        resume request (``swap_handles``) — a handle nobody will ever
        restore or free is a host-RAM leak, the tier's analogue of the
        pool's dangling refcount."""
        with self._lock:
            swaps = {h: e.n_pages for h, e in self._swaps.items()}
            spills = len(self._spills)
            used = self.used
        expect = set(swap_handles)
        held = set(swaps)
        assert used == sum(swaps.values()) + spills, (
            f"host tier budget diverged: used={used}, swaps={swaps}, "
            f"spilled={spills}"
        )
        assert used <= self.pages, (
            f"host tier over budget: {used} > {self.pages}"
        )
        assert held == expect, (
            f"host-tier swap handles diverge from queued resume requests: "
            f"parked={sorted(held)} expected={sorted(expect)}"
        )

