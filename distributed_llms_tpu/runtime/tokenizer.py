"""Tokenization front-end.

The reference tokenizes on the master with a HF tokenizer and ships raw bytes
(src/master/node.py:235-245, defect D4 — the json.dumps of bytes always
throws) and never detokenizes (SURVEY §2.5).  Here: a uniform interface with
two backends — HF tokenizers when the files are available, and an offline
byte-level fallback so the framework is usable with zero network access.
Both sides round-trip: encode -> generate -> decode.
"""

from __future__ import annotations

import numpy as np


def _plain_chat_template(messages: list[dict]) -> str:
    """Model-agnostic fallback chat layout: ``role: content`` lines plus a
    trailing assistant cue.  Used when no model template is available."""
    lines = [f"{m['role']}: {m['content']}" for m in messages]
    return "\n".join(lines) + "\nassistant:"


class ByteTokenizer:
    """Byte-level tokenizer: ids 0..255 are raw bytes; specials follow.
    Deterministic, offline, round-trips any UTF-8 text."""

    PAD, BOS, EOS = 256, 257, 258
    vocab_size = 259

    @property
    def pad_id(self) -> int:
        return self.PAD

    @property
    def eos_id(self) -> int:
        return self.EOS

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return [self.BOS] + ids if add_bos else ids

    def decode(self, ids) -> str:
        data = bytes(i for i in ids if 0 <= int(i) < 256)
        return data.decode("utf-8", errors="replace")

    def token_bytes(self, i: int) -> bytes | None:
        """The exact byte content of one token id (constrained decoding
        builds token-mask automata from this — runtime/constrain.py).
        None for specials/out-of-range ids: they carry no text and are
        masked out of every grammar."""
        return bytes([i]) if 0 <= i < 256 else None

    def apply_chat_template(self, messages: list[dict]) -> str:
        """Plain-text fallback template (no model-specific control tokens
        exist at the byte level)."""
        return _plain_chat_template(messages)


class HFTokenizer:
    """Wrapper over a transformers tokenizer (requires local files)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name_or_path)
        # len() includes added special tokens; .vocab_size does not.
        self.vocab_size = len(self._tok)

    @property
    def pad_id(self) -> int:
        pid = self._tok.pad_token_id
        return pid if pid is not None else (self._tok.eos_token_id or 0)

    @property
    def eos_id(self) -> int:
        return self._tok.eos_token_id if self._tok.eos_token_id is not None else -1

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids) -> str:
        return self._tok.decode([int(i) for i in ids], skip_special_tokens=True)

    def token_bytes(self, i: int) -> bytes | None:
        """Best-effort byte content of one token id for constrained
        decoding (runtime/constrain.py).  Specials, empty decodes, and
        tokens whose single-id decode is not faithful map to None
        (masked out of every grammar): a byte-level BPE vocabulary's
        UTF-8-FRAGMENT tokens decode to U+FFFD replacement characters,
        and building the automaton from those phantom bytes would
        enforce the grammar on content the model never emits —
        conservative masking keeps every allowed token's bytes exact
        (ASCII-coded grammars, i.e. all generated JSON structure, are
        unaffected; multi-byte text inside strings is reachable only
        through whole-character tokens)."""
        if not 0 <= i < self.vocab_size:
            return None
        if i in (self._tok.all_special_ids or ()):
            return None
        try:
            s = self._tok.decode([i])
        except Exception:
            return None
        if not s or "�" in s:
            return None
        return s.encode("utf-8")

    def apply_chat_template(self, messages: list[dict]) -> str:
        """The model's own chat template when it ships one (Llama/Mistral/
        Qwen/... control-token formats differ; the tokenizer files are the
        source of truth), else the plain-text fallback."""
        if getattr(self._tok, "chat_template", None):
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True
            )
        return _plain_chat_template(messages)


def get_tokenizer(name_or_path: str | None):
    """HF tokenizer if files exist locally, else the byte fallback (with a
    loud warning — byte ids into a real model's vocab are gibberish)."""
    if name_or_path:
        try:
            return HFTokenizer(name_or_path)
        except Exception as e:
            import logging

            logging.getLogger("tokenizer").warning(
                "could not load HF tokenizer %r (%s); falling back to "
                "byte-level tokenizer", name_or_path, e,
            )
    return ByteTokenizer()


def pad_batch(
    sequences: list[list[int]], pad_id: int, length: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Right-pad to a common length.  Returns (tokens [B, T], lens [B])."""
    lens = np.array([len(s) for s in sequences], dtype=np.int32)
    t = int(length if length is not None else max(1, lens.max()))
    if lens.max() > t:
        raise ValueError(f"sequence length {lens.max()} exceeds pad length {t}")
    out = np.full((len(sequences), t), pad_id, dtype=np.int32)
    for i, s in enumerate(sequences):
        out[i, : len(s)] = s
    return out, lens
