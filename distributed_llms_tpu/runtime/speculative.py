"""Speculative decoding: draft k tokens with a cheap model, verify them all
in ONE target forward, commit the longest agreeing prefix plus one token.

Decode on TPU is weight-bandwidth bound (BASELINE.md: one token per full
weight stream).  Verification reads the target's weights once per ROUND of
up to k+1 tokens instead of once per token, so end-to-end speed multiplies
by ~(mean accepted + 1) while the MXU does a (k+1)-token matmul it is far
better shaped for than single-token decode.  The reference framework has no
speculative path at all (its inference is one placeholder matmul per worker,
src/worker/node.py:24-32) — this is a beyond-parity serving feature.

EXACT at temperature 0: the emitted tokens are identical to
``generate.generate_tokens``'s, for ANY draft model and any k — the draft
only affects speed.  (tests/runtime/test_speculative.py pins this with a
deliberately different draft model.)

DISTRIBUTION-PRESERVING at temperature > 0 (speculative sampling,
Leviathan et al. 2023 / Chen et al. 2023): draft token d_j ~ q_j is
accepted iff u_j < p_j(d_j)/q_j(d_j); on the first rejection the
correction is drawn from normalize(max(p - q, 0)); after k acceptances
the bonus draws from p_{k+1} directly (the unified residual below: q is
zero-extended, so max(p - 0, 0) IS p).  The emitted sequence is an exact
sample from the target's warped (temperature/top-k/top-p) distribution —
the theorem, pinned empirically by tests/runtime/test_speculative.py's
residual-distribution test.  p and q are both post-warp distributions.

TPU-first formulation — the whole loop is one jitted ``lax.while_loop``
with static shapes:

- Rows advance by different amounts per round (per-row acceptance), so all
  cache writes use the per-row ``cache_index`` vector + explicit masks path
  of ``models.model._attention`` (the continuous batcher's machinery).
- Rollback is free: a rejected draft slot is never "undone" — the per-row
  attention masks cap every read at that row's committed frontier, and the
  slot is overwritten the next time the frontier reaches it.  The same
  argument keeps the DRAFT cache correct: its KVs match the committed
  sequence exactly up to the accepted prefix, and everything later is
  masked junk awaiting overwrite.

Slot convention (matches ``generate.generate_tokens``): emitted token i of
row b lives at cache slot T + i with RoPE position prompt_lens[b] + i; a
token's KV is written by the forward call that CONSUMES it.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from ..core.config import ModelConfig
from ..models import model as model_lib
from . import sampling


def greedy_accept_commit(
    drafts: jax.Array,   # [B, k] draft tokens d_1..d_k
    greedy: jax.Array,   # [B, k+1] target greedy tokens g_1..g_{k+1}
    live: jax.Array,     # [B] bool — rows that may commit this round
    budget: jax.Array,   # [B] int32 — tokens each row may still emit
    eos_id: int,
    k: int,
    k_row: jax.Array | None = None,  # [B] int32 — per-row effective draft
    #   length (the adaptive spec_k downshift): acceptance is clamped at
    #   j < k_row[b], so a row commits at most k_row[b]+1 tokens.  A
    #   forced stop at j == k_row emits greedy[j] — the token the
    #   sequential greedy decode would emit there — so the stream stays
    #   bit-identical at ANY per-row clamp; only arrival granularity
    #   changes.  Traced, so every clamp value shares one compiled
    #   program (graftcheck GC4 batcher.spec_chunk_paged).
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Greedy acceptance + commit bookkeeping — the SINGLE definition shared
    by the standalone loop and the batcher's spec_chunk (their only
    difference is the cache frontier convention, which stays at the call
    sites).  Returns (cand [B, k+1], m [B], has_eos [B], a [B]): commit
    cand[:m] per row; m accounts for EOS truncation, the budget clamp, and
    dead rows; a is the raw accepted-draft count (for acceptance stats)."""
    agree = drafts == greedy[:, :k]
    if k_row is not None:
        jk = jnp.arange(k, dtype=jnp.int32)
        agree = jnp.logical_and(agree, jk[None, :] < k_row[:, None])
    lead = jnp.cumprod(agree.astype(jnp.int32), axis=1)
    a = jnp.sum(lead, axis=1)                            # [B] in 0..k
    j_ar = jnp.arange(k + 1, dtype=jnp.int32)
    # Accepted drafts then the bonus/correction (greedy[j] at j == a).
    cand = jnp.where(j_ar[None, :] < a[:, None],
                     jnp.concatenate([drafts, drafts[:, -1:]], axis=1),
                     greedy)                             # [B, k+1]
    m, has_eos = commit_clamp(cand, a, live, budget, eos_id, k)
    return cand, m, has_eos, a


def commit_clamp(
    cand: jax.Array,   # [B, k+1] committed candidates
    a: jax.Array,      # [B] accepted-draft counts
    live: jax.Array,   # [B] bool
    budget: jax.Array, # [B] int32
    eos_id: int,
    k: int,
) -> tuple[jax.Array, jax.Array]:
    """The commit count: a+1 candidates, truncated at the first committed
    EOS (inclusive), clamped to the row's budget, zero for dead rows.
    Shared by the greedy and rejection-sampling paths."""
    j_ar = jnp.arange(k + 1, dtype=jnp.int32)
    m = a + 1
    b = cand.shape[0]
    if eos_id >= 0:
        is_eos = jnp.logical_and(cand == eos_id, j_ar[None, :] < m[:, None])
        eos_pos = jnp.argmax(is_eos, axis=1)
        has_eos = jnp.any(is_eos, axis=1)
        m = jnp.where(has_eos, jnp.minimum(m, eos_pos + 1), m)
    else:
        has_eos = jnp.zeros((b,), bool)
    m = jnp.minimum(m, budget)
    m = jnp.where(live, m, 0)
    return m, has_eos


def backfill_coords(
    cand: jax.Array,      # [B, k+1] committed candidates
    m: jax.Array,         # [B] committed counts
    frontier: jax.Array,  # [B] the slot the NEXT round's first feed writes
) -> tuple[jax.Array, jax.Array]:
    """Draft-backfill coordinates (shared by both spec loops): after a
    fully accepted round the draft never consumed the last accepted draft,
    leaving a KV hole one slot below the new frontier.  Rounds with
    2 <= m <= k rewrite an already-correct slot with the same token
    (harmless); m < 2 redirects to the frontier slot, which the next
    round's first feed overwrites before any query reads it."""
    bf_idx = jnp.where(m >= 2, frontier - 1, frontier)
    bf_tok = jnp.take_along_axis(
        cand, jnp.maximum(m - 2, 0)[:, None], axis=1
    )[:, 0]
    return bf_idx, bf_tok


def _prefill(params, cfg, prompt, prompt_lens, max_len):
    b, t = prompt.shape
    cache = model_lib.init_cache(cfg, b, max_len)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    logits, cache = model_lib.forward(
        params, cfg, prompt, positions=positions, cache=cache,
        cache_index=jnp.int32(0),
    )
    last = jnp.maximum(prompt_lens - 1, 0)
    return jnp.take_along_axis(logits, last[:, None, None], axis=1)[:, 0], cache


@partial(
    jax.jit,
    static_argnames=(
        "target_cfg", "draft_cfg", "k", "max_new_tokens", "eos_id", "pad_id",
        "return_stats", "temperature", "top_k", "top_p",
    ),
)
def speculative_generate_tokens(
    target_params: Any,
    target_cfg: ModelConfig,
    draft_params: Any,
    draft_cfg: ModelConfig,
    prompt: jax.Array,        # [B, T] int32, right-padded with pad_id
    prompt_lens: jax.Array,   # [B] int32 true lengths
    k: int = 4,               # draft tokens per round
    max_new_tokens: int = 32,
    eos_id: int = -1,         # -1 => never stops early
    pad_id: int = 0,
    return_stats: bool = False,
    temperature: float = 0.0,  # 0 => greedy (bit-exact); > 0 => speculative
    #                            sampling (distribution-preserving)
    top_k: int = 0,
    top_p: float = 1.0,
    rng: jax.Array | None = None,  # required when temperature > 0
) -> jax.Array | tuple[jax.Array, dict[str, jax.Array]]:
    """Speculative decode.  Returns new tokens [B, max_new_tokens]
    (positions after a row's EOS hold pad_id).  temperature == 0: greedy,
    bit-identical to ``generate_tokens(..., temperature=0.0)`` on the
    target alone.  temperature > 0: rejection sampling — an exact sample
    from the target's warped distribution (see module docstring); the RNG
    stream differs from generate_tokens', so per-seed tokens differ while
    the distribution does not.

    With ``return_stats``: also ``{"rounds": scalar, "drafted": scalar,
    "accepted": scalar}`` summed over the batch — mean accepted/drafted is
    the acceptance rate; (accepted + rounds·1)/rounds is tokens per target
    forward, the speedup lever.
    """
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    sampled = temperature > 0.0
    if sampled and rng is None:
        raise ValueError("temperature > 0 requires an rng key")
    for cfg, who in ((target_cfg, "target"), (draft_cfg, "draft")):
        if cfg.ragged_decode:
            # The ragged kernel reads each row's full slot prefix — including
            # right-pad slots the masks here exclude.
            raise ValueError(f"{who} cfg.ragged_decode is unsupported here")
    if target_cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError(
            "draft and target must share a vocabulary: "
            f"{draft_cfg.vocab_size} != {target_cfg.vocab_size}"
        )

    b, t = prompt.shape
    # Verify can write up to k+1 slots past the last in-budget frontier.
    max_len = t + max_new_tokens + k + 1
    tgt_logits0, tgt_cache = _prefill(
        target_params, target_cfg, prompt, prompt_lens, max_len
    )
    _, drf_cache = _prefill(draft_params, draft_cfg, prompt, prompt_lens, max_len)

    slots = jnp.arange(max_len, dtype=jnp.int32)          # [S]
    prompt_valid = slots[None, :] < prompt_lens[:, None]  # [B, S]
    rows = jnp.arange(b, dtype=jnp.int32)
    # Sliding-window models: true slot->position map for the window mask
    # (shared definition: generate.window_key_positions).
    from .generate import window_key_positions

    def _win_kwargs(cfg):
        if cfg.sliding_window is None:
            return {}
        return {"key_positions": window_key_positions(t, prompt_lens, max_len)}

    tgt_win = _win_kwargs(target_cfg)
    drf_win = _win_kwargs(draft_cfg)

    def gen_mask(e, q_off):
        """[B, 1, 1, S] valid-keys mask for a query at emitted-index
        e - 1 + q_off (its own write slot included)."""
        hi = t + e - 1 + q_off
        gen = jnp.logical_and(slots[None, :] >= t, slots[None, :] <= hi[:, None])
        return jnp.logical_or(prompt_valid, gen)[:, None, None, :]

    if sampled:
        rng, k0 = jax.random.split(rng)
        tok0 = sampling.sample(k0, tgt_logits0, temperature, top_k, top_p)
    else:
        rng = jax.random.key(0)  # uniform carry shape; never consumed
        tok0 = jnp.argmax(tgt_logits0, axis=-1).astype(jnp.int32)
    out0 = jnp.full((b, max_new_tokens + k + 1), pad_id, jnp.int32)
    out0 = out0.at[:, 0].set(tok0)
    e0 = jnp.ones((b,), jnp.int32)           # tokens emitted so far
    done0 = (tok0 == eos_id) if eos_id >= 0 else jnp.zeros((b,), bool)
    stats0 = jnp.zeros((3,), jnp.int32)      # rounds, drafted, accepted

    def cond(carry):
        _, _, _, e, _, done, _, _ = carry
        return jnp.any(jnp.logical_and(~done, e < max_new_tokens))

    def body(carry):
        tgt_cache, drf_cache, out, e, y, done, stats, rng = carry
        rng, kd, ku, kc = jax.random.split(rng, 4)

        # --- draft: k single-token steps (batched, per-row index).  When
        # sampling, each step also emits its full post-warp distribution
        # q_j — the rejection test needs q_j(d_j) and the residual needs
        # the whole vector.
        def draft_step(dc, inputs):
            drf_cache, cur = dc
            j, kj = inputs
            idx = t + e - 1 + j
            logits, drf_cache = model_lib.forward(
                draft_params, draft_cfg, cur[:, None],
                positions=(prompt_lens + e - 1 + j)[:, None],
                cache=drf_cache, cache_index=idx, attn_mask=gen_mask(e, j),
                **drf_win,
            )
            step_logits = logits[:, 0]
            if sampled:
                warped = sampling.warp_logits(
                    step_logits, temperature, top_k, top_p
                )
                nxt = jax.random.categorical(kj, warped, axis=-1).astype(
                    jnp.int32
                )
                q = jax.nn.softmax(warped, axis=-1)          # [B, V]
                return (drf_cache, nxt), (nxt, q)
            # Greedy emits only the token — no zero-sized q placeholder
            # through the scan (0-element carries inside scan-in-while_loop
            # are exactly the shape XLA:CPU handles worst).
            nxt = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
            return (drf_cache, nxt), nxt

        (drf_cache, _), draft_ys = jax.lax.scan(
            draft_step, (drf_cache, y),
            (jnp.arange(k, dtype=jnp.int32), jax.random.split(kd, k)),
        )
        if sampled:
            drafts, qs = draft_ys
            qs = jnp.moveaxis(qs, 0, 1)  # [B, k, V]
        else:
            drafts, qs = draft_ys, None
        drafts = drafts.T                # [B, k]: d_1..d_k

        # --- verify: ONE target forward over [y, d_1..d_k] (k+1 tokens).
        vtoks = jnp.concatenate([y[:, None], drafts], axis=1)  # [B, k+1]
        voff = jnp.arange(k + 1, dtype=jnp.int32)
        vmask = jnp.concatenate(
            [gen_mask(e, q) for q in range(k + 1)], axis=2
        )  # [B, 1, k+1, S]
        vlogits, tgt_cache = model_lib.forward(
            target_params, target_cfg, vtoks,
            positions=prompt_lens[:, None] + e[:, None] - 1 + voff[None, :],
            cache=tgt_cache, cache_index=t + e - 1, attn_mask=vmask,
            **tgt_win,
        )
        # Logits after consuming position j of the verify block predict
        # emitted index e+j.
        j_ar = jnp.arange(k + 1, dtype=jnp.int32)
        if sampled:
            ps = jax.nn.softmax(
                sampling.warp_logits(vlogits, temperature, top_k, top_p),
                axis=-1,
            )  # [B, k+1, V]
            # Rejection test: accept d_j iff u_j < p_j(d_j)/q_j(d_j)
            # (u in [0,1) makes min(1, ratio) implicit).
            p_at = jnp.take_along_axis(
                ps[:, :k], drafts[..., None], axis=-1
            )[..., 0]                                        # [B, k]
            q_at = jnp.take_along_axis(
                qs, drafts[..., None], axis=-1
            )[..., 0]                                        # [B, k]
            u = jax.random.uniform(ku, (b, k))
            accept = u * jnp.maximum(q_at, 1e-20) < p_at
            lead = jnp.cumprod(accept.astype(jnp.int32), axis=1)
            a = jnp.sum(lead, axis=1)                        # [B] in 0..k
            # Unified residual: zero-extend q so position k's "residual"
            # is p_{k+1} itself (the bonus draw).
            q_ext = jnp.concatenate(
                [qs, jnp.zeros_like(ps[:, :1])], axis=1
            )                                                # [B, k+1, V]
            p_a = jnp.take_along_axis(ps, a[:, None, None], axis=1)[:, 0]
            q_a = jnp.take_along_axis(q_ext, a[:, None, None], axis=1)[:, 0]
            resid = jnp.maximum(p_a - q_a, 0.0)
            norm = jnp.sum(resid, axis=-1, keepdims=True)
            # Numerical guard: p == q on the whole support leaves an empty
            # residual; fall back to p (any sample from it is valid there).
            resid = jnp.where(norm > 1e-9, resid / jnp.maximum(norm, 1e-9), p_a)
            corr = jax.random.categorical(
                kc,
                jnp.where(resid > 0, jnp.log(jnp.maximum(resid, 1e-30)),
                          -jnp.inf),
                axis=-1,
            ).astype(jnp.int32)                              # [B]
            cand = jnp.where(
                j_ar[None, :] < a[:, None],
                jnp.concatenate([drafts, drafts[:, -1:]], axis=1),
                corr[:, None],
            )                                                # [B, k+1]
            budget = max_new_tokens - e                      # pre-commit
            m, has_eos = commit_clamp(cand, a, ~done, budget, eos_id, k)
        else:
            greedy_toks = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
            budget = max_new_tokens - e                      # pre-commit
            cand, m, has_eos, a = greedy_accept_commit(
                drafts, greedy_toks, ~done, budget, eos_id, k
            )

        # Scatter the committed tokens into the (padded-wide) out buffer.
        valid = j_ar[None, :] < m[:, None]                   # [B, k+1]
        idx = jnp.where(valid, e[:, None] + j_ar[None, :],
                        out.shape[1] - 1)                    # scratch col
        vals = jnp.where(valid, cand, pad_id)
        out = out.at[rows[:, None], idx].set(vals)
        # (Duplicate scratch-column writes: XLA picks a winner; all pad_id.)
        out = out.at[:, out.shape[1] - 1].set(pad_id)

        y = jnp.where(
            m > 0, jnp.take_along_axis(cand, jnp.maximum(m - 1, 0)[:, None],
                                       axis=1)[:, 0], y,
        )
        e = e + m
        done = jnp.logical_or(done, jnp.logical_and(has_eos, m > 0))

        # --- draft backfill: after a FULLY accepted round (m == k+1) the
        # draft proposed d_k but never consumed it, leaving a zero-KV hole
        # one slot below the new frontier t+e-1 (backfill_coords has the
        # full rationale; a hole silently wrecks acceptance from then on).
        bf_idx, bf_tok = backfill_coords(cand, m, frontier=t + e - 1)
        bf_gen = jnp.logical_and(slots[None, :] >= t,
                                 slots[None, :] <= bf_idx[:, None])
        bf_mask = jnp.logical_or(prompt_valid, bf_gen)[:, None, None, :]
        _, drf_cache = model_lib.forward(
            draft_params, draft_cfg, bf_tok[:, None],
            positions=(prompt_lens + bf_idx - t)[:, None],
            cache=drf_cache, cache_index=bf_idx, attn_mask=bf_mask,
            **drf_win,
        )
        stats = stats + jnp.array([1, 0, 0], jnp.int32)
        # Drafted counts only drafts that HAD a chance to commit: the budget
        # caps a round at `budget` tokens, so at most min(k, budget) drafts
        # were in play — counting the full k would deflate the acceptance
        # rate of a perfect draft whenever (n-1) % (k+1) lands mid-round.
        # (EOS truncation still counts the post-EOS drafts: that loss is
        # data, not bookkeeping.)  Self-draft, no EOS => accepted == drafted
        # exactly, for ANY n and k — the verify invariant.
        stats = stats.at[1].add(
            jnp.sum(jnp.where(m > 0, jnp.minimum(k, budget), 0))
        )
        # Committed drafts this round: all m tokens when a clamp (EOS/budget)
        # cut the round short of its bonus token, else the a accepted drafts.
        stats = stats.at[2].add(jnp.sum(jnp.minimum(a, m)))
        return tgt_cache, drf_cache, out, e, y, done, stats, rng

    carry = (tgt_cache, drf_cache, out0, e0, tok0, done0, stats0, rng)
    *_, out, _, _, _, stats, _ = jax.lax.while_loop(cond, body, carry)
    toks = out[:, :max_new_tokens]
    if return_stats:
        return toks, {"rounds": stats[0], "drafted": stats[1],
                      "accepted": stats[2]}
    return toks
