"""Grammar-constrained structured output: token-mask automata for the
continuous batcher.

JSON-schema / regex constraints reduce to a finite-state token-mask
automaton (Willard & Louf 2023, "Outlines"): compile the constraint to a
byte-level DFA, then lift it to TOKEN level against the serving
tokenizer's vocabulary — for every (DFA state, token id) pair, walking the
token's bytes through the char DFA either survives (token allowed; the
end state is the transition target) or dies (token masked).  The result
is two dense tables the jitted decode step can gather from with zero host
work per token:

- ``bias  [n_states, V] float32`` — 0 for allowed tokens, a -1e30 mask
  for forbidden ones (plus any per-request ``logit_bias``), applied as
  ``logits + bias[state]`` before sampling — the same additive-warp shape
  as top-k/top-p masking, so constrained and free rows share ONE compiled
  decode program (free rows ride state 0, whose bias row is all zeros);
- ``next  [n_states, V] int32`` — the DFA transition per token (self-loop
  on EOS; 0 for masked tokens, which are never drawn).

Compilation is host-side numpy, paid once per (constraint, tokenizer)
pair and LRU-cached (``configure_cache``) — serving front-ends build the
automaton OFF the engine thread (``asyncio.to_thread`` in
runtime/server.py) and the batcher's ``submit`` then hits the cache.

Per-request ``logit_bias`` / ``banned_tokens`` ride the SAME mechanism as
a 1-state automaton whose single bias row carries the bias values — no
second mask path exists anywhere in the engine.

Grammar subset (documented in README "Structured output"):

- regex: literals, escapes (``\\d \\w \\s \\xNN`` + escaped specials),
  char classes ``[a-z0-9]`` / ``[^...]`` (byte-valued), ``.`` (any byte
  but newline), groups ``(...)``/``(?:...)``, alternation ``|``, and
  quantifiers ``* + ? {m} {m,} {m,n}`` (n <= 256).  Semantics are
  BYTE-level over the UTF-8 encoding (multi-byte characters are literal
  byte sequences), matching how byte-level vocabularies tokenize.
- JSON schema: ``type`` object/array/string/integer/number/boolean/null,
  ``enum``/``const``, nested compositions, ``minLength``/``maxLength``
  (strings; default max 64), ``minItems``/``maxItems`` (arrays; default
  max 8), ``minimum >= 0`` (drops the minus sign).  Every declared
  property must be listed in ``required`` (optional-property comma
  placement explodes the regex; rejected loudly, not silently wrong).
  Output is canonical compact JSON — always ``json.loads``-able.

Unsupported constructs raise :class:`ConstraintError` (a ``ValueError``:
serving front-ends answer a structured 400 before admission).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from dataclasses import dataclass

import numpy as np

from ..core.observability import METRICS, get_logger

log = get_logger("constrain")

# Mask value for forbidden tokens.  Finite on purpose: -inf would turn a
# fully-masked garbage row (an inactive slot's junk compute) into NaNs in
# the softmax, while -1e30 merely drives its probability to exactly 0 in
# float32 — and it dominates every finite logit/penalty/bias adjustment.
MASK = np.float32(-1e30)

# Compile-size guards: a pathological pattern must fail loudly at compile,
# not wedge the serving front-end enumerating states.
_MAX_CHAR_STATES = 4096
_MAX_REPEAT = 256


class ConstraintError(ValueError):
    """Malformed or unsupported constraint — serving answers 400."""


# ---------------------------------------------------------------------------
# regex -> byte-level DFA
# ---------------------------------------------------------------------------

_SPECIALS = set("\\.*+?()[]{}|")
_ANY_BYTE = frozenset(range(256))
_DIGITS = frozenset(range(ord("0"), ord("9") + 1))
_WORD = frozenset(
    list(range(ord("a"), ord("z") + 1)) + list(range(ord("A"), ord("Z") + 1))
    + list(_DIGITS) + [ord("_")]
)
_SPACE = frozenset(b" \t\n\r\f\v")


class _Parser:
    """Recursive-descent parser for the supported regex subset.  Produces
    an AST of tuples; all literals are BYTE sets (non-ASCII characters
    expand to their UTF-8 byte sequence)."""

    def __init__(self, pattern: str) -> None:
        self.p = pattern
        self.i = 0

    def error(self, msg: str) -> ConstraintError:
        return ConstraintError(
            f"regex error at offset {self.i}: {msg} (pattern {self.p!r})"
        )

    def peek(self) -> str | None:
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self) -> str:
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self):
        node = self.alt()
        if self.i != len(self.p):
            raise self.error(f"unexpected {self.p[self.i]!r}")
        return node

    def alt(self):
        branches = [self.seq()]
        while self.peek() == "|":
            self.take()
            branches.append(self.seq())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def seq(self):
        items = []
        while self.peek() not in (None, "|", ")"):
            items.append(self.repeat())
        if not items:
            return ("seq", [])
        return items[0] if len(items) == 1 else ("seq", items)

    def repeat(self):
        node = self.atom()
        while True:
            c = self.peek()
            if c == "*":
                self.take()
                node = ("rep", node, 0, None)
            elif c == "+":
                self.take()
                node = ("rep", node, 1, None)
            elif c == "?":
                self.take()
                node = ("rep", node, 0, 1)
            elif c == "{":
                node = self.braces(node)
            else:
                return node

    def braces(self, node):
        self.take()  # '{'
        spec = ""
        while self.peek() not in (None, "}"):
            spec += self.take()
        if self.peek() != "}":
            raise self.error("unterminated {m,n}")
        self.take()
        try:
            if "," in spec:
                lo_s, hi_s = spec.split(",", 1)
                lo = int(lo_s)
                hi = int(hi_s) if hi_s.strip() else None
            else:
                lo = hi = int(spec)
        except ValueError:
            raise self.error(f"bad repetition {{{spec}}}") from None
        if lo < 0 or (hi is not None and (hi < lo or hi > _MAX_REPEAT)):
            raise self.error(
                f"repetition bounds {{{spec}}} out of range (max "
                f"{_MAX_REPEAT})"
            )
        return ("rep", node, lo, hi)

    def atom(self):
        c = self.peek()
        if c is None:
            raise self.error("dangling quantifier or empty atom")
        if c == "(":
            self.take()
            if self.p[self.i: self.i + 2] == "?:":
                self.i += 2
            node = self.alt()
            if self.peek() != ")":
                raise self.error("unbalanced '('")
            self.take()
            return node
        if c == "[":
            return ("lit", self.char_class())
        if c == ".":
            self.take()
            return ("lit", _ANY_BYTE - {ord("\n")})
        if c == "\\":
            return ("lit", frozenset(self.escape()))
        if c in ")|":
            raise self.error(f"unexpected {c!r}")
        if c in "*+?{}":
            raise self.error(f"dangling quantifier {c!r}")
        self.take()
        enc = c.encode("utf-8")
        if len(enc) == 1:
            return ("lit", frozenset(enc))
        # Multi-byte character: a fixed byte sequence.
        return ("seq", [("lit", frozenset([b])) for b in enc])

    def escape(self) -> frozenset:
        self.take()  # '\'
        c = self.peek()
        if c is None:
            raise self.error("dangling backslash")
        self.take()
        if c == "d":
            return _DIGITS
        if c == "w":
            return _WORD
        if c == "s":
            return _SPACE
        if c == "n":
            return frozenset([ord("\n")])
        if c == "t":
            return frozenset([ord("\t")])
        if c == "r":
            return frozenset([ord("\r")])
        if c == "f":
            return frozenset([ord("\f")])
        if c == "v":
            return frozenset([ord("\v")])
        if c == "0":
            return frozenset([0])
        if c == "x":
            hexpart = self.p[self.i: self.i + 2]
            if len(hexpart) != 2:
                raise self.error("\\x needs two hex digits")
            try:
                b = int(hexpart, 16)
            except ValueError:
                raise self.error(f"bad \\x escape {hexpart!r}") from None
            self.i += 2
            return frozenset([b])
        if c in ("D", "W", "S", "b", "B", "A", "Z"):
            raise ConstraintError(
                f"unsupported escape \\{c} (grammar subset: \\d \\w \\s, "
                f"\\xNN, and escaped literals)"
            )
        enc = c.encode("utf-8")
        if len(enc) != 1:
            raise self.error(f"cannot escape multi-byte character {c!r}")
        return frozenset(enc)

    def char_class(self) -> frozenset:
        self.take()  # '['
        negate = self.peek() == "^"
        if negate:
            self.take()
        out: set[int] = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise self.error("unterminated character class")
            if c == "]" and not first:
                self.take()
                break
            first = False
            if c == "\\":
                charset = self.escape()
                if len(charset) == 1 and self.peek() == "-" \
                        and self.p[self.i + 1: self.i + 2] not in ("]", ""):
                    lo = next(iter(charset))
                    self.take()  # '-'
                    hi = self._class_byte()
                    if hi < lo:
                        raise self.error("reversed class range")
                    out.update(range(lo, hi + 1))
                else:
                    out.update(charset)
                continue
            lo = self._class_byte()
            if self.peek() == "-" and self.p[self.i + 1: self.i + 2] \
                    not in ("]", ""):
                self.take()  # '-'
                hi = self._class_byte()
                if hi < lo:
                    raise self.error("reversed class range")
                out.update(range(lo, hi + 1))
            else:
                out.add(lo)
        return frozenset(_ANY_BYTE - out) if negate else frozenset(out)

    def _class_byte(self) -> int:
        c = self.peek()
        if c == "\\":
            charset = self.escape()
            if len(charset) != 1:
                raise self.error("class range endpoint must be one byte")
            return next(iter(charset))
        self.take()
        enc = c.encode("utf-8")
        if len(enc) != 1:
            raise self.error(
                f"non-ASCII character {c!r} in class (use explicit byte "
                f"escapes)"
            )
        return enc[0]


def _nfa(node, eps, trans, counter):
    """Thompson construction: returns (start, end) for ``node``.  ``eps``
    maps state -> set of epsilon targets; ``trans`` maps state -> list of
    (byteset, target)."""

    def new():
        counter[0] += 1
        return counter[0] - 1

    kind = node[0]
    if kind == "lit":
        s, e = new(), new()
        trans.setdefault(s, []).append((node[1], e))
        return s, e
    if kind == "seq":
        s = e = new()
        for item in node[1]:
            si, ei = _nfa(item, eps, trans, counter)
            eps.setdefault(e, set()).add(si)
            e = ei
        return s, e
    if kind == "alt":
        s, e = new(), new()
        for item in node[1]:
            si, ei = _nfa(item, eps, trans, counter)
            eps.setdefault(s, set()).add(si)
            eps.setdefault(ei, set()).add(e)
        return s, e
    if kind == "rep":
        _, inner, lo, hi = node
        s = e = new()
        for _ in range(lo):
            si, ei = _nfa(inner, eps, trans, counter)
            eps.setdefault(e, set()).add(si)
            e = ei
        if hi is None:  # unbounded tail: one star
            si, ei = _nfa(inner, eps, trans, counter)
            eps.setdefault(e, set()).add(si)
            eps.setdefault(ei, set()).add(si)
            tail = new()
            eps.setdefault(e, set()).add(tail)
            eps.setdefault(ei, set()).add(tail)
            return s, tail
        tail = new()
        eps.setdefault(e, set()).add(tail)
        for _ in range(hi - lo):
            si, ei = _nfa(inner, eps, trans, counter)
            eps.setdefault(e, set()).add(si)
            e = ei
            eps.setdefault(e, set()).add(tail)
        return s, tail
    raise AssertionError(f"unknown AST node {kind!r}")


@dataclass(frozen=True)
class CharDFA:
    """Byte-level DFA: ``trans [n, 256] int32`` (-1 = dead) + accepting
    states.  State 0 is the start state; dead-end states (no path to any
    accept) are pruned, so every live state either accepts or has at
    least one outgoing byte."""

    trans: np.ndarray   # [n, 256] int32, -1 = no transition
    accept: np.ndarray  # [n] bool


def regex_to_char_dfa(pattern: str) -> CharDFA:
    """Compile the regex subset to a pruned byte-level DFA (full-match
    semantics — no anchors needed or supported)."""
    ast = _Parser(pattern).parse()
    eps: dict[int, set[int]] = {}
    trans: dict[int, list] = {}
    counter = [0]
    start, end = _nfa(ast, eps, trans, counter)

    def closure(states: frozenset) -> frozenset:
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in eps.get(s, ()):
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    start_set = closure(frozenset([start]))
    index = {start_set: 0}
    order = [start_set]
    rows: list[np.ndarray] = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        # Byte -> union of NFA targets, built per distinct byteset first.
        per_byte: list[set[int] | None] = [None] * 256
        for s in cur:
            for byteset, tgt in trans.get(s, ()):
                for b in byteset:
                    if per_byte[b] is None:
                        per_byte[b] = set()
                    per_byte[b].add(tgt)
        row = np.full((256,), -1, np.int32)
        memo: dict[frozenset, int] = {}
        for b in range(256):
            tgts = per_byte[b]
            if not tgts:
                continue
            key = frozenset(tgts)
            if key in memo:
                row[b] = memo[key]
                continue
            nxt = closure(key)
            if nxt not in index:
                if len(index) >= _MAX_CHAR_STATES:
                    raise ConstraintError(
                        f"constraint automaton exceeds {_MAX_CHAR_STATES} "
                        f"states; simplify the pattern/schema"
                    )
                index[nxt] = len(order)
                order.append(nxt)
            memo[key] = row[b] = index[nxt]
        rows.append(row)
    tmat = np.stack(rows) if rows else np.full((1, 256), -1, np.int32)
    accept = np.array([end in st for st in order], bool)
    if not accept.any():
        raise ConstraintError(f"regex {pattern!r} matches nothing")
    # Prune dead states (no path to an accept): reverse reachability.
    n = len(order)
    live = accept.copy()
    changed = True
    while changed:
        changed = False
        reaches = live[np.where(tmat >= 0, tmat, 0)] & (tmat >= 0)
        new_live = live | reaches.any(axis=1)
        if (new_live != live).any():
            live, changed = new_live, True
    if not live[0]:
        raise ConstraintError(f"regex {pattern!r} matches nothing")
    dead_tgt = ~live[np.where(tmat >= 0, tmat, 0)]
    tmat = np.where((tmat >= 0) & ~dead_tgt, tmat, -1).astype(np.int32)
    return CharDFA(trans=tmat, accept=accept)


def char_dfa_matches(dfa: CharDFA, data: bytes) -> bool:
    """Host-side full-match check (tests + the bench row's validation)."""
    s = 0
    for b in data:
        s = int(dfa.trans[s, b])
        if s < 0:
            return False
    return bool(dfa.accept[s])


# ---------------------------------------------------------------------------
# JSON schema -> regex
# ---------------------------------------------------------------------------

def _re_escape(s: str) -> str:
    return "".join("\\" + c if c in _SPECIALS else c for c in s)


def _json_literal_regex(value) -> str:
    try:
        text = json.dumps(value, separators=(",", ":"), ensure_ascii=False)
    except (TypeError, ValueError) as e:
        raise ConstraintError(f"unserializable enum/const value: {e}") from e
    return _re_escape(text)

# JSON string body bytes: printable ASCII minus '"' and '\'.  No escape
# sequences and no bytes >= 0x80 in GENERATED strings: a byte-level
# character class cannot enforce multi-byte UTF-8 SEQUENCING, and a lone
# high byte would make the output invalid UTF-8 — ASCII-only is what
# keeps every completion json.loads-able and schema-valid (byte length
# == character length, too).
_STRING_CHAR = '[^"\\\\\\x00-\\x1f\\x7f-\\xff]'

# ALLOWLIST, not a blocklist: a constraint keyword this compiler does not
# enforce (maximum, pattern, multipleOf, format, ...) must 400, never be
# silently ignored — the whole point of the feature is that the output
# provably satisfies the schema the caller sent.  Annotation-only keys
# ride along harmlessly.
_ALLOWED_KEYS = frozenset({
    "type", "enum", "const", "properties", "required", "items",
    "minLength", "maxLength", "minItems", "maxItems", "minimum",
    "additionalProperties", "title", "description", "$schema",
})


def schema_to_regex(schema) -> str:
    """Compile the supported JSON-schema subset to a regex over canonical
    compact JSON (module docstring lists the subset; anything else raises
    :class:`ConstraintError`)."""
    if not isinstance(schema, dict):
        raise ConstraintError("schema must be a JSON object")
    unknown = set(schema) - _ALLOWED_KEYS
    if unknown:
        raise ConstraintError(
            f"unsupported schema keyword(s) {sorted(unknown)} — the "
            f"grammar cannot enforce them, and silently ignoring a "
            f"constraint would emit output that violates the schema"
        )
    if schema.get("additionalProperties") not in (None, False, {}):
        # Generated objects are CLOSED by construction, so `false` is
        # exactly what the grammar already guarantees; anything else
        # would require enforcing an open-object grammar we don't have.
        raise ConstraintError(
            "additionalProperties must be false (generated objects are "
            "closed: exactly the declared required properties)"
        )
    if "const" in schema:
        return _json_literal_regex(schema["const"])
    if "enum" in schema:
        options = schema["enum"]
        if not isinstance(options, list) or not options:
            raise ConstraintError("'enum' must be a non-empty list")
        return "(?:" + "|".join(_json_literal_regex(v) for v in options) + ")"
    t = schema.get("type")
    if t not in ("integer", "number") and schema.get("minimum") is not None:
        raise ConstraintError("'minimum' applies to integer/number only")
    if t == "string":
        # BYTE lengths over the UTF-8 encoding — the automaton runs at
        # byte level, and validates() checks the same measure.
        lo = int(schema.get("minLength", 0))
        hi = int(schema.get("maxLength", 64))
        if not 0 <= lo <= hi or hi > _MAX_REPEAT:
            raise ConstraintError(
                f"string length bounds [{lo}, {hi}] out of range "
                f"(max {_MAX_REPEAT})"
            )
        return f'"{_STRING_CHAR}{{{lo},{hi}}}"'
    if t in ("integer", "number"):
        # The only enforceable bound is non-negativity: 'minimum': 0
        # drops the minus sign.  Any other value would admit outputs the
        # schema rejects (the digit grammar cannot count magnitudes), so
        # it 400s instead of silently under-constraining.
        minimum = schema.get("minimum")
        if minimum not in (None, 0):
            raise ConstraintError(
                f"'minimum' must be 0 or absent (got {minimum!r}) — the "
                f"digit grammar can only enforce non-negativity"
            )
        sign = "" if minimum == 0 else "-?"
        # Bounded digit count keeps the language finite, so every greedy
        # path reaches an accept state within a known budget.
        body = f"{sign}(?:0|[1-9][0-9]{{0,14}})"
        if t == "integer":
            return body
        return f"{body}(?:\\.[0-9]{{1,6}})?"
    if t == "boolean":
        return "(?:true|false)"
    if t == "null":
        return "null"
    if t == "array":
        item = schema_to_regex(schema.get("items", {"type": "null"}))
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", 8))
        if not 0 <= lo <= hi or hi > 64:
            raise ConstraintError(
                f"array bounds [{lo}, {hi}] out of range (max 64 items)"
            )
        if hi == 0:
            return "\\[\\]"
        core = f"{item}(?:,{item}){{{max(lo - 1, 0)},{hi - 1}}}"
        return f"\\[(?:{core})?\\]" if lo == 0 else f"\\[{core}\\]"
    if t == "object":
        props = schema.get("properties", {})
        if not isinstance(props, dict):
            raise ConstraintError("'properties' must be an object")
        required = schema.get("required", [])
        if set(props) != set(required):
            raise ConstraintError(
                "grammar subset: every declared property must be listed in "
                "'required' (optional properties are not supported)"
            )
        if not props:
            return "\\{\\}"
        parts = [
            f'"{_re_escape(k)}":{schema_to_regex(v)}'
            for k, v in props.items()
        ]
        return "\\{" + ",".join(parts) + "\\}"
    raise ConstraintError(
        f"unsupported schema type {t!r} (grammar subset: object/array/"
        f"string/integer/number/boolean/null/enum/const)"
    )


def validates(schema, value) -> bool:
    """Host-side instance check for the SAME subset ``schema_to_regex``
    compiles — the tests' and bench row's parse-valid oracle."""
    if "const" in schema:
        return value == schema["const"]
    if "enum" in schema:
        return value in schema["enum"]
    t = schema.get("type")
    if t == "string":
        # Byte lengths over UTF-8, matching the grammar's measure.
        return (isinstance(value, str)
                and int(schema.get("minLength", 0))
                <= len(value.encode("utf-8"))
                <= int(schema.get("maxLength", 64)))
    if t == "integer":
        return (isinstance(value, int) and not isinstance(value, bool)
                and value >= float(schema.get("minimum", float("-inf"))))
    if t == "number":
        return (isinstance(value, (int, float))
                and not isinstance(value, bool)
                and value >= float(schema.get("minimum", float("-inf"))))
    if t == "boolean":
        return isinstance(value, bool)
    if t == "null":
        return value is None
    if t == "array":
        return (isinstance(value, list)
                and int(schema.get("minItems", 0)) <= len(value)
                <= int(schema.get("maxItems", 8))
                and all(validates(schema.get("items", {"type": "null"}), v)
                        for v in value))
    if t == "object":
        props = schema.get("properties", {})
        return (isinstance(value, dict) and set(value) == set(props)
                and all(validates(props[k], value[k]) for k in value))
    return False


# ---------------------------------------------------------------------------
# token-level automaton
# ---------------------------------------------------------------------------

def _token_byte_table(tokenizer, vocab_size: int):
    """(bytes matrix [V, Lmax] int16 (-1 pad), lengths [V], fingerprint).
    Cached on the tokenizer object — built once per (tokenizer, vocab)."""
    cached = getattr(tokenizer, "_constrain_token_bytes", None)
    if cached is not None and cached[0] == vocab_size:
        return cached[1], cached[2], cached[3]
    rows: list[bytes] = []
    for i in range(vocab_size):
        tb = getattr(tokenizer, "token_bytes", None)
        raw = tb(i) if tb is not None else None
        if raw is None and tb is None and i < getattr(
                tokenizer, "vocab_size", 0):
            # Best-effort fallback for tokenizers without token_bytes.
            try:
                s = tokenizer.decode([i])
                raw = s.encode("utf-8") if s else None
            except Exception:
                raw = None
        rows.append(raw or b"")
    lens = np.array([len(r) for r in rows], np.int32)
    lmax = max(1, int(lens.max()))
    mat = np.full((vocab_size, lmax), -1, np.int16)
    for i, r in enumerate(rows):
        if r:
            mat[i, : len(r)] = np.frombuffer(r, np.uint8)
    fp = hashlib.blake2b(mat.tobytes(), digest_size=12).hexdigest()
    try:
        tokenizer._constrain_token_bytes = (vocab_size, mat, lens, fp)
    except Exception:  # a slotted/frozen tokenizer just recomputes
        pass
    return mat, lens, fp


@dataclass
class TokenDFA:
    """Token-level mask automaton.  ``bias[s]`` is the additive logit mask
    for state ``s`` (0 allowed / MASK forbidden, plus any logit_bias);
    ``next[s, t]`` the transition (EOS self-loops; masked entries are 0
    and never taken).  State 0 is the start state.  ``pattern`` is the
    source regex ("" for a pure bias/ban automaton)."""

    bias: np.ndarray     # [n_states, V] float32
    next: np.ndarray     # [n_states, V] int32
    accept: np.ndarray   # [n_states] bool
    allowed: np.ndarray  # [n_states, V] bool (pre-bias mask)
    eos_id: int
    pattern: str = ""

    @property
    def n_states(self) -> int:
        return self.bias.shape[0]

    def advance(self, state: int, toks) -> int:
        """Host-side replay: the DFA state after emitting ``toks`` from
        ``state``.  Preemption/swap resume rebuilds a row's device state
        this way — the state is a pure function of the emitted tokens, so
        nothing extra rides the requeued request."""
        for t in toks:
            t = int(t)
            if t == self.eos_id:
                return state
            if not self.allowed[state, t]:
                # Every emitted token was drawn under this mask; a miss
                # means the caller replayed a foreign stream.  Hold state
                # (masking stays sound) and say so.
                log.warning(
                    "DFA replay: token %d not allowed in state %d", t, state
                )
                return state
            state = int(self.next[state, t])
        return state

    def bias_row(self, state: int) -> np.ndarray:
        return self.bias[state]


def _lift_to_tokens(cdfa: CharDFA, token_mat: np.ndarray,
                    token_lens: np.ndarray, eos_id: int,
                    vocab_size: int) -> tuple[np.ndarray, np.ndarray]:
    """Walk every token's bytes through the char DFA from every state.
    Returns (allowed [n, V] bool, next [n, V] int32)."""
    n = cdfa.trans.shape[0]
    v = vocab_size
    allowed = np.zeros((n, v), bool)
    nxt = np.zeros((n, v), np.int32)
    lmax = token_mat.shape[1]
    has_bytes = token_lens > 0
    for s in range(n):
        cur = np.full((v,), s, np.int32)
        alive = has_bytes.copy()
        for p in range(lmax):
            col = token_mat[:, p]
            step = alive & (col >= 0)
            if not step.any():
                break
            tgt = cdfa.trans[cur[step], col[step].astype(np.int32)]
            cur[step] = tgt
            dead = np.zeros_like(alive)
            dead[step] = tgt < 0
            alive &= ~dead
        allowed[s] = alive
        nxt[s] = np.where(alive, np.maximum(cur, 0), 0)
    if 0 <= eos_id < v:
        allowed[:, eos_id] = cdfa.accept
        nxt[:, eos_id] = np.arange(n)
    return allowed, nxt


def _build_token_dfa(pattern: str, tokenizer, vocab_size: int,
                     eos_id: int) -> TokenDFA:
    cdfa = regex_to_char_dfa(pattern)
    token_mat, token_lens, _fp = _token_byte_table(tokenizer, vocab_size)
    allowed, nxt = _lift_to_tokens(
        cdfa, token_mat, token_lens, eos_id, vocab_size
    )
    # Reachability check AT TOKEN level: a state the decode can reach must
    # always offer at least one token (or EOS) — a byte path no token
    # realizes would otherwise dead-end the row mid-generation.
    reach = np.zeros((cdfa.trans.shape[0],), bool)
    reach[0] = True
    frontier = [0]
    while frontier:
        s = frontier.pop()
        if not allowed[s].any():
            raise ConstraintError(
                "tokenizer cannot realize this constraint: automaton state "
                f"{s} (pattern {pattern!r}) allows no token and no EOS"
            )
        for t in np.unique(nxt[s][allowed[s]]):
            if not reach[t]:
                reach[t] = True
                frontier.append(int(t))
    bias = np.where(allowed, np.float32(0.0), MASK).astype(np.float32)
    return TokenDFA(bias=bias, next=nxt, accept=cdfa.accept,
                    allowed=allowed, eos_id=eos_id, pattern=pattern)


# ---------------------------------------------------------------------------
# request-level compile + LRU cache
# ---------------------------------------------------------------------------

class _LRU:
    """Tiny thread-safe LRU for compiled automata (compile is host numpy
    work measured in ms-to-seconds; serving must pay it once per
    (constraint, tokenizer) pair)."""

    def __init__(self, capacity: int = 64) -> None:
        self.capacity = capacity
        self._lock = threading.Lock()
        self._data: dict = {}
        self.hits = 0
        self.misses = 0

    def get(self, key):
        with self._lock:
            if key in self._data:
                val = self._data.pop(key)
                self._data[key] = val  # move to MRU
                self.hits += 1
                return val
            self.misses += 1
            return None

    def put(self, key, val) -> None:
        with self._lock:
            self._data.pop(key, None)
            self._data[key] = val
            while len(self._data) > max(1, self.capacity):
                self._data.pop(next(iter(self._data)))

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self.hits = 0
            self.misses = 0


_CACHE = _LRU(64)


def configure_cache(capacity: int) -> None:
    """Resize the compile cache (``RuntimeConfig.constrain_cache_size`` /
    ``dlt-serve --constrain-cache``)."""
    if capacity < 1:
        raise ValueError(f"constrain cache capacity must be >= 1, got "
                         f"{capacity}")
    _CACHE.capacity = int(capacity)


def cache_stats() -> dict[str, int]:
    return {"hits": _CACHE.hits, "misses": _CACHE.misses,
            "size": len(_CACHE._data), "capacity": _CACHE.capacity}


def clear_cache() -> None:
    _CACHE.clear()


def _canon_response_format(response_format) -> tuple[str, str]:
    """Validate + canonicalize a ``response_format`` field.  Returns
    (kind, pattern): kind "regex"|"json_schema", pattern the regex to
    compile (schemas compile through :func:`schema_to_regex`)."""
    if not isinstance(response_format, dict):
        raise ConstraintError("'response_format' must be an object")
    kind = response_format.get("type")
    if kind == "regex":
        pattern = response_format.get("regex")
        if not isinstance(pattern, str) or not pattern:
            raise ConstraintError(
                "response_format.type 'regex' needs a non-empty 'regex' "
                "string"
            )
        return "regex", pattern
    if kind == "json_schema":
        spec = response_format.get("json_schema")
        if isinstance(spec, dict) and "schema" in spec:
            spec = spec["schema"]  # OpenAI nests {name, schema}
        if spec is None:
            spec = response_format.get("schema")
        if not isinstance(spec, dict):
            raise ConstraintError(
                "response_format.type 'json_schema' needs a 'json_schema' "
                "(or 'schema') object"
            )
        return "json_schema", schema_to_regex(spec)
    raise ConstraintError(
        f"response_format.type must be 'json_schema' or 'regex', got "
        f"{kind!r}"
    )


def _canon_bias(logit_bias, banned_tokens, vocab_size: int):
    """Validate logit_bias/banned_tokens.  Returns (bias items tuple,
    banned tuple) in canonical order."""
    items: list[tuple[int, float]] = []
    if logit_bias is not None:
        if not isinstance(logit_bias, dict) or not logit_bias:
            raise ConstraintError(
                "'logit_bias' must be a non-empty object of token id -> "
                "bias"
            )
        for k, val in logit_bias.items():
            try:
                tid = int(k)
            except (TypeError, ValueError):
                raise ConstraintError(
                    f"logit_bias key {k!r} is not a token id"
                ) from None
            if not 0 <= tid < vocab_size:
                raise ConstraintError(
                    f"logit_bias token {tid} outside vocab [0, {vocab_size})"
                )
            if isinstance(val, bool) or not isinstance(val, (int, float)) \
                    or not np.isfinite(val) or not -100.0 <= val <= 100.0:
                raise ConstraintError(
                    f"logit_bias value for token {tid} must be a finite "
                    f"number in [-100, 100], got {val!r}"
                )
            items.append((tid, float(val)))
    banned: list[int] = []
    if banned_tokens is not None:
        if not isinstance(banned_tokens, (list, tuple)) or not banned_tokens:
            raise ConstraintError(
                "'banned_tokens' must be a non-empty list of token ids"
            )
        for t in banned_tokens:
            if isinstance(t, bool) or not isinstance(t, int) \
                    or not 0 <= t < vocab_size:
                raise ConstraintError(
                    f"banned token {t!r} outside vocab [0, {vocab_size})"
                )
            banned.append(t)
    return tuple(sorted(set(items))), tuple(sorted(set(banned)))


def compile_request(response_format=None, logit_bias=None,
                    banned_tokens=None, *, tokenizer=None,
                    vocab_size: int, eos_id: int) -> TokenDFA | None:
    """THE front door: compile a request's constraint surface into one
    TokenDFA (or None when the request carries none).  Grammar constraints
    (``response_format``) and the bias ride-alongs fold into the SAME
    automaton: a pure logit_bias/ban request compiles to a 1-state DFA
    whose single bias row carries the values.  LRU-cached; raises
    :class:`ConstraintError` on malformed input (serving answers 400
    before admission)."""
    bias_items, banned = _canon_bias(logit_bias, banned_tokens, vocab_size)
    if response_format is None and not bias_items and not banned:
        return None
    pattern = ""
    if response_format is not None:
        kind, pattern = _canon_response_format(response_format)
        if tokenizer is None:
            raise ConstraintError(
                "constrained decoding needs a tokenizer (token-level masks "
                "are built against the vocabulary)"
            )
        if eos_id < 0:
            raise ConstraintError(
                "constrained decoding needs an EOS token to terminate "
                "accepted outputs (engine has eos_id < 0)"
            )
        del kind
    _, _, tok_fp = (_token_byte_table(tokenizer, vocab_size)
                    if response_format is not None else (None, None, "-"))
    key = (pattern, bias_items, banned, tok_fp, vocab_size, eos_id)
    hit = _CACHE.get(key)
    if hit is not None:
        METRICS.inc("batcher.constrain.cache_hits")
        return hit
    METRICS.inc("batcher.constrain.cache_misses")
    t0 = time.perf_counter()
    if response_format is not None:
        dfa = _build_token_dfa(pattern, tokenizer, vocab_size, eos_id)
    else:
        bias = np.zeros((1, vocab_size), np.float32)
        dfa = TokenDFA(
            bias=bias, next=np.zeros((1, vocab_size), np.int32),
            accept=np.ones((1,), bool),
            allowed=np.ones((1, vocab_size), bool), eos_id=eos_id,
        )
    if bias_items or banned:
        bias = dfa.bias.copy()
        allowed = dfa.allowed
        for tid, val in bias_items:
            # Bias applies only where the grammar already allows the
            # token — it must never resurrect a forbidden one.
            bias[:, tid] = np.where(allowed[:, tid], bias[:, tid] + val,
                                    bias[:, tid])
        for tid in banned:
            bias[:, tid] = MASK
        if banned:
            # A ban must not dead-end the automaton.
            ok = (bias > MASK / 2).any(axis=1)
            if not ok.all():
                raise ConstraintError(
                    "banned_tokens leave an automaton state with no "
                    "allowed token"
                )
        dfa = TokenDFA(bias=bias, next=dfa.next, accept=dfa.accept,
                       allowed=allowed, eos_id=eos_id, pattern=dfa.pattern)
    METRICS.observe(
        "batcher.constrain.compile_seconds", time.perf_counter() - t0
    )
    _CACHE.put(key, dfa)
    return dfa


# ---------------------------------------------------------------------------
# span-stack assembly (host) + the jitted gather/advance leg
# ---------------------------------------------------------------------------

def build_stack(dfas: list[TokenDFA], vocab_size: int,
                pad_states_to: int | None = None):
    """Concatenate the live rows' automata into ONE (bias, next) stack the
    decode step gathers from.  State 0 is the shared FREE state (zero
    bias, self-loop) unconstrained rows ride; automaton ``i``'s states
    occupy ``[offsets[i], offsets[i] + n_i)`` with transitions rebased to
    absolute indices.  ``pad_states_to`` pads the state axis (dead all-
    free states) so the stack walks a closed shape ladder — the compile
    key must not change with the mix of live schemas."""
    total = 1 + sum(d.n_states for d in dfas)
    n = max(total, pad_states_to or 0)
    bias = np.zeros((n, vocab_size), np.float32)
    nxt = np.zeros((n, vocab_size), np.int32)
    offsets: list[int] = []
    at = 1
    for d in dfas:
        offsets.append(at)
        k = d.n_states
        bias[at: at + k] = d.bias
        # One rebase covers every transition, EOS self-loops included
        # (the automaton stores next[s, eos] = s, so s + at is the
        # absolute self-loop).
        nxt[at: at + k] = np.where(d.allowed, d.next + at, 0)
        at += k
    return bias, nxt, offsets


def gather_bias(mask_stack, state):
    """[S, V] stack x [B] states -> [B, V] additive logit mask (the
    decode step's per-row constraint gather; graftcheck GC1 pins the
    shape/dtype contract)."""
    import jax.numpy as jnp

    return jnp.take(mask_stack, state, axis=0)


def advance_states(next_stack, state, tok):
    """[S, V] transitions x [B] states x [B] sampled tokens -> [B] next
    states — the DFA advance fused into the decode step (one gather; the
    carry stays device-resident across dispatch-ahead chunks)."""
    return next_stack[state, tok]
