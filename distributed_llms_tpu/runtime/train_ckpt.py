"""Training-state checkpoint / resume (Orbax-backed).

SURVEY §5.4: the reference's only persistence is the model shard store —
"no training/serving state, no resume protocol".  The model store
(checkpoint/store.py) covers weights; this module covers the *training*
state: params + optimizer state + step, saved as a sharded array tree and
restored mesh-aware (each host reads only what its devices need — resume is
``device_put`` onto the live mesh, not a socket transfer).
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import jax

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover - orbax is baked into the image
    _HAVE_ORBAX = False


def _checkpointer() -> "ocp.Checkpointer":
    if not _HAVE_ORBAX:
        raise RuntimeError("orbax-checkpoint is not available")
    return ocp.Checkpointer(ocp.PyTreeCheckpointHandler())


_TREEDEF_FILE = "opt_treedef.pkl"


def save_train_state(
    ckpt_dir: str,
    step: int,
    params: Any,
    opt_state: Any,
    keep: int = 3,
) -> str:
    """Write ``step``'s training state under ``ckpt_dir/step_<n>``; prunes to
    the newest ``keep`` checkpoints.  Returns the written path.

    The optimizer state is stored as an ordered leaf list plus a pickled
    treedef sidecar: Orbax's PyTree handler round-trips optax NamedTuple
    states (ScaleByAdamState etc.) as plain dicts, which optax then rejects;
    a flat list keeps leaf order exactly and the treedef rebuilds the real
    structure on restore without needing the optimizer at restore time.

    The sidecar doubles as the checkpoint's commit marker: it is written
    last (atomically, via tmp-file rename), and ``list_checkpoints`` ignores
    directories that lack it, so a crash between Orbax finalize and sidecar
    write can never leave a 'latest' checkpoint that restore would brick on.
    Uncommitted directories are ignored, never deleted — they may be another
    writer's in-flight save or a user's foreign data."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    path = os.path.join(ckpt_dir, f"step_{step:08d}")
    leaves, treedef = jax.tree.flatten(opt_state)
    _checkpointer().save(
        path, {"step": step, "params": params, "opt_state_leaves": leaves}, force=True
    )
    marker = os.path.join(path, _TREEDEF_FILE)
    tmp = marker + ".tmp"
    # pickle is safe here (unlike the reference's pickled network frames,
    # src/network/protocol.py): this sidecar is a LOCAL file in the
    # checkpoint directory we just wrote, read back only by restore() on
    # the same trusted filesystem — never from the network.
    with open(tmp, "wb") as f:
        pickle.dump(treedef, f)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, marker)
    for old in list_checkpoints(ckpt_dir)[:-keep]:
        _rmtree(os.path.join(ckpt_dir, old))
    return path


def _is_committed(step_dir: str) -> bool:
    return os.path.exists(os.path.join(step_dir, _TREEDEF_FILE))


def list_checkpoints(ckpt_dir: str) -> list[str]:
    """Committed step_<n> directory names, oldest first."""
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(
        d for d in os.listdir(ckpt_dir)
        if d.startswith("step_") and _is_committed(os.path.join(ckpt_dir, d))
    )


def latest_step(ckpt_dir: str) -> int | None:
    names = list_checkpoints(ckpt_dir)
    return int(names[-1][len("step_"):]) if names else None


def restore_train_state(
    ckpt_dir: str,
    step: int | None = None,
    template: Any = None,
) -> tuple[int, Any, Any]:
    """Restore (step, params, opt_state).  ``step=None`` takes the latest.

    ``template`` is a pytree of like-structured arrays (e.g. freshly-built
    sharded params + opt_state as ``{"step": 0, "params": ..., "opt_state":
    ...}``): restored arrays adopt its shardings, so resume lands directly on
    the live mesh."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step:08d}")
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint at {path}")
    if not _is_committed(path):
        raise RuntimeError(
            f"checkpoint at {path} has no {_TREEDEF_FILE} sidecar: either the "
            "save crashed before committing, or it predates the leaf-list "
            "optimizer-state format and cannot be restored by this version"
        )
    with open(os.path.join(path, _TREEDEF_FILE), "rb") as f:
        opt_treedef = pickle.load(f)
    if template is not None:
        stored_shape = {
            "step": 0,
            "params": template["params"],
            "opt_state_leaves": jax.tree.leaves(template["opt_state"]),
        }
        restore_args = ocp.checkpoint_utils.construct_restore_args(stored_shape)
        out = _checkpointer().restore(path, restore_args=restore_args)
    else:
        out = _checkpointer().restore(path)
    opt_state = jax.tree.unflatten(opt_treedef, out["opt_state_leaves"])
    return int(out["step"]), out["params"], opt_state


def _rmtree(path: str) -> None:
    import shutil

    shutil.rmtree(path, ignore_errors=True)
