"""HTTP serving gateway: an OpenAI-style REST front door over the
continuous batcher.

The reference's only user interface is the master's blocking REPL
(run_master.py:28-42); a serving/REST layer is future scope in its plan
(plan.md:225-233) and never existed.  This module is that layer, built for
how a TPU actually serves:

- the asyncio event loop owns every connection and all request bookkeeping;
- ONE engine thread owns the ``ContinuousBatcher`` and therefore the device
  — a single dispatch thread keeps XLA dispatch uncontended and makes the
  batcher's host scheduling mirrors single-writer by construction;
- requests cross from loop to engine through the batcher's FIFO queue
  (``submit`` is loop-side: deque append is the only shared mutation);
  token deliveries cross back via ``loop.call_soon_threadsafe`` from the
  batcher's ``on_tokens`` streaming callback;
- client disconnects and stop-sequence hits cancel lazily: the loop flags
  the rid, the engine's next chunk-boundary delivery observes the flag and
  frees the row (``ContinuousBatcher.cancel_row``), so an abandoned request
  costs at most one scheduling chunk.

Endpoints:

- ``POST /v1/completions``       OpenAI text-completion shape (+ ``prefix``
  extension naming a registered KV prefix); ``stream: true`` serves SSE.
- ``POST /v1/chat/completions``  chat shape via the tokenizer's own chat
  template (model-correct control tokens) or a plain-text fallback.
- ``GET /v1/models``, ``GET /healthz`` (real readiness/liveness JSON, non-200
  when unhealthy or draining), ``GET /metrics`` (Prometheus).

Crash-safe serving (crash-only design: recovery is the TESTED, ordinary
path, provoked on demand by runtime/faults.py):

- a SUPERVISOR wraps the engine thread: when ``batcher.run`` raises, the
  batcher is discarded wholesale (its jitted chunks donate the KV cache, so
  per-row device state is unreconstructable) and respawned fresh — pool,
  prefix cache, scheduling state.  Requests that streamed ZERO tokens are
  re-admitted under their original rid with a bounded retry budget (exact
  at temperature 0 — the same recompute-is-exact contract as prefix-cache
  reuse); partially-streamed ones fail with a structured error (deltas
  cannot be retracted).  ``server_engine_restarts`` / \
  ``server_requests_retried`` count it all, and a post-restart
  ``PagePool.assert_consistent`` audit proves nothing leaked.
- per-request DEADLINES: a ``timeout_s`` field (or the server-wide default)
  cancels an expired request at its next chunk boundary; the client gets
  ``finish_reason: "timeout"`` with the tokens produced so far and the row's
  pages are freed through the ordinary cancel path.
- an engine WATCHDOG: the engine stamps every delivery; ``/healthz`` reports
  seconds-since-last-chunk and flips unhealthy when in-flight work exists
  but the engine has not progressed within ``watchdog_timeout_s`` (a stalled
  XLA dispatch looks exactly like this).

Overload-safe serving (PR 3; README "Overload behavior"):

- a per-request ``priority`` field orders admission (higher first, FIFO
  within a priority) and shields rows from preemption — under KV pool
  pressure the engine grows rows on demand and preempts the lowest-priority,
  most-recently-admitted row for recompute instead of wedging;
- an estimated-COST gate: when queued + resident token mass exceeds
  ``shed_cost_factor`` x the batcher's KV capacity, new requests 429
  immediately with ``Retry-After`` — overload sheds at the front door;
- queue-time deadlines: a request whose ``timeout_s`` expires before it
  has produced ANY output (still queued, or admitted but still prefilling)
  is shed with 503 + ``Retry-After`` (type ``overloaded_error``) instead
  of being admitted doomed — no deltas were delivered, so a retry is
  safe; one that expires after tokens flowed keeps today's 200 +
  ``finish_reason: "timeout"`` partial-output contract (a preempted
  request's streamed prefix counts: it finishes with that output);
- every 429/503 the server emits (queue full, cost gate, draining, shed,
  unhealthy /healthz) carries a ``Retry-After`` header scaled to the
  committed work; ``cluster.client.ServingClient`` honors it with jittered
  exponential backoff.
"""

from __future__ import annotations

import asyncio
import functools
import json
import math
import threading
import time
import uuid

from ..core.observability import METRICS, get_logger
from .scheduler import ANON_TENANT

log = get_logger("server")

# How long a timed-out request waits for the engine to ack its cancel flag
# (one chunk away on a healthy engine) before answering the client anyway.
# The flag stays set on expiry, so the row is still freed whenever the
# engine comes back — the client just stops waiting for proof.
_TIMEOUT_ACK_GRACE_S = 10.0

# Structured error message partially-streamed requests receive when the
# engine restarts under them (their deltas cannot be retracted, so replaying
# the request could duplicate output).
_RESTART_ERR = "engine restarted mid-stream; partial output could not be resumed"

# Mailbox-delivered error prefix for load-shed requests (queue deadline
# expired before admission): the blocking handler answers 503 with a
# Retry-After so clients and load balancers back off instead of retrying hot.
_SHED_ERR = "shed before admission: "

_MAX_REQUEST_LINE = 8192
_MAX_HEADERS = 100
_MAX_BODY = 8 * 1024 * 1024
_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 413: "Payload Too Large",
    429: "Too Many Requests", 431: "Request Header Fields Too Large",
    500: "Internal Server Error", 501: "Not Implemented",
    503: "Service Unavailable",
}


class _Mailbox:
    """Per-request delivery queue, filled by the engine thread via
    call_soon_threadsafe, drained by the owning handler coroutine.
    ``finished`` flips once generation concluded (done seen / stop acked)
    so the disconnect path knows whether a cancel flag is still needed.
    ``t0``/``first_seen`` drive the TTFT histogram (first delivery).
    ``cached_tokens`` is filled by the engine thread on first delivery
    (prompt tokens served from the automatic prefix cache — surfaced as
    usage.prompt_tokens_details); read loop-side only after done.
    ``deadline`` is the request's absolute per-request deadline on the
    perf_counter clock (None = no deadline).
    ``meta``/``delivered``/``retries`` are the supervisor's per-request
    state: the submit arguments (so a restart can re-admit verbatim), the
    count of tokens the ENGINE delivered (the zero-streamed test —
    loop-side queue state may lag), and the re-admissions consumed.  They
    live on the mailbox so their lifetime IS the request's: once the
    handler pops ``_requests[rid]`` nothing else needs cleanup, and an
    engine-thread write racing that pop mutates a garbage object instead
    of resurrecting a side-table entry.
    ``export_ids``/``export_result`` serve the prefill-role handoff: a
    /v1/prefill request sets ``export_ids`` so the engine thread gathers
    the prompt's cached KV pages at the done delivery (the one thread
    that may touch the device) and stashes them in ``export_result``
    BEFORE the done notify — the handler reads them only after done."""

    __slots__ = ("queue", "finished", "t0", "first_seen", "cached_tokens",
                 "deadline", "meta", "delivered", "retries",
                 "export_ids", "export_result")

    def __init__(self) -> None:
        self.queue: asyncio.Queue = asyncio.Queue()
        self.finished = False
        self.t0 = time.perf_counter()
        self.first_seen = False
        self.cached_tokens: int | None = None
        self.deadline: float | None = None
        self.meta: dict | None = None
        self.delivered = 0
        self.retries = 0
        self.export_ids: list[int] | None = None
        self.export_result: tuple | None = None  # ("done", payload|None)


class BadRequest(ValueError):
    pass


# The ONE tenant-id charset, shared with the router (which forwards valid
# ids verbatim and 400s the rest — never rewrites, so router and replica
# agree on what a malformed id means).  ASCII-only on purpose: an id is a
# metric label, a scheduler key, and a header value — Unicode lookalikes
# would split one tenant's accounting into mojibake buckets.
_TENANT_CHARS = frozenset(
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789._-"
)

# Rate-ledger cardinality cap: admitting a NEW tenant id past this first
# ages every ledger and drops the empties (see _tenant_charge) — ids are
# client-minted, so the map must not grow with distinct-id count.
_TENANT_LEDGER_CAP = 4096


def valid_tenant_id(tenant) -> bool:
    # "-" (scheduler.ANON_TENANT) is reserved: a client claiming it would
    # alias its quota/fairness accounting onto all untagged traffic.
    return (isinstance(tenant, str) and 0 < len(tenant) <= 64
            and tenant != ANON_TENANT
            and all(c in _TENANT_CHARS for c in tenant))


def _field(req: dict, name: str, default, kind, *, minimum=None):
    v = req.get(name, default)
    if kind is int and isinstance(v, bool):  # bool passes isinstance(int)
        raise BadRequest(f"{name!r} must be an integer")
    if not isinstance(v, kind):
        raise BadRequest(f"{name!r} must be {kind.__name__}")
    if minimum is not None and v < minimum:
        raise BadRequest(f"{name!r} must be >= {minimum}")
    return v


def _stop_list(req: dict) -> list[str]:
    stop = req.get("stop")
    if stop is None:
        return []
    if isinstance(stop, str):
        stop = [stop]
    if (
        not isinstance(stop, list)
        or len(stop) > 4
        or not all(isinstance(s, str) and s for s in stop)
    ):
        raise BadRequest("'stop' must be a non-empty string or up to 4 of them")
    return stop


class InferenceServer:
    """Serve a ContinuousBatcher over HTTP.  See module docstring."""

    def __init__(
        self,
        batcher,
        model_name: str = "dlt-model",
        host: str = "0.0.0.0",
        port: int = 8000,
        max_pending: int = 256,
        batcher_factory=None,  # () -> fresh batcher; default batcher.respawn
        request_timeout_s: float | None = None,  # default per-request deadline
        watchdog_timeout_s: float = 30.0,  # /healthz stall threshold
        max_request_retries: int = 2,  # restart re-admissions per request
        # Estimated-cost admission gate: 429 (with Retry-After) when the
        # token mass already queued + resident would exceed this multiple
        # of the batcher's KV capacity — sustained overload sheds EARLY,
        # at the front door, instead of queueing work that will time out
        # doomed.  None/0 disables the gate (queue-full still 429s).
        shed_cost_factor: float | None = 2.0,
        # Disaggregated serving role: "colocated" (the default: prefill
        # and decode in one engine), "prefill" (serves /v1/prefill handoff
        # requests and ships finished KV pages to decode engines over
        # cluster/kv_transfer.py), or "decode" (additionally listens for
        # KV_PAGES transfers and adopts verified pages into its pool).
        # Both disaggregated roles require a paged batcher with the
        # automatic prefix cache — the handoff plane IS page content
        # addressing.
        role: str = "colocated",
        # Sender-side transfer hardening (prefill role): per-attempt
        # deadline, bounded jittered-exponential retries, and a cap on
        # concurrent in-flight transfers.
        xfer_attempt_s: float = 5.0,
        xfer_max_retries: int = 3,
        max_inflight_transfers: int = 4,
        # Grammar-constrained structured output (runtime/constrain.py):
        # response_format / logit_bias / banned_tokens request fields.
        # False answers 400 to any constrained request (operator
        # kill-switch: RuntimeConfig.constrained_decoding /
        # dlt-serve --no-constrained).
        constrained: bool = True,
        # Multi-tenant QoS (the gateway half; runtime/scheduler.py
        # TenantScheduler owns admission fairness).  Requests carry a
        # tenant id as the X-Tenant header or "tenant" body field
        # (header wins).  tenant_weights ({name: weight}, "*" = default)
        # scale the RATE quota: a tenant whose admitted token mass
        # (prompt + budget) over the trailing window would exceed
        # weight * tenant_quota_tps tokens/s sheds 429 with a PER-TENANT
        # Retry-After (when its own window frees) before any admission
        # state exists.  None disables the rate gate.
        tenant_weights: "dict[str, float] | None" = None,
        tenant_quota_tps: float | None = None,
        tenant_rate_window_s: float = 10.0,
        # Fleet mode: when a fronting router runs the AUTHORITATIVE
        # fleet-wide tenant ledger (runtime/router.py), this gateway's
        # per-replica ledger degrades to a LOOSE BACKSTOP — the allowance
        # is multiplied by this factor (~2x fair share), so a bypassed or
        # drilled router gate still never yields a silent unmetered path,
        # while ordinary traffic (already metered once, at the router)
        # is not double-gated at full strictness.  None = this gateway
        # is the authority (single-replica serving).
        tenant_backstop_x: float | None = None,
    ) -> None:
        if batcher.tokenizer is None:
            raise ValueError(
                "InferenceServer needs a batcher with a tokenizer "
                "(the completion API speaks text)"
            )
        if request_timeout_s is not None and request_timeout_s <= 0:
            raise ValueError(
                f"request_timeout_s must be > 0, got {request_timeout_s}"
            )
        if role not in ("colocated", "prefill", "decode"):
            raise ValueError(
                f"role must be colocated/prefill/decode, got {role!r}"
            )
        if role != "colocated" and (
            getattr(batcher, "pool", None) is None
            or getattr(batcher, "prefix_cache", None) is None
        ):
            raise ValueError(
                f"role {role!r} needs a paged batcher with the automatic "
                "prefix cache (paged_pages= + prefix_cache=True) — the "
                "KV handoff ships content-addressed pool pages"
            )
        self.batcher = batcher
        self.model_name = model_name
        self.host = host
        self.port = port
        self.max_pending = max_pending
        self._batcher_factory = batcher_factory
        self.request_timeout_s = request_timeout_s
        self.watchdog_timeout_s = watchdog_timeout_s
        self.max_request_retries = max_request_retries
        self.shed_cost_factor = shed_cost_factor
        self.role = role
        self.xfer_attempt_s = xfer_attempt_s
        self.xfer_max_retries = xfer_max_retries
        self.max_inflight_transfers = max_inflight_transfers
        self.constrained = bool(constrained)
        if tenant_quota_tps is not None and tenant_quota_tps <= 0:
            tenant_quota_tps = None  # the CLI/config "disable" spelling
        if tenant_rate_window_s <= 0:
            raise ValueError(
                f"tenant_rate_window_s must be > 0, got {tenant_rate_window_s}"
            )
        if tenant_backstop_x is not None and tenant_backstop_x < 1.0:
            raise ValueError(
                f"tenant_backstop_x must be >= 1 (a backstop looser than "
                f"the authority) or None, got {tenant_backstop_x}"
            )
        self.tenant_weights = dict(tenant_weights or {})
        self.tenant_default_weight = self.tenant_weights.pop("*", 1.0)
        self.tenant_quota_tps = tenant_quota_tps
        self.tenant_rate_window_s = tenant_rate_window_s
        self.tenant_backstop_x = tenant_backstop_x
        # Trailing-window admitted-token-mass ledger per tenant, for the
        # rate quota: deque of (perf_counter ts, est tokens), appended at
        # admission, aged out lazily.  Only the loop thread (the one
        # running every handler) touches it.
        from collections import deque

        self._tenant_window: dict[str, "deque[tuple[float, int]]"] = {}  # guarded-by: event-loop
        self._xfer_sem: asyncio.Semaphore | None = None  # made on start()
        self._kv_server: asyncio.base_events.Server | None = None
        from ..cluster.kv_transfer import ReceiverStats

        self.kv_stats = ReceiverStats()  # decode role: import accounting
        # Serializes (next_rid + submit) on the loop thread against the
        # supervisor's batcher swap on the engine thread: without it a
        # submit could land in the dying batcher's queue after the
        # supervisor scanned it, stranding the request forever.  Also
        # guards the mailbox registry and cancel-flag set below (loop
        # registers/pops, engine reads/consumes — PR 3 leaned on GIL-atomic
        # dict/set ops here, which graftlint's GL101 now rejects).  Held
        # only for host bookkeeping (never across an await or a device
        # call); lock order is _submit_lock -> batcher._lock, everywhere.
        self._submit_lock = threading.Lock()
        # A mailbox registered here and then stranded by an exception is
        # the PR-3 _Mailbox leak class (its handler coroutine blocks
        # forever); GF303 demands a pop on every raising path.
        # graftflow: cleanup-required
        self._requests: dict[int, _Mailbox] = {}  # guarded-by: self._submit_lock
        self._cancelled: set[int] = set()  # guarded-by: self._submit_lock
        # Supervisor per-request state (meta/delivered/retries) rides on
        # each _Mailbox — see its docstring.
        self._restarts = 0
        self._engine_dead = False  # respawn itself failed; serve errors
        self._last_progress = time.monotonic()  # engine watchdog stamp
        self._recover_t0: float | None = None  # crash time, for recovery_seconds
        self._work = threading.Event()
        self._stopping = False
        self._draining = False  # graceful stop: reject new, finish in-flight
        self._server: asyncio.base_events.Server | None = None
        self._engine: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._conns: set[asyncio.StreamWriter] = set()

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self._xfer_sem = asyncio.Semaphore(self.max_inflight_transfers)
        if self.role == "decode" or (
            self.role == "colocated"
            and getattr(self.batcher, "pool", None) is not None
            and getattr(self.batcher, "prefix_cache", None) is not None
        ):
            # The KV import listener: prefill-role peers ship finished
            # pages here over cluster/kv_transfer.py framing (always an
            # ephemeral port; the fleet records where it landed).  A
            # paged+prefix-cache COLOCATED replica listens too — it is a
            # cross-replica pull target (the router's digest directory
            # ships a sibling's cached run here instead of re-prefilling).
            self._kv_server = await asyncio.start_server(
                self._handle_kv, self.host, 0
            )
        self._engine = threading.Thread(
            target=self._engine_loop, name="dlt-serve-engine", daemon=True
        )
        self._engine.start()
        addr = self._server.sockets[0].getsockname()
        log.info(
            "serving %s (%s) on http://%s:%s/v1/completions",
            self.model_name, self.role, addr[0], addr[1],
        )
        return addr[0], addr[1]

    @property
    def kv_bound_port(self) -> int | None:
        """Where the KV import listener landed (decode role, or a
        paged+prefix-cache colocated replica — a pull target either way;
        None when this replica cannot import pages)."""
        if self._kv_server is None:
            return None
        return self._kv_server.sockets[0].getsockname()[1]

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self, drain_timeout: float = 0.0) -> None:
        """Stop serving.  ``drain_timeout > 0``: graceful — new requests
        get 500 immediately while in-flight ones run to completion (up to
        the deadline), then the engine stops; anything still unfinished at
        the deadline is cancelled.  ``0``: immediate — in-flight rows are
        cancel-flagged and the engine drains within one chunk."""
        self._draining = True
        if drain_timeout > 0:
            loop = asyncio.get_running_loop()
            deadline = loop.time() + drain_timeout
            # force_stop() flips _stopping mid-drain (second SIGTERM/^C).
            while (self._inflight() and loop.time() < deadline
                   and not self._stopping):
                await asyncio.sleep(0.05)
        self._stopping = True
        with self._submit_lock:
            for rid in list(self._requests):
                self._cancelled.add(rid)
        self._work.set()
        if self._engine is not None:
            # Every active row delivers each chunk, so the cancel flags
            # drain run() within one chunk; join must not block the loop.
            await asyncio.to_thread(self._engine.join, 60.0)
        # The engine answered every mailbox; give handler coroutines a
        # bounded window to consume those final deliveries and FLUSH their
        # (partial) responses before the connections are torn down — a
        # force-stopped request should see "200, fewer tokens", not a
        # reset socket.  Bounded so a dead client cannot hold shutdown.
        if self._loop is not None:
            deadline = self._loop.time() + 5.0
            while self._inflight() and self._loop.time() < deadline:
                await asyncio.sleep(0.02)
        if self._kv_server is not None:
            self._kv_server.close()
        if self._server is not None:
            self._server.close()
        # Sever every open connection (HTTP and KV — both register in
        # _conns) BEFORE awaiting wait_closed: on Pythons where
        # wait_closed waits for active handlers, an open KV connection
        # from a stalled prefill peer would otherwise hold shutdown.
        for w in list(self._conns):
            w.close()
        if self._server is not None:
            await self._server.wait_closed()
        if self._kv_server is not None:
            await self._kv_server.wait_closed()

    def force_stop(self) -> None:
        """Cut a graceful drain short (second SIGTERM/Ctrl-C): in-flight
        rows cancel at their next chunk instead of running to completion."""
        self._stopping = True

    async def kill(self) -> None:
        """Abrupt-death simulation (replica chaos drills, cluster/fleet.py):
        sever every open connection WITHOUT flushing, stop accepting, and
        reap the engine thread — the closest an in-process replica gets to
        SIGKILL.  Unlike :meth:`stop`, nothing drains gracefully: clients
        observe reset sockets mid-response, exactly what a crashed process
        produces, so a fronting router exercises its real failover path."""
        self._draining = True
        self._stopping = True
        if self._server is not None:
            self._server.close()
        if self._kv_server is not None:
            self._kv_server.close()
        for w in list(self._conns):
            w.close()
        with self._submit_lock:
            for rid in list(self._requests):
                self._cancelled.add(rid)
        self._work.set()
        if self._engine is not None:
            # Cancel flags drain run() within one chunk; never block the loop.
            await asyncio.to_thread(self._engine.join, 60.0)
        if self._server is not None:
            await self._server.wait_closed()

    # -- engine thread -----------------------------------------------------

    def _inflight(self) -> int:
        """Registered (mailbox-holding) requests, from any thread."""
        with self._submit_lock:
            return len(self._requests)

    def _pending(self) -> bool:
        b = self.batcher
        # b.rows is engine-owned; this loop-thread probe only snapshot-
        # iterates and reads immutable attributes (the documented healthz
        # contract).  The queue read goes through the batcher's lock, and
        # a verified KV handoff awaiting adoption counts as work too (the
        # engine must wake to import it).
        return (b.has_queued() or b.has_kv_imports() or b.has_kv_exports()
                or any(r.rid is not None for r in list(b.rows)))

    def _pending_token_mass(self) -> int:
        """Estimated token mass the engine still has to absorb: every
        queued or resident request's prompt + budget.  A resumed
        (preempted) request's ids already fold in its emitted prefix and
        its budget shrank to the remainder, so the estimate never double
        counts.  The queue is read through the batcher's submission lock;
        rows are engine-owned and snapshot-iterated (healthz contract)."""
        b = self.batcher
        mass = 0
        for r in b.queue_snapshot():
            mass += len(r.ids) + r.max_new_tokens
        for row in list(b.rows):
            req = row.req
            if row.rid is not None and req is not None:
                mass += len(req.ids) + req.max_new_tokens
        return mass

    def _retry_after_s(self) -> int:
        """Retry-After hint for 429/503 answers: roughly how many
        pool-capacity drains of work are already committed, clamped to
        [1, 30] — a coarse, monotone backoff signal, not a promise."""
        cap = max(1, self.batcher.capacity_tokens())
        return int(min(30, max(1, -(-self._pending_token_mass() // cap))))

    # -- multi-tenant QoS: the gateway's rate-quota half -------------------

    @staticmethod
    def _parse_tenant(req: dict, tenant_hdr: str | None) -> str | None:
        """The request's tenant id: X-Tenant header first (proxies stamp
        identity), "tenant" body field as the fallback.  None = the
        anonymous bucket.  Malformed ids 400 — a tenant id becomes a
        metric label and a scheduler key, so the charset is tight."""
        tenant = tenant_hdr if tenant_hdr else req.get("tenant")
        if tenant is None or tenant == "":
            return None
        if not valid_tenant_id(tenant):
            raise BadRequest(
                "'tenant' must be 1-64 chars of [A-Za-z0-9._-] "
                "(X-Tenant header or body field)"
            )
        return tenant

    def _tenant_weight(self, tenant: str) -> float:
        return self.tenant_weights.get(tenant, self.tenant_default_weight)

    def _tenant_allowance(self, tenant: str) -> float:
        """Token mass the tenant's trailing window may hold HERE.  With a
        fronting router running the authoritative fleet ledger, the
        backstop factor loosens this gateway's cap (~2x fair share): it
        only trips when the router gate was bypassed or drilled — never
        a silent unmetered path, never a double gate at full strictness."""
        allowed = (self._tenant_weight(tenant) * self.tenant_quota_tps
                   * self.tenant_rate_window_s)
        if self.tenant_backstop_x is not None:
            allowed *= self.tenant_backstop_x
        return allowed

    # graftlint: holds(event-loop)
    def _tenant_retry_after(self, tenant: str, est: int) -> int | None:
        """Per-tenant token-rate gate (loop thread only).  Returns None
        when ``est`` more admission tokens fit the tenant's trailing-
        window quota (weight x tenant_quota_tps tokens/s), else the
        PER-TENANT Retry-After: when the tenant's own window has aged
        out enough room — unlike the global ``_retry_after_s`` hint,
        this is a promise about this tenant's ledger, not fleet load.
        The ``tenant.quota`` fault site (tag = tenant) can force the
        over-quota path for drills (action ``exhaust``)."""
        if self.tenant_quota_tps is None:
            return None
        win = self.tenant_rate_window_s
        allowed = self._tenant_allowance(tenant)
        now = time.perf_counter()
        ledger = self._tenant_window.get(tenant)
        forced = False
        plane = self.batcher.faults
        if plane is not None:
            # defer_stall: this gate runs on the event loop (a stall rule
            # must not freeze every handler and the fleet's probes).
            rule = plane.fire("tenant.quota", tag=tenant, defer_stall=True)
            forced = rule is not None and rule.action == "exhaust"
        if ledger:
            while ledger and ledger[0][0] <= now - win:
                ledger.popleft()
            if not ledger:  # fully aged out: drop the deque itself too
                del self._tenant_window[tenant]
                ledger = None
        used = sum(n for _, n in ledger) if ledger else 0
        if not forced and used + est <= allowed:
            return None
        # Walk the tenant's own ledger oldest-first: the hint is when
        # enough of ITS charges age out that est fits again.
        room_needed = used + est - allowed
        freed = 0.0
        hint = win
        for ts, n in (ledger or ()):
            freed += n
            if freed >= room_needed:
                hint = ts + win - now
                break
        return int(min(60, max(1, math.ceil(hint))))

    # graftlint: holds(event-loop)
    def _tenant_charge(self, tenant: str | None, est: int) -> None:
        """Record an accepted request's admission-time token mass on its
        tenant's trailing window (loop thread only) + per-tenant
        counters.  Anonymous requests bill the shared ANON bucket's
        ledger (the rate gate checks it) but mint no per-tenant
        metrics."""
        if tenant is not None:
            METRICS.inc(f"tenant.requests.{tenant}")
            METRICS.inc(f"tenant.admitted_tokens.{tenant}", est)
        if self.tenant_quota_tps is None:
            return  # no rate gate -> nothing ever ages the ledger; keep none
        from collections import deque

        key = tenant if tenant is not None else ANON_TENANT
        if key not in self._tenant_window \
                and len(self._tenant_window) >= _TENANT_LEDGER_CAP:
            # Cardinality bound: tenant ids are client-minted, so a new id
            # must not grow the map past the cap without first aging every
            # ledger and dropping the empties.  Ids still inside their
            # window are genuine concurrent tenants — those stay.
            cutoff = time.perf_counter() - self.tenant_rate_window_s
            for t in list(self._tenant_window):
                d = self._tenant_window[t]
                while d and d[0][0] <= cutoff:
                    d.popleft()
                if not d:
                    del self._tenant_window[t]
        ledger = self._tenant_window.setdefault(key, deque())
        ledger.append((time.perf_counter(), est))

    def _engine_loop(self) -> None:
        while True:
            self._work.wait()
            self._work.clear()
            if self._stopping:
                # Drain before exiting: a request submitted between the
                # last run and stop() (engine idle, _work set by both) is
                # in the batcher queue but will never run — without this
                # its handler coroutine blocks forever on its mailbox.
                # Lock order inside: _submit_lock -> batcher._lock (same
                # as the submit path).
                with self._submit_lock:
                    for rid in list(self._requests):
                        self.batcher.cancel_row(rid)
                        self._cancelled.discard(rid)
                        self._notify(rid, [], True,
                                     err="server is shutting down")
                return
            if not self._pending():
                continue
            self._last_progress = time.monotonic()
            try:
                self.batcher.run(on_tokens=self._deliver)
            except Exception:
                log.exception("batcher.run crashed; supervising a restart")
                try:
                    self._recover_engine()
                except Exception:
                    # Respawn itself failed (OOM, wedged device): fail
                    # everything in flight and mark the engine dead so
                    # /healthz goes unhealthy — crash-only all the way up.
                    log.exception(
                        "engine recovery failed; failing in-flight requests"
                    )
                    self._engine_dead = True
                    with self._submit_lock:
                        for rid in list(self._requests):
                            self._cancelled.discard(rid)
                            self._notify(rid, [], True,
                                         err="engine unrecoverable")
                    return
                continue  # fresh batcher: nothing of the old run to clear
            # run() accumulated per-rid results we already streamed; drop
            # them so a long-lived server's memory stays flat.  (Shed
            # reasons are popped at delivery; clear what disconnected
            # handlers left behind.)
            self.batcher.results.clear()
            self.batcher.result_logprobs.clear()
            self.batcher.prefix_cached_tokens.clear()
            self.batcher.shed.clear()

    def _recover_engine(self) -> None:
        """Supervisor (engine thread): replace the crashed batcher with a
        fresh one and triage every in-flight request.

        Zero-streamed requests re-admit under their ORIGINAL rid (the
        handler's mailbox/cancel bookkeeping keys on it) with a bounded
        retry budget — at temperature 0 the re-decode is token-identical,
        the same recompute-is-exact contract prefix caching relies on.
        Partially-streamed requests fail with a structured error: their
        deltas are already on the wire and cannot be retracted.  The swap
        and the queue re-seed happen under _submit_lock so a concurrent
        HTTP submit can never land in the dying batcher."""
        crash_t = time.monotonic()
        old = self.batcher
        new = (self._batcher_factory() if self._batcher_factory is not None
               else old.respawn())
        # Named prefixes are host-side KV (never donated); carry them over
        # so registered system prompts survive the restart.
        new.prefixes.update(old.prefixes)
        retried: list[int] = []
        failed: list[int] = []
        with self._submit_lock:
            for rid in sorted(self._requests):
                mbox = self._requests[rid]
                meta = mbox.meta
                if rid in self._cancelled:
                    # Canceller (disconnect/stop hit) initiated this and
                    # already knows; ack quietly like cancel_row would.
                    self._cancelled.discard(rid)
                    self._notify(rid, [], True)
                    continue
                if (meta is not None
                        and mbox.delivered == 0
                        and mbox.retries < self.max_request_retries):
                    mbox.retries += 1
                    # Re-admit under the ORIGINAL rid (handler bookkeeping
                    # keys on it) through the normal submit path, so every
                    # validation/normalization rule applies identically.
                    new._next_rid = rid
                    try:
                        got = new.submit(meta["ids"], **{
                            k: v for k, v in meta.items() if k != "ids"
                        })
                        assert got == rid
                        retried.append(rid)
                        continue
                    except (ValueError, KeyError):
                        log.exception("re-admission of rid %d failed", rid)
                failed.append(rid)
                self._cancelled.discard(rid)
                self._notify(rid, [], True, err=_RESTART_ERR)
            new._next_rid = old._next_rid  # rid continuity across the swap
            # Transplant VERIFIED KV imports awaiting adoption: their
            # payloads are host-side (no device state lost in the crash)
            # and their on_done callbacks have KV-listener coroutines
            # waiting — leaving them on the dying batcher would strand
            # each one for the full import timeout.  Under _submit_lock,
            # so the loop thread cannot submit into `old` mid-move (lock
            # order _submit_lock -> batcher._lock, the submit path's).
            with old._lock:
                pending_imports = list(old._kv_imports)
                old._kv_imports.clear()
                pending_exports = list(old._kv_exports)
                old._kv_exports.clear()
            if pending_imports:
                with new._lock:
                    new._kv_imports.extend(pending_imports)
            # Queued cross-replica EXPORTS cannot transplant: the crashed
            # pool's cached pages died with it, and the fresh pool is
            # cold — answer each waiting /v1/kv_export handler "nothing
            # to export" now (the router recomputes locally) instead of
            # stranding it for the full export timeout.
            for _ids, on_done in pending_exports:
                try:
                    on_done(None)
                except Exception:
                    log.exception("kv-export completion callback raised")
            self.batcher = new
        self._restarts += 1
        if retried:
            # Recovery latency closes at the first post-restart delivery.
            self._recover_t0 = crash_t
        else:
            # Nothing to re-admit: recovery is complete right here — leaving
            # _recover_t0 armed would bill the idle gap until the NEXT
            # request as "recovery".
            METRICS.observe(
                "server.recovery_seconds", time.monotonic() - crash_t
            )
            self._recover_t0 = None
        METRICS.inc("server.engine_restarts")
        if retried:
            METRICS.inc("server.requests_retried", len(retried))
        # The fresh pool must audit clean — a failure here means respawn
        # itself leaked, which the outer except escalates to engine-dead.
        new.assert_pool_consistent()
        log.warning(
            "engine restarted (#%d): %d request(s) re-admitted, %d failed "
            "partially-streamed", self._restarts, len(retried), len(failed),
        )
        self._last_progress = time.monotonic()
        if retried or self._pending():
            self._work.set()

    def _deliver(self, rid: int, toks: list[int], done: bool,
                 lps: list[float] | None = None) -> None:
        # Engine thread, between device chunks: the one safe point to act
        # on loop-side cancel flags.
        self._last_progress = time.monotonic()  # watchdog: engine is moving
        if self._recover_t0 is not None:
            # First delivery after a supervised restart: recovery latency
            # (crash -> tokens flowing again), exported at /metrics.
            METRICS.observe(
                "server.recovery_seconds", time.monotonic() - self._recover_t0
            )
            self._recover_t0 = None
        # A done delivery for a rid the batcher SHED (queue deadline
        # expired before admission) carries the shed reason as a
        # structured error: the handler answers 503 + Retry-After, not an
        # empty 200.  Engine thread owns batcher.shed; popped exactly once.
        shed = self.batcher.shed.pop(rid, None) if done else None
        err = (_SHED_ERR + shed) if shed is not None else None
        if done:
            # Prefill-role handoff: gather the finished prompt's cached
            # pages HERE, on the engine thread (the only thread that may
            # touch the device), OUTSIDE the submission lock (a device
            # gather must never ride a host-bookkeeping lock), and stash
            # the payload before the done notify is queued — the handler
            # coroutine reads it strictly after done.
            with self._submit_lock:
                mb = self._requests.get(rid)
                export_ids = mb.export_ids if mb is not None else None
            if export_ids is not None and err is None:
                try:
                    payload = self.batcher.export_prefix_pages(export_ids)
                except Exception:
                    log.exception("kv page export failed for rid %d", rid)
                    payload = None
                with self._submit_lock:
                    mb = self._requests.get(rid)
                    if mb is not None:
                        mb.export_result = ("done", payload)
        with self._submit_lock:
            mbox = self._requests.get(rid)
            if mbox is not None and toks:
                # Engine-side streamed accounting: the supervisor's
                # zero-streamed test reads THIS, not loop-side queue state
                # (which lags by however many deliveries sit unconsumed).
                mbox.delivered += len(toks)
            if mbox is not None and mbox.cached_tokens is None:
                # Prefix-cache usage accounting: the batcher recorded the
                # rid's cached prompt tokens at admission (before any
                # delivery); this thread owns the batcher, so the read is
                # race-free.  The loop reads it only after the done
                # delivery it is ordered before.
                mbox.cached_tokens = \
                    self.batcher.prefix_cached_tokens.get(rid, 0)
            cancelled = rid in self._cancelled
            self._cancelled.discard(rid)
            if cancelled and not done:
                # Lock order _submit_lock -> batcher._lock (submit path's).
                self.batcher.cancel_row(rid)
            self._notify(rid, toks, True if cancelled else done,
                         err=err, lps=lps)
            self._sweep_cancelled(exclude=rid)

    # graftlint: holds(self._submit_lock)
    def _sweep_cancelled(self, exclude: int) -> None:
        """Consume cancel flags for OTHER rids at this chunk boundary.
        A QUEUED request (no row yet, so no deliveries of its own) would
        otherwise never see its flag consumed — a timed-out queued request
        would sit out the full ack grace instead of cancelling at the next
        chunk boundary as documented.  cancel_row is legal here: we are
        inside run()'s on_tokens callback, the documented safe point.
        Caller holds _submit_lock."""
        for other in list(self._cancelled):
            if other == exclude:
                continue
            if self.batcher.cancel_row(other):
                self._cancelled.discard(other)
                self._notify(other, [], True)

    # graftlint: holds(self._submit_lock)
    def _notify(self, rid: int, toks: list[int], done: bool,
                err: str | None = None, lps: list[float] | None = None):
        """Queue one delivery onto the rid's mailbox (caller holds
        _submit_lock — every producer already does, for the registry
        scan/swap it performs around the notify)."""
        mbox = self._requests.get(rid)
        if mbox is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(
                mbox.queue.put_nowait,
                (list(toks), done, err, list(lps) if lps else None),
            )

    # -- HTTP plumbing -----------------------------------------------------

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._conns.add(writer)
        t0 = time.perf_counter()  # request receipt: latency clocks start here
        try:
            try:
                # Deadline covers the parse phase only: generation itself
                # may legitimately exceed any fixed request timeout.
                # (wait_for, not asyncio.timeout: pyproject allows 3.10.)
                method, path, body, tenant_hdr = await asyncio.wait_for(
                    self._read_request(writer, reader), 30.0
                )
            except _Responded:
                return
            await self._route(writer, method, path, body, t0,
                              tenant_hdr=tenant_hdr)
        except (asyncio.TimeoutError, ConnectionError, OSError, ValueError,
                EOFError):  # IncompleteReadError: client hung up mid-body
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    async def _read_request(
        self, writer, reader
    ) -> tuple[str, str, bytes, str | None]:
        line = await reader.readline()
        if len(line) > _MAX_REQUEST_LINE:
            await self._plain(writer, 431, "request line too long")
            raise _Responded
        parts = line.decode("latin-1", "replace").split()
        if len(parts) < 2:
            await self._plain(writer, 400, "bad request")
            raise _Responded
        method, path = parts[0], parts[1]
        content_len = 0
        tenant_hdr: str | None = None
        for _ in range(_MAX_HEADERS):
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            name, _, value = h.decode("latin-1", "replace").partition(":")
            hname = name.strip().lower()
            if hname == "content-length":
                try:
                    content_len = int(value.strip())
                except ValueError:
                    await self._plain(writer, 400, "bad content-length")
                    raise _Responded
            elif hname == "transfer-encoding":
                # Only Content-Length bodies are read; a chunked POST would
                # otherwise parse as empty and fail with a misleading
                # "'prompt' missing" 400.
                await self._plain(writer, 501, "chunked bodies not supported")
                raise _Responded
            elif hname == "x-tenant":
                # Multi-tenant QoS: the tenant id a request bills against
                # (header form; a "tenant" body field is the fallback —
                # the header wins so proxies can stamp identity).
                tenant_hdr = value.strip()
        else:
            await self._plain(writer, 431, "too many headers")
            raise _Responded
        if content_len > _MAX_BODY:
            await self._plain(writer, 413, "body too large")
            raise _Responded
        body = await reader.readexactly(content_len) if content_len else b""
        return method, path, body, tenant_hdr

    def health(self) -> tuple[int, dict]:
        """Readiness/liveness report behind GET /healthz.  Non-200 while
        draining (load balancers stop routing BEFORE the drain 503s start)
        or when the engine is dead/stalled: stalled means in-flight work
        exists but the engine has not delivered a chunk within
        ``watchdog_timeout_s`` (a wedged device call looks exactly so)."""
        age = time.monotonic() - self._last_progress
        alive = (not self._engine_dead
                 and self._engine is not None and self._engine.is_alive())
        # "Work exists" must include batcher-held rows, not just open HTTP
        # handlers: timed-out handlers answer their clients and leave
        # _requests while a wedged engine still pins their rows/pages —
        # keying on _requests alone would report a wedged engine healthy
        # the moment the last handler gave up.  _pending() reads batcher
        # state through the batcher's own lock/snapshot contract.
        with self._submit_lock:
            inflight = len(self._requests)
            cancels = bool(self._cancelled)
        busy = inflight > 0 or cancels or self._pending()
        stalled = busy and age > self.watchdog_timeout_s
        healthy = alive and not stalled and not self._draining
        METRICS.set_gauge("server.engine_last_chunk_age_s", age)
        status = ("ok" if healthy
                  else "draining" if self._draining and alive and not stalled
                  else "unhealthy")
        return (200 if healthy else 503), {
            "status": status,
            # Disaggregated serving: the router places completions only on
            # decode-capable replicas and handoffs only on prefill ones —
            # the role rides the same probe that carries health.
            "role": self.role,
            "engine_alive": alive,
            "engine_stalled": stalled,
            "seconds_since_last_chunk": round(age, 3),
            "draining": self._draining,
            "inflight_requests": inflight,
            "engine_restarts": self._restarts,
            # Queued + resident token mass: the load signal a fronting
            # replica router reads for least-committed placement.
            "committed_tokens": self._pending_token_mass(),
        }

    async def _route(self, writer, method: str, path: str, body: bytes,
                     t0: float, tenant_hdr: str | None = None) -> None:
        if method == "GET" and path == "/healthz":
            code, report = self.health()
            # Every non-200 carries Retry-After: probes and load balancers
            # get an explicit back-off hint (draining/stalled is transient).
            await self._json(writer, code, report, headers=(
                None if code == 200
                else {"Retry-After": str(self._retry_after_s())}
            ))
        elif method == "GET" and path == "/metrics":
            # Refresh the watchdog gauge so scrapes see a current age, and
            # the pool occupancy view (batcher_pool_*) so an idle engine
            # still exports current free/cached/held page counts.
            METRICS.set_gauge(
                "server.engine_last_chunk_age_s",
                time.monotonic() - self._last_progress,
            )
            pool = getattr(self.batcher, "pool", None)
            if pool is not None:
                pool.publish_gauges()
            await self._respond(
                writer, 200, "text/plain; version=0.0.4; charset=utf-8",
                METRICS.prometheus_text().encode(),
            )
        elif method == "GET" and path == "/v1/models":
            await self._json(writer, 200, {
                "object": "list",
                "data": [{
                    "id": self.model_name, "object": "model",
                    "owned_by": "distributed-llms-tpu",
                }],
            })
        elif method == "POST" and path in ("/v1/completions", "/v1/chat/completions"):
            try:
                req = json.loads(body or b"{}")
                if not isinstance(req, dict):
                    raise BadRequest("request body must be a JSON object")
                await self._completions(writer, req, chat="chat" in path,
                                        t0=t0, tenant_hdr=tenant_hdr)
            except (BadRequest, json.JSONDecodeError) as e:
                await self._json(writer, 400, _err_body(str(e)))
        elif method == "POST" and path == "/v1/prefill":
            if self.role != "prefill":
                await self._json(writer, 404, _err_body(
                    "this replica does not serve prefill handoffs "
                    f"(role {self.role!r})"
                ))
                return
            try:
                req = json.loads(body or b"{}")
                if not isinstance(req, dict):
                    raise BadRequest("request body must be a JSON object")
                await self._prefill(writer, req)
            except (BadRequest, json.JSONDecodeError) as e:
                await self._json(writer, 400, _err_body(str(e)))
        elif method == "POST" and path == "/v1/kv_export":
            # Cross-replica pull source (any role with a paged prefix
            # cache): export a prompt's CACHED page run to a sibling's KV
            # listener — no admission, no recompute; "nothing to export"
            # when the run is not resident.
            try:
                req = json.loads(body or b"{}")
                if not isinstance(req, dict):
                    raise BadRequest("request body must be a JSON object")
                await self._kv_export(writer, req)
            except (BadRequest, json.JSONDecodeError) as e:
                await self._json(writer, 400, _err_body(str(e)))
        elif method not in ("GET", "POST"):
            await self._plain(writer, 405, "method not allowed")
        else:
            await self._plain(writer, 404, "not found")

    # -- the completion core ----------------------------------------------

    def _parse_prompt(self, req: dict, chat: bool) -> tuple[list[int], str]:
        tok = self.batcher.tokenizer
        if chat:
            messages = req.get("messages")
            if (
                not isinstance(messages, list) or not messages
                or not all(
                    isinstance(m, dict)
                    and isinstance(m.get("role"), str)
                    and isinstance(m.get("content"), str)
                    for m in messages
                )
            ):
                raise BadRequest(
                    "'messages' must be a non-empty list of "
                    "{role, content} objects"
                )
            text = tok.apply_chat_template(messages)
            return tok.encode(text), text
        prompt = req.get("prompt")
        if isinstance(prompt, str) and prompt:
            return tok.encode(prompt), prompt
        if (
            isinstance(prompt, list) and prompt
            and all(isinstance(t, int) and not isinstance(t, bool) for t in prompt)
        ):
            return list(prompt), ""
        raise BadRequest("'prompt' must be a non-empty string or token-id list")

    def _parse_sampling(self, req: dict):
        """Per-request temperature/top_p/top_k ride the batcher's per-row
        sampling path (top_k via a traced per-row mask — no recompile per
        value); presence/frequency penalties adjust against the request's
        own output histogram.
        Returns (temperature, top_p, top_k, presence, frequency)."""
        import math

        out = []
        for name in ("temperature", "top_p"):
            want = req.get(name)
            if want is None:
                out.append(None)
                continue
            if not isinstance(want, (int, float)) or isinstance(want, bool):
                raise BadRequest(f"{name!r} must be a number")
            want = float(want)
            if not math.isfinite(want):  # json.loads accepts Infinity/NaN
                raise BadRequest(f"{name!r} must be finite")
            if name == "temperature" and not 0.0 <= want:
                raise BadRequest("'temperature' must be >= 0")
            if name == "top_p" and not 0.0 < want <= 1.0:
                raise BadRequest("'top_p' must be in (0, 1]")
            # Speculative engines accept only values matching their
            # engine-wide sampling config — submit() enforces it and its
            # ValueError becomes a 400 at the call site.
            out.append(want)
        for name in ("presence_penalty", "frequency_penalty"):
            pen = req.get(name)
            if pen is None:
                out.append(0.0)
                continue
            if not isinstance(pen, (int, float)) or isinstance(pen, bool):
                raise BadRequest(f"{name!r} must be a number")
            # Range and engine-capability policy live in submit() — its
            # ValueError becomes a 400 at the call site; duplicating the
            # checks here would just drift.
            out.append(float(pen))
        want_k = req.get("top_k")
        if want_k is not None:
            if not isinstance(want_k, int) or isinstance(want_k, bool) \
                    or want_k < 0:
                raise BadRequest("'top_k' must be an integer >= 0")
            # Speculative engines accept only the engine-wide value —
            # submit() enforces it and its ValueError becomes a 400.
        return out[0], out[1], want_k, out[2], out[3]

    async def _completions(self, writer, req: dict, chat: bool,
                           t0: float | None = None,
                           tenant_hdr: str | None = None) -> None:
        if t0 is None:
            t0 = time.perf_counter()
        prompt_ids, _ = self._parse_prompt(req, chat)
        max_tokens = _field(
            req, "max_completion_tokens" if chat else "max_tokens",
            req.get("max_tokens", 16), int, minimum=1,
        )
        stream = bool(req.get("stream", False))
        stop = _stop_list(req)
        prefix = req.get("prefix")
        use_cache = req.get("prefix_cache", True)
        if not isinstance(use_cache, bool):
            # Extension knob: opt THIS request out of automatic prefix
            # caching (its prompt neither matches nor populates the cache).
            raise BadRequest("'prefix_cache' must be a boolean")
        temperature, top_p, top_k, pres_pen, freq_pen = \
            self._parse_sampling(req)
        response_format = req.get("response_format")
        logit_bias = req.get("logit_bias")
        banned_tokens = req.get("banned_tokens")
        dfa = None
        if (response_format is not None or logit_bias is not None
                or banned_tokens is not None):
            if not self.constrained:
                raise BadRequest(
                    "constrained decoding is disabled on this server "
                    "(runtime.constrained_decoding / --no-constrained)"
                )
            from . import constrain as constrain_lib

            b = self.batcher
            try:
                # Compile (or LRU-hit) the token-mask automaton OFF the
                # event loop — a large schema's DFA build is host numpy
                # work measured in wall-clock, and this loop answers the
                # fleet's health probes.  The compiled automaton itself is
                # handed to submit() below: re-looking it up could MISS
                # (LRU eviction in the window) and rebuild synchronously
                # on this loop.
                dfa = await asyncio.to_thread(
                    constrain_lib.compile_request,
                    response_format, logit_bias, banned_tokens,
                    tokenizer=b.tokenizer, vocab_size=b.cfg.vocab_size,
                    eos_id=b.eos_id,
                )
            except constrain_lib.ConstraintError as e:
                # Malformed schema/regex/bias: structured 400 BEFORE any
                # admission state exists (no mailbox, no queue entry).
                raise BadRequest(str(e)) from None
        lp_req = req.get("logprobs")
        if lp_req is None or lp_req is False:
            want_lp = False
        elif lp_req is True or (isinstance(lp_req, int)
                                and not isinstance(lp_req, bool)
                                and lp_req == 0):
            want_lp = True
        else:
            raise BadRequest(
                "'logprobs' top-alternatives are not supported; pass true "
                "(or 0) for chosen-token logprobs"
            )
        n = _field(req, "n", 1, int, minimum=1)
        if n > 8:
            raise BadRequest("'n' must be <= 8")
        timeout_s = req.get("timeout_s")
        if timeout_s is not None:
            # Per-request deadline: generation past it cancels at the next
            # chunk boundary and returns finish_reason "timeout" with the
            # tokens produced so far; a request still QUEUED at expiry is
            # shed with 503 + Retry-After instead of admitted doomed.
            if (not isinstance(timeout_s, (int, float))
                    or isinstance(timeout_s, bool)
                    or not math.isfinite(float(timeout_s))
                    or float(timeout_s) <= 0):
                raise BadRequest("'timeout_s' must be a positive number")
            timeout_s = float(timeout_s)
        else:
            timeout_s = self.request_timeout_s  # server-wide default (maybe None)
        priority = req.get("priority", 0)
        # Extension field: admission order (higher first; FIFO within a
        # priority) and preemption shield — under pool pressure the engine
        # preempts the lowest-priority, most-recently-admitted row first.
        if (isinstance(priority, bool) or not isinstance(priority, int)
                or not -(2**31) <= priority < 2**31):
            raise BadRequest("'priority' must be an integer")
        tenant = self._parse_tenant(req, tenant_hdr)
        # THE admission-token estimate (prompt + budget per choice) — the
        # cost gate, the tenant rate gate, and the accepted request's
        # ledger charge all read this one value, so what is gated is
        # exactly what is billed.
        est = n * (len(prompt_ids) + max_tokens)
        # Shed gates, all BEFORE any delivery state is registered: a shed
        # request must leave zero trace (no _Mailbox, no batcher queue
        # entry) — the leak-check test pins this.
        if self._inflight() + n > self.max_pending:
            await self._shed_json(
                writer, 429, "server request queue is full", "queue_full"
            )
            return
        if self.shed_cost_factor:
            # Estimated-cost gate: token mass already committed (queued +
            # resident prompt+budget) plus this request against the KV
            # capacity.  Sustained overload 429s at the front door — the
            # cheap place — instead of queueing work doomed to time out.
            mass = self._pending_token_mass() + est
            cap = self.batcher.capacity_tokens()
            if mass > self.shed_cost_factor * cap:
                await self._shed_json(
                    writer, 429,
                    f"server overloaded: {mass} tokens of work queued "
                    f"against {cap}-token KV capacity", "cost_gate",
                )
                return
        if self.tenant_quota_tps is not None:
            # Per-tenant token-rate quota: shed with the TENANT's own
            # Retry-After (when its trailing window frees) — the other
            # tenants' headroom is none of this request's business.
            # Untagged requests bill the shared ANONYMOUS bucket at the
            # default weight (scheduler parity) — dropping the X-Tenant
            # header is not an escape hatch from the rate gate.
            key = tenant if tenant is not None else ANON_TENANT
            allowed = self._tenant_allowance(key)
            if est > allowed:
                # Bigger than the tenant's ENTIRE window allowance: a 429
                # would promise a Retry-After that can never come true
                # (the ledger can't free room the quota doesn't hold) —
                # this is a malformed-for-this-tenant request, not load.
                await self._json(writer, 400, _err_body(
                    f"request needs {est} admission tokens but tenant "
                    f"{key!r}'s quota window holds at most {int(allowed)}"
                ))
                return
            hint = self._tenant_retry_after(key, est)
            if hint is not None:
                if tenant is not None:
                    METRICS.inc(f"tenant.shed.{tenant}")
                # A backstop trip is a DIFFERENT event from an ordinary
                # quota shed: the authoritative (router) gate let ~2x
                # fair share through — it was bypassed, drilled, or is
                # misconfigured — and dashboards must see that class.
                await self._shed_json(
                    writer, 429,
                    f"tenant {key!r} over its token-rate quota "
                    f"({est} tokens would exceed the "
                    f"{self.tenant_rate_window_s:g}s window)",
                    "tenant_backstop" if self.tenant_backstop_x is not None
                    else "tenant_quota", retry_after=hint,
                )
                return
        if self._draining and not self._stopping:
            # Graceful drain (rolling restarts): 503 tells load balancers
            # to retry elsewhere — 500 would read as an application error.
            await self._json(
                writer, 503, _err_body("server is draining"),
                headers={"Retry-After": str(self._retry_after_s())},
            )
            return
        if self._stopping:
            await self._json(writer, 500, _err_body("server is shutting down"))
            return
        if self._engine_dead:
            # Recovery itself failed (the engine thread exited): a submit
            # would queue into a batcher nothing will ever run — answer
            # with the structured engine error instead of hanging the
            # handler forever.  /healthz is already non-200.
            await self._json(
                writer, 500, _err_body("engine unrecoverable", "engine_error")
            )
            return
        # One batcher request per choice.  Register each mailbox BEFORE its
        # submit: the engine thread may already be inside run() and can
        # admit + deliver the moment the request hits the queue — a mailbox
        # registered after submit would miss those deliveries (and hang
        # forever on a 1-chunk completion).  All submissions happen on this
        # loop thread, so next_rid is ours.  The whole block holds
        # _submit_lock (pure host bookkeeping, no awaits) so the
        # supervisor's batcher swap cannot interleave and strand a request
        # in a dying batcher's queue.
        deadline = t0 + timeout_s if timeout_s is not None else None
        meta = dict(
            ids=list(prompt_ids), max_new_tokens=max_tokens, prefix=prefix,
            temperature=temperature, top_p=top_p, top_k=top_k,
            presence_penalty=pres_pen, frequency_penalty=freq_pen,
            prefix_cache=use_cache, priority=priority, deadline=deadline,
            response_format=response_format, logit_bias=logit_bias,
            banned_tokens=banned_tokens, tenant=tenant,
        )
        subs: list[tuple[int, int, _Mailbox]] = []  # (choice index, rid, mbox)
        sub_err: Exception | None = None
        # Construct every mailbox BEFORE the first registration (graftflow
        # GF303): once choice 0's mailbox is in _requests, nothing on the
        # path to the cleanup handlers may raise — a failing construction
        # for choice 2 must not strand choice 1's registered entry.
        mboxes: list[_Mailbox] = []
        for _ in range(n):
            mbox = _Mailbox()
            mbox.t0 = t0  # latency clocks run from request receipt
            mbox.deadline = deadline
            mbox.meta = meta
            mboxes.append(mbox)
        with self._submit_lock:
            for idx, mbox in enumerate(mboxes):
                rid = self.batcher.next_rid
                self._requests[rid] = mbox
                try:
                    got = self.batcher.submit(
                        prompt_ids, max_new_tokens=max_tokens, prefix=prefix,
                        temperature=temperature, top_p=top_p, top_k=top_k,
                        presence_penalty=pres_pen, frequency_penalty=freq_pen,
                        prefix_cache=use_cache, priority=priority,
                        deadline=deadline, response_format=response_format,
                        logit_bias=logit_bias, banned_tokens=banned_tokens,
                        constraint=dfa, tenant=tenant,
                    )
                    assert got == rid
                except (ValueError, KeyError) as e:
                    self._requests.pop(rid, None)
                    for _, r, _m in subs:
                        # Already-queued siblings die too — via the cancel
                        # flag, NOT cancel_row: the engine thread may be
                        # mid-run() and owns the batcher state.
                        self._cancelled.add(r)
                        self._requests.pop(r, None)
                    sub_err = e
                    break
                except BaseException:
                    # Anything else (a failed rid-continuity assert, an
                    # engine invariant error) must not strand registered
                    # mailboxes in _requests: each leaked entry permanently
                    # inflates the queue-full gate's count — enough of them
                    # and every future request 429s on a server doing no
                    # work.  Clean up, then let the error surface.
                    self._requests.pop(rid, None)
                    for _, r, _m in subs:
                        self._cancelled.add(r)
                        self._requests.pop(r, None)
                    raise
                subs.append((idx, rid, mbox))
        if sub_err is not None:
            self._work.set()  # let an idle engine drain the flags
            await self._json(writer, 400, _err_body(str(sub_err)))
            return
        # The rate-quota ledger charges the ACCEPTED request — after the
        # last gate AND a fully successful submit: a 400 from the batcher
        # (oversized prefix, unknown cache id) must not burn the tenant's
        # window for zero service.
        self._tenant_charge(tenant, est)
        self._work.set()
        METRICS.inc("server.requests")
        try:
            # Inside the try on purpose (graftflow GF303): everything
            # between the mailbox registrations and this finally must be
            # unable to raise, or the registered mailboxes leak — the id
            # mint and clock read ride the same cleanup as the serve path.
            oid = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:24]
            created = int(time.time())
            if stream:
                await self._serve_stream(
                    writer, subs, stop, chat, oid, created, want_lp
                )
            else:
                await self._serve_blocking(
                    writer, subs, stop, chat, oid, created,
                    len(prompt_ids), want_lp
                )
        except (ConnectionError, OSError, asyncio.TimeoutError):
            METRICS.inc("server.disconnects")
        finally:
            METRICS.observe(
                "server.request_seconds",
                time.perf_counter() - subs[0][2].t0,
            )
            # Runs on EVERY exit (normal, disconnect, or an unexpected
            # exception from the serve path): rows still generating get
            # cancel-flagged — the engine consumes the flag at its next
            # delivery; only unfinished rids are flagged because rids are
            # never reused and a stale flag would sit in the set forever.
            with self._submit_lock:
                for _, rid, mbox in subs:
                    if mbox.finished:
                        # Drop any stop-flag the engine never got to
                        # consume (the row finished naturally in the same
                        # delivery).
                        self._cancelled.discard(rid)
                    else:
                        self._cancelled.add(rid)
                    self._requests.pop(rid, None)

    # -- disaggregated serving: prefill handoff + KV import ---------------

    async def _prefill(self, writer, req: dict) -> None:
        """Prefill-role front door (``POST /v1/prefill``): run the
        prompt through this engine's ordinary admission (max_new_tokens=1,
        automatic prefix caching ON — the prompt's full pages publish
        content-addressed), export the cached run, and SHIP it to the
        requesting decode engine's KV listener over cluster/kv_transfer.py
        — per-attempt deadline, bounded jittered-exponential retries,
        bounded in-flight transfers.  Every outcome is a structured JSON
        answer; the router treats anything but ``ok: true`` as a handoff
        failure and degrades to colocated prefill."""
        from ..cluster import kv_transfer
        from .faults import InjectedFault

        plane = self.batcher.faults
        if plane is not None:
            # Injection site "prefill.crash": the mid-handoff death drill.
            # close/raise = abrupt replica death (sockets severed
            # unflushed) — the router observes a reset, not an answer.
            try:
                rule = plane.fire("prefill.crash", defer_stall=True)
            except InjectedFault:
                rule = None
                await self.kill()
                return
            if rule is not None and rule.action == "close":
                await self.kill()
                return
            if rule is not None and rule.action in ("delay", "stall"):
                await asyncio.sleep(rule.arg or 0.0)
        prompt_ids, _ = self._parse_prompt(req, chat=False)
        kv_host = req.get("kv_host")
        kv_port = req.get("kv_port")
        transfer_id = req.get("transfer_id")
        if not isinstance(kv_host, str) or not kv_host:
            raise BadRequest("'kv_host' must be a non-empty string")
        if (isinstance(kv_port, bool) or not isinstance(kv_port, int)
                or not 0 < kv_port < 65536):
            raise BadRequest("'kv_port' must be a TCP port")
        if not isinstance(transfer_id, str) or not transfer_id:
            raise BadRequest("'transfer_id' must be a non-empty string")
        if self._inflight() + 1 > self.max_pending:
            await self._shed_json(
                writer, 429, "server request queue is full", "queue_full"
            )
            return
        if self._draining and not self._stopping:
            await self._json(
                writer, 503, _err_body("server is draining"),
                headers={"Retry-After": str(self._retry_after_s())},
            )
            return
        if self._stopping:
            await self._json(writer, 500, _err_body("server is shutting down"))
            return
        if self._engine_dead:
            await self._json(
                writer, 500, _err_body("engine unrecoverable", "engine_error")
            )
            return
        METRICS.inc("server.prefill_requests")
        meta = dict(ids=list(prompt_ids), max_new_tokens=1,
                    prefix_cache=True)
        with self._submit_lock:
            rid = self.batcher.next_rid
            mbox = _Mailbox()
            mbox.meta = meta
            mbox.export_ids = list(prompt_ids)
            self._requests[rid] = mbox
            try:
                got = self.batcher.submit(
                    prompt_ids, max_new_tokens=1, prefix_cache=True
                )
                assert got == rid
            except (ValueError, KeyError) as e:
                self._requests.pop(rid, None)
                await self._json(writer, 400, _err_body(str(e)))
                return
            except BaseException:
                self._requests.pop(rid, None)
                raise
        self._work.set()
        try:
            fail = None
            while True:
                try:
                    _toks, done, err, _lps = await asyncio.wait_for(
                        mbox.queue.get(), 60.0
                    )
                except asyncio.TimeoutError:
                    fail = "prefill timed out"
                    break
                if done:
                    mbox.finished = True
                    if err is not None:
                        fail = err
                    break
        finally:
            with self._submit_lock:
                if not mbox.finished:
                    self._cancelled.add(rid)
                self._requests.pop(rid, None)
        if fail is not None:
            await self._json(writer, 500, _err_body(fail, _err_type(fail)))
            return
        export = mbox.export_result
        payload = export[1] if export is not None else None
        if payload is None:
            # Nothing shipped: prompt under one full page, caching off,
            # or the run was evicted before the gather.  Not an error —
            # the router simply serves the request colocated.
            await self._json(writer, 200, {
                "ok": False, "reason": "nothing to export", "pages": 0,
            })
            return
        digests, k_pages, v_pages = payload
        # b64 of a multi-MB payload runs off the loop: this same loop
        # answers the fleet's /healthz probes.
        msg = await asyncio.to_thread(
            kv_transfer.encode_kv_pages, kv_transfer.KVTransferPayload(
                transfer_id=transfer_id,
                token_ids=list(
                    prompt_ids[: len(digests) * self.batcher.page_size]
                ),
                page_size=self.batcher.page_size,
                digests=digests, k_pages=k_pages, v_pages=v_pages,
            ),
        )
        async with self._xfer_sem:
            res = await kv_transfer.send_kv_pages(
                kv_host, kv_port, msg, faults=plane,
                attempt_s=self.xfer_attempt_s,
                max_retries=self.xfer_max_retries,
            )
        await self._json(writer, 200, {
            "ok": res.ok, "reason": res.reason, "attempts": res.attempts,
            "pages": len(digests),
            "tokens": len(digests) * self.batcher.page_size,
            "bytes": res.bytes_sent,
            "digests": [d.hex() for d in digests],
        })

    async def _kv_export(self, writer, req: dict) -> None:
        """Cross-replica pull source (``POST /v1/kv_export``, from the
        router's fleet digest directory): gather the prompt's longest
        CACHED page run — engine thread, at a round boundary; nothing is
        admitted or recomputed here — and ship it to the pulling decode
        replica's KV listener over cluster/kv_transfer.py, verified and
        retried exactly like a prefill handoff.  The ``xfer.pull`` fault
        site (tag = transfer id) drills the ship path: 'drop' refuses the
        export, 'corrupt' flips payload bytes after the checksum (the
        puller-side verify NACKs every attempt), 'dup' ships the verified
        frame twice (the receiver absorbs the duplicate), 'delay' stalls
        toward the router's pull deadline.  Every outcome is a structured
        JSON answer; anything but ``ok: true`` makes the router degrade
        to local recompute — byte-exact regardless."""
        from ..cluster import kv_transfer

        prompt_ids, _ = self._parse_prompt(req, chat=False)
        kv_host = req.get("kv_host")
        kv_port = req.get("kv_port")
        transfer_id = req.get("transfer_id")
        if not isinstance(kv_host, str) or not kv_host:
            raise BadRequest("'kv_host' must be a non-empty string")
        if (isinstance(kv_port, bool) or not isinstance(kv_port, int)
                or not 0 < kv_port < 65536):
            raise BadRequest("'kv_port' must be a TCP port")
        if not isinstance(transfer_id, str) or not transfer_id:
            raise BadRequest("'transfer_id' must be a non-empty string")
        if self._stopping or self._draining or self._engine_dead:
            await self._json(writer, 200, {
                "ok": False, "reason": "replica unavailable", "pages": 0,
            })
            return
        plane = self.batcher.faults
        rule = None
        if plane is not None:
            # defer_stall: this handler runs on the serving event loop —
            # a delay/stall rule is applied as an awaited sleep below.
            rule = plane.fire("xfer.pull", tag=transfer_id,
                              defer_stall=True)
        if rule is not None and rule.action == "drop":
            await self._json(writer, 200, {
                "ok": False, "reason": "pull dropped (drill)", "pages": 0,
            })
            return
        if rule is not None and rule.action in ("delay", "stall"):
            await asyncio.sleep(rule.arg or 0.0)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def on_done(payload) -> None:
            # Engine thread -> loop: same crossing as mailbox deliveries.
            def settle() -> None:
                if not fut.done():
                    fut.set_result(payload)

            loop.call_soon_threadsafe(settle)

        with self._submit_lock:
            self.batcher.submit_kv_export(list(prompt_ids), on_done)
        self._work.set()
        try:
            # Bounded so a crashed engine cannot wedge the router's pull
            # (which has its own, shorter deadline) or leak this handler.
            payload = await asyncio.wait_for(fut, 30.0)
        except asyncio.TimeoutError:
            await self._json(writer, 200, {
                "ok": False, "reason": "export timed out", "pages": 0,
            })
            return
        if payload is None:
            # Run not resident: prompt under one full page, caching off,
            # or the pages were evicted since the directory entry was
            # recorded (a stale answer).  Not an error — the router
            # recomputes locally.
            await self._json(writer, 200, {
                "ok": False, "reason": "nothing to export", "pages": 0,
            })
            return
        digests, k_pages, v_pages = payload
        # b64 of a multi-MB payload runs off the loop: this same loop
        # answers the fleet's /healthz probes.
        msg = await asyncio.to_thread(
            kv_transfer.encode_kv_pages, kv_transfer.KVTransferPayload(
                transfer_id=transfer_id,
                token_ids=list(
                    prompt_ids[: len(digests) * self.batcher.page_size]
                ),
                page_size=self.batcher.page_size,
                digests=digests, k_pages=k_pages, v_pages=v_pages,
            ),
        )
        if rule is not None and rule.action == "corrupt":
            # Post-checksum bit-flip: the frame parses but can never
            # verify — the pull target NACKs every attempt and the
            # router degrades to local recompute, cache unpoisoned.
            msg = kv_transfer.corrupt_payload(msg)
        async with self._xfer_sem:
            res = await kv_transfer.send_kv_pages(
                kv_host, kv_port, msg, faults=plane,
                attempt_s=self.xfer_attempt_s,
                max_retries=self.xfer_max_retries,
            )
            if res.ok and rule is not None and rule.action == "dup":
                # Deliver the verified frame AGAIN: the receiver's digest
                # check absorbs it ("duplicate" ack), pinning pull-path
                # idempotence.
                await kv_transfer.send_kv_pages(
                    kv_host, kv_port, msg, faults=plane,
                    attempt_s=self.xfer_attempt_s,
                    max_retries=self.xfer_max_retries,
                )
        await self._json(writer, 200, {
            "ok": res.ok, "reason": res.reason, "attempts": res.attempts,
            "pages": len(digests),
            "tokens": len(digests) * self.batcher.page_size,
            "bytes": res.bytes_sent,
            "digests": [d.hex() for d in digests],
        })

    async def _handle_kv(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        """Decode-role KV listener: verify each KV_PAGES frame (checksum +
        digest-chain recompute, the ``xfer.recv``/``xfer.verify`` sites)
        and hand verified payloads to the engine thread for adoption."""
        from ..cluster import kv_transfer
        from .batcher import PrefixCache

        self._conns.add(writer)
        try:
            await kv_transfer.handle_kv_connection(
                reader, writer,
                # Digest recompute must use the engine's salt: pool
                # digests fold in the KV width (--kv-bits), so a frame
                # from a differently-configured sender reads as a chain
                # mismatch instead of poisoning the cache.
                page_digests_fn=functools.partial(
                    PrefixCache.page_digests,
                    kv_bits=getattr(self.batcher, "kv_bits", 16),
                ),
                import_fn=self._kv_import,
                faults=self.batcher.faults,
                stats=self.kv_stats,
            )
        except (ConnectionError, OSError):
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    async def _kv_import(self, payload) -> tuple[bool, str]:
        """Bridge one verified transfer to the engine thread: queue it on
        the batcher (under the submit lock, so the supervisor's batcher
        swap cannot strand it unseen), wake the engine, await the
        engine-side completion."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def on_done(ok: bool, reason: str) -> None:
            # Engine thread -> loop: same crossing as mailbox deliveries.
            def settle() -> None:
                if not fut.done():
                    fut.set_result((ok, reason))

            loop.call_soon_threadsafe(settle)

        with self._submit_lock:
            self.batcher.submit_kv_import(
                payload.digests, payload.k_pages, payload.v_pages, on_done
            )
        self._work.set()
        return await fut

    async def _collect_until_done(self, mbox, rid, stop, need_text=True):
        """Drain the mailbox; yield (text_so_far, ids_so_far, done, err).
        ``err`` is "stopped" when a stop sequence truncated the text (the
        rid is then flagged for engine-side cancel, and the generator keeps
        draining until the cancel ack so the row is verifiably freed).
        Token accounting lives HERE, not in ``batcher.results`` — the
        engine thread clears that dict between runs, so readers on the
        loop thread would race it.  ``need_text=False`` (blocking handler,
        no stop strings) skips the per-delivery decode and yields
        ``text=None`` until the final delivery — per-delivery full decodes
        are O(n^2) over a generation and all on the loop thread."""
        tok = self.batcher.tokenizer
        ids: list[int] = []
        lps: list[float] = []
        stopped_at: int | None = None
        timed_out = False
        scanned = 0  # chars already known stop-free
        hold = max((len(s) for s in stop), default=1) - 1
        while True:
            try:
                if timed_out:
                    # Deadline already hit; we only wait (briefly) for the
                    # engine to ack the cancel so the row is provably freed.
                    toks, done, err, new_lps = await asyncio.wait_for(
                        mbox.queue.get(), _TIMEOUT_ACK_GRACE_S
                    )
                elif mbox.deadline is not None:
                    try:
                        # Deliveries already sitting in the mailbox were
                        # produced BEFORE now (possibly the final done) —
                        # bill them even if the deadline lapsed while this
                        # handler was blocked writing to a slow client.
                        # Only an EMPTY mailbox past the deadline times out.
                        toks, done, err, new_lps = mbox.queue.get_nowait()
                    except asyncio.QueueEmpty:
                        remaining = mbox.deadline - time.perf_counter()
                        if remaining <= 0:
                            raise asyncio.TimeoutError from None
                        toks, done, err, new_lps = await asyncio.wait_for(
                            mbox.queue.get(), remaining
                        )
                else:
                    toks, done, err, new_lps = await mbox.queue.get()
            except asyncio.TimeoutError:
                if timed_out:
                    # Engine never acked within the grace window (stalled):
                    # answer the client anyway.  The cancel flag stays set,
                    # so the row still frees whenever the engine recovers.
                    if stopped_at is not None:
                        yield None, ids, lps, True, "stopped"
                    else:
                        yield tok.decode(ids), ids, lps, True, "timeout"
                    return
                # Deadline expired.  After a stop-sequence hit the response
                # already terminated on "stop" and we are only draining the
                # cancel ack — switch to the bounded ack wait but don't
                # relabel a legitimate stop as a timeout (the rid is
                # already cancel-flagged from the hit).
                timed_out = True
                if stopped_at is None:
                    with self._submit_lock:
                        self._cancelled.add(rid)
                    self._work.set()
                    METRICS.inc("server.request_timeouts")
                continue
            if timed_out:
                # Post-deadline deliveries exist only to confirm the row is
                # freed; their tokens arrived past the deadline — not billed.
                if done:
                    mbox.finished = True
                    if err is not None and err.startswith(_SHED_ERR):
                        # The engine shed the still-queued request at this
                        # chunk boundary: nothing was ever produced — the
                        # answer is 503 + Retry-After, not an empty 200.
                        yield "", ids, lps, True, err
                    elif stopped_at is not None:
                        yield None, ids, lps, True, "stopped"
                    else:
                        yield tok.decode(ids), ids, lps, True, "timeout"
                    return
                continue
            if err is None and not mbox.first_seen:
                # Time to first token, measured from request receipt
                # (mbox.t0 is set by _completions from _handle's clock, so
                # body read + parse + tokenization count).  Error/shutdown
                # notices are NOT samples — they would poison the
                # quantiles with time-to-failure.  Exported at /metrics.
                mbox.first_seen = True
                METRICS.observe(
                    "server.ttft_seconds", time.perf_counter() - mbox.t0
                )
            if err is not None:
                mbox.finished = True
                yield "", ids, lps, True, err
                return
            if stopped_at is None:
                # Past the stop cut, later deliveries (the cancel-ack chunk)
                # are not part of the response — don't bill them.
                ids.extend(toks)
                if new_lps is not None:
                    lps.extend(new_lps)
                text = tok.decode(ids) if (need_text or stop or done) else None
                hit = -1
                if text is not None and stop:
                    # Only the unscanned tail can hit, minus a lookbehind
                    # for stops spanning a delivery boundary.
                    start = max(0, scanned - hold)
                    hit = min(
                        (i for i in (text.find(s, start) for s in stop) if i >= 0),
                        default=-1,
                    )
                    scanned = len(text)
                if hit >= 0:
                    stopped_at = hit
                    text = text[:hit]
                    # Align the token-level view with the truncated text:
                    # keep only the tokens whose decode fits within the
                    # cut, so logprobs/usage agree with the returned text.
                    # (Streaming may have shipped a few pre-cut logprob
                    # entries already — deltas can't be retracted; the
                    # blocking response is exact.)
                    keep = 0
                    while (keep < len(ids)
                           and len(tok.decode(ids[: keep + 1])) <= hit):
                        keep += 1
                    del ids[keep:]
                    del lps[keep:]
                    if not done:
                        # Flag for the engine; its next delivery for this
                        # rid (one chunk away at most — an active row
                        # streams every chunk) is the done ack.
                        with self._submit_lock:
                            self._cancelled.add(rid)
                if done:
                    mbox.finished = True
                yield text, ids, lps, done, (
                    "stopped" if stopped_at is not None and done else None
                )
                if done:
                    return
            elif done:
                # Cancel ack after a stop hit: no new text (None marks the
                # truncated text already delivered as authoritative).
                mbox.finished = True
                yield None, ids, lps, True, "stopped"
                return

    async def _gather_choice(self, mbox, rid, stop):
        """Drain one choice to completion.  Returns
        (text, ids, lps, finish_reason, fatal_err)."""
        text = ""
        ids: list[int] = []
        lps: list[float] = []
        reason = "length"
        async for t, ids, lps, done, err in self._collect_until_done(
            mbox, rid, stop, need_text=bool(stop)
        ):
            if err == "stopped":
                if t is not None:
                    text = t
                reason = "stop"
                break
            if err == "timeout":
                # Deadline hit: the tokens produced so far ARE the response.
                if t is not None:
                    text = t
                reason = "timeout"
                break
            if err is not None:
                return text, ids, lps, reason, err
            text = t
            if done:
                break
        if reason == "timeout" and not ids and mbox.delivered == 0:
            # Deadline expired with NOTHING ever produced — still queued,
            # or admitted but mid-chunked-prefill (the only admitted state
            # with zero deliveries); either way the engine's shed ack may
            # have been eaten by a stall.  No deltas ever reached the
            # client, so a retry is safe: answer a 503 shed, not a useless
            # empty 200 "timeout".
            return text, ids, lps, reason, \
                _SHED_ERR + "deadline expired before any output was produced"
        if reason == "length" and self.batcher.eos_id >= 0 and (
            ids and ids[-1] == self.batcher.eos_id
        ):
            reason = "stop"
        return text, ids, lps, reason, None

    async def _serve_blocking(
        self, writer, subs, stop, chat, oid, created, n_prompt,
        want_lp=False,
    ) -> None:
        outs = await asyncio.gather(*[
            self._gather_choice(mbox, rid, stop) for _, rid, mbox in subs
        ])
        fatal = next((e for *_x, e in outs if e is not None), None)
        if fatal is not None:
            if fatal.startswith(_SHED_ERR):
                # Load-shed before admission: 503 + Retry-After tells the
                # client (and its load balancer) to back off and retry —
                # the request was never worked on, so a retry is safe.
                await self._shed_json(writer, 503, fatal, "queue_deadline")
            else:
                await self._json(
                    writer, 500, _err_body(fatal, _err_type(fatal))
                )
            return
        choices = []
        total_completion = 0
        cached = [m.cached_tokens for _, _, m in subs
                  if m.cached_tokens is not None]
        for (idx, _rid, _mbox), (text, ids, lps, reason, _e) in zip(subs, outs):
            choice = (
                {"index": idx,
                 "message": {"role": "assistant", "content": text},
                 "finish_reason": reason}
                if chat else
                {"index": idx, "text": text, "logprobs": None,
                 "finish_reason": reason}
            )
            if want_lp:
                choice["logprobs"] = _lp_field(
                    self.batcher.tokenizer, ids, lps, chat
                )
            choices.append(choice)
            total_completion += len(ids)
        await self._json(writer, 200, {
            "id": oid,
            "object": "chat.completion" if chat else "text_completion",
            "created": created,
            "model": self.model_name,
            "choices": choices,
            "usage": {
                "prompt_tokens": n_prompt,
                "completion_tokens": total_completion,
                "total_tokens": n_prompt + total_completion,
                # OpenAI usage extension: prompt tokens served from the
                # automatic prefix cache instead of being re-prefilled
                # (max across choices — every choice shares one prompt).
                **({"prompt_tokens_details": {"cached_tokens": max(cached)}}
                   if cached else {}),
            },
        })

    async def _stream_choice(
        self, writer, wlock, mbox, rid, index, stop, chat, oid, created,
        want_lp
    ) -> None:
        """Stream one choice's SSE chunks (its `index` tags every chunk);
        n>1 choices interleave on the same connection, each driven by its
        own task.  ``wlock`` serializes write+drain across sibling tasks:
        StreamWriter.drain is not reentrant (FlowControlMixin asserts a
        single waiter), so two choices draining concurrently under write
        backpressure would raise AssertionError."""

        async def emit(data: bytes) -> None:
            async with wlock:
                writer.write(data)
                await writer.drain()

        sent = 0
        lp_sent = 0
        reason = "length"
        stop_hold = max((len(s) for s in stop), default=1) - 1

        def chunk(delta: str, finish: str | None,
                  lp_items: tuple | None = None) -> bytes:
            choice = (
                {"index": index, "delta": ({"content": delta} if delta else {}),
                 "finish_reason": finish}
                if chat else
                {"index": index, "text": delta, "logprobs": None,
                 "finish_reason": finish}
            )
            if lp_items is not None:
                choice["logprobs"] = _lp_field(
                    self.batcher.tokenizer, lp_items[0], lp_items[1], chat
                )
            payload = {
                "id": oid,
                "object": "chat.completion.chunk" if chat else "text_completion",
                "created": created,
                "model": self.model_name,
                "choices": [choice],
            }
            return b"data: " + json.dumps(payload).encode() + b"\n\n"

        if chat:
            # OpenAI stream fidelity: the first chunk announces the role.
            await emit(
                b"data: " + json.dumps({
                    "id": oid, "object": "chat.completion.chunk",
                    "created": created, "model": self.model_name,
                    "choices": [{"index": index,
                                 "delta": {"role": "assistant"},
                                 "finish_reason": None}],
                }).encode() + b"\n\n"
            )
        stopped = False
        last_text = None  # survives the cancel-ack yield (text=None)
        async for text, ids, lps, done, err in self._collect_until_done(mbox, rid, stop):
            if err == "stopped":
                stopped = True
            elif err == "timeout":
                reason = "timeout"  # final chunk carries it below
            elif err is not None:
                await emit(
                    b"data: "
                    + json.dumps(_err_body(err, _err_type(err))).encode()
                    + b"\n\n"
                )
                break
            if text is not None:
                last_text = text
            else:
                text = last_text
            if text is None:
                delta = ""
            else:
                # Streamed deltas cannot be retracted, so hold back text
                # that may still change: (a) a trailing U+FFFD — usually a
                # partially-decoded multi-byte sequence whose chars CHANGE
                # once the continuation tokens arrive; (b) a tail that
                # could become the head of a stop sequence spanning a
                # delivery boundary (the blocking path would truncate it).
                if done:
                    emit_src = text
                else:
                    emit_src = text.rstrip("\ufffd")
                    if stop_hold:
                        emit_src = emit_src[: max(sent, len(emit_src) - stop_hold)]
                delta = emit_src[sent:]
                sent = max(sent, len(emit_src))
            def lp_slice():
                nonlocal lp_sent
                if not want_lp:
                    return None
                items = (ids[lp_sent:len(lps)], lps[lp_sent:])
                lp_sent = len(lps)
                return items
            if delta and not done:
                await emit(chunk(delta, None, lp_slice()))
            if done:
                if reason == "length" and (stopped or (
                    self.batcher.eos_id >= 0 and ids
                    and ids[-1] == self.batcher.eos_id
                )):
                    reason = "stop"
                await emit(chunk(delta, reason, lp_slice()))
                break

    async def _serve_stream(
        self, writer, subs, stop, chat, oid, created, want_lp=False
    ) -> None:
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Connection: close\r\n\r\n"
        )
        await writer.drain()
        # One task per choice; chunks interleave, each tagged with its
        # choice index, writes serialized by a shared per-connection lock
        # (drain is not reentrant).  return_exceptions so one dead socket
        # lets every sibling finish its drain before the disconnect
        # propagates.
        wlock = asyncio.Lock()
        results = await asyncio.gather(*[
            self._stream_choice(writer, wlock, mbox, rid, idx, stop, chat,
                                oid, created, want_lp)
            for idx, rid, mbox in subs
        ], return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                raise r
        writer.write(b"data: [DONE]\n\n")
        await writer.drain()

    # -- response helpers --------------------------------------------------

    async def _plain(self, writer, code: int, body: str) -> None:
        await self._respond(writer, code, "text/plain", body.encode())

    async def _json(self, writer, code: int, obj: dict,
                    headers: dict[str, str] | None = None) -> None:
        await self._respond(
            writer, code, "application/json",
            (json.dumps(obj) + "\n").encode(), headers=headers,
        )

    async def _shed_json(self, writer, code: int, msg: str,
                         reason: str, retry_after: int | None = None) -> None:
        """Answer a shed request (429 too-busy / 503 not-yet-admitted):
        structured overloaded_error body + a Retry-After header so clients
        and load balancers back off instead of retrying hot, and the shed
        counters the dashboards alarm on.  The body carries the machine-
        readable ``reason`` (queue_full / cost_gate / tenant_quota / ...)
        so clients can distinguish "the server is busy" from "MY quota is
        exhausted"; ``retry_after`` overrides the global hint with a
        per-tenant one."""
        METRICS.inc("server.requests_shed_total")
        METRICS.inc(f"server.requests_shed.{reason}")
        body = _err_body(msg, "overloaded_error")
        body["error"]["reason"] = reason
        await self._json(
            writer, code, body,
            headers={"Retry-After": str(
                retry_after if retry_after is not None
                else self._retry_after_s()
            )},
        )

    async def _respond(self, writer, code: int, ctype: str, payload: bytes,
                       headers: dict[str, str] | None = None) -> None:
        extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
        writer.write(
            (
                f"HTTP/1.1 {code} {_REASONS.get(code, '')}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"{extra}"
                "Connection: close\r\n\r\n"
            ).encode()
            + payload
        )
        await writer.drain()


class _Responded(Exception):
    """Internal: the parse phase already wrote an error response."""


def _err_body(msg: str, type_: str = "invalid_request_error") -> dict:
    return {"error": {"message": msg, "type": type_}}


def _err_type(msg: str) -> str:
    """Error class for a mailbox-delivered failure: engine-side faults get
    a structured machine-readable type (clients distinguish 'the engine
    restarted under me, retry if idempotent' from bad input, from 'the
    server shed me unworked — retry after backoff')."""
    if msg in (_RESTART_ERR, "engine unrecoverable"):
        return "engine_error"
    if msg.startswith(_SHED_ERR):
        return "overloaded_error"
    return "server_error"


def _lp_field(tok, ids: list[int], lps: list[float], chat: bool) -> dict:
    """OpenAI logprobs shapes: completions carries parallel arrays, chat
    carries per-token objects.  ``ids``/``lps`` align 1:1 (the batcher
    emits them together); tokens render as their individual decode."""
    pieces = [tok.decode([i]) for i in ids[: len(lps)]]
    lps = [round(v, 6) for v in lps]
    if chat:
        return {"content": [
            {"token": p, "logprob": v} for p, v in zip(pieces, lps)
        ]}
    return {"tokens": pieces, "token_logprobs": lps,
            "top_logprobs": None, "text_offset": None}
