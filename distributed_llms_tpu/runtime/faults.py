"""Deterministic fault injection: the tested half of crash-only serving.

The cluster layer already implements the paper scaffold's fault contract
(heartbeat eviction, task retry/reassignment), but nothing in the tree could
*provoke* those paths on purpose — recovery was exercised only by killing
tasks and sleeping past wall-clock deadlines.  Crash-only design (Candea &
Fox, HotOS'03) demands the opposite: recovery must be the ordinary, tested
path.  This module is the lever: a ``FaultPlane`` holds named injection
rules that the hot paths consult at fixed sites, so a test (or an operator
drill via ``dlt-serve --fault``) can crash the Nth decode chunk, dry up the
KV page pool, stall the engine under the watchdog, or drop/delay/sever
cluster protocol frames — deterministically, with no timing dependence.

Spec grammar (comma-separated rules)::

    rule   := site[/tag] ":" action ["@" when] [":" arg]
    when   := N        fire on the Nth matching hit only (default: 1)
            | N+       fire on every matching hit from the Nth on
            | *        fire on every matching hit
    arg    := seconds (stall / delay)

Examples::

    batcher.decode:raise@3            crash the 3rd decode chunk
    batcher.page_alloc:exhaust@1+     every admission sees a dry page pool
    batcher.decode:stall@2:1.5        sleep 1.5 s before the 2nd chunk
    proto.send/HEARTBEAT:drop@1+      swallow all heartbeat frames
    proto.recv:close@5                sever the stream at the 5th frame

Sites wired in this tree (callers pass ``tag`` where noted):

- ``batcher.admit``       each admission round (ContinuousBatcher)
- ``batcher.decode``      before each decode/speculative chunk
- ``batcher.page_alloc``  paged-pool allocation check, tag = ``admit``
  (admission reservation) or ``grow`` (chunk-boundary on-demand growth);
  ``exhaust`` forces the pressure path as if the pool were dry — the
  caller then preempts a victim row or back-pressures, exactly as a real
  exhaustion would
- ``batcher.preempt``     one hit per row preemption, fired BEFORE the
  victim's pages are freed (a ``raise`` here crashes mid-preemption — the
  supervisor-restart drill for the preemption path; tests read
  ``rule.fired`` to pin how many preemptions a storm actually took)
- ``proto.send`` / ``proto.recv``  cluster protocol framing, tag = message
  type (install process-wide via ``cluster.protocol.set_fault_plane``)
- ``worker.heartbeat``    one heartbeat tick (``drop`` skips the send)
- ``worker.result``       a worker about to answer, tag = command type
- ``worker.handle``       a command handler about to run, tag = command type
- ``coordinator.dispatch``  a task about to be sent, tag = task type
- ``router.place``        one hit per router placement decision, tag = the
  chosen replica (``drop`` vetoes it — the router spills to the next-best
  healthy replica)
- ``replica.crash`` / ``replica.stall`` / ``replica.partition``  replica-
  scoped chaos, fired once per fleet probe tick per replica with tag =
  replica name (cluster/fleet.py): ``close`` kills the replica abruptly,
  ``delay:<s>`` wedges its engine past the watchdog, ``drop[:<s>]``
  partitions it from the router while it keeps running
- ``xfer.send`` / ``xfer.recv`` / ``xfer.verify``  the KV-handoff plane
  (cluster/kv_transfer.py): drop a transfer frame, corrupt its payload,
  deliver it twice, stall it, or force the receiver's verification to
  fail — the disaggregated prefill/decode drill set
- ``prefill.crash``  a prefill-role replica about to serve a handoff
  (``close``/``raise`` kills it mid-handoff)

Actions ``raise`` (raises :class:`InjectedFault`) and ``stall`` (blocking
sleep) are applied by :meth:`FaultPlane.fire` itself; the context-specific
actions (``exhaust``, ``drop``, ``delay``, ``close``, ``corrupt``, ``dup``)
are returned to the caller, which knows what "dropping" (or corrupting, or
duplicating) means at its site (``delay`` is returned rather than slept so
async call sites can ``await`` it).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..core.observability import METRICS, get_logger

log = get_logger("faults")

ACTIONS = frozenset({"raise", "exhaust", "stall", "drop", "delay", "close",
                     "corrupt", "dup"})
# Actions fire() applies itself; the rest are returned for the call site.
_SELF_APPLIED = frozenset({"raise", "stall"})

# THE registry of wired injection sites: every site string a hot path
# passes to fire() (and every site in an operator's --fault spec under
# strict parsing) must appear here.  graftlint's GL301 pins call sites to
# this dict and the README table is generated from it — a typo'd site is
# otherwise a rule that silently never fires.
FAULT_SITES: dict[str, str] = {
    "batcher.admit":
        "each admission round (ContinuousBatcher._admit_pending)",
    "batcher.decode":
        "before each decode/speculative chunk is dispatched",
    "batcher.page_alloc":
        "paged-pool allocation check; tag 'admit' (reservation) or 'grow' "
        "(chunk-boundary growth) — 'exhaust' forces the pressure path",
    "batcher.preempt":
        "one hit per row preemption, BEFORE the victim's pages are freed",
    "batcher.spec_verify":
        "each speculative draft/verify round about to dispatch (the "
        "round is one compiled program): tag 'draft' = the k draft "
        "steps, 'verify' = the (k+1)-token target pass — 'raise' is the "
        "supervisor-restart drill for the speculative leg (respawn "
        "re-admits and serves byte-exact), 'stall:<s>' wedges it for "
        "the watchdog",
    "batcher.mixed_step":
        "each mixed-schedule dispatch (runtime/scheduler.py): tag "
        "'prefill' when the step carries a fused prefill bite, 'decode' "
        "for a budget-only decode dispatch — 'raise' crashes the fused "
        "step (the supervisor-restart drill for the stall-free path), "
        "'stall:<s>' wedges it for the watchdog",
    "proto.send":
        "cluster protocol frame about to be written (tag = message type)",
    "proto.recv":
        "cluster protocol frame just read (tag = message type)",
    "worker.heartbeat":
        "one worker heartbeat tick ('drop' skips the send)",
    "worker.result":
        "a worker about to answer (tag = command type)",
    "worker.handle":
        "a worker command handler about to run (tag = command type)",
    "coordinator.dispatch":
        "a task about to be sent to a worker (tag = task type)",
    "router.place":
        "each router placement decision (tag = chosen replica name); "
        "'drop' vetoes the choice and spills to the next-best replica",
    "replica.crash":
        "one fleet probe tick per replica (tag = replica name); 'close' "
        "(or 'raise') kills the replica process-style — connections "
        "severed unflushed, engine reaped, no drain",
    "replica.stall":
        "one fleet probe tick per replica (tag = replica name); "
        "'delay:<s>' (or 'stall:<s>' — deferred, never blocks the fleet "
        "loop) wedges the replica's engine for <s> seconds (one blocking "
        "stall on its next decode chunk — the watchdog drill)",
    "replica.partition":
        "one fleet probe tick per replica (tag = replica name); "
        "'drop[:<s>]' makes the replica unreachable from the router for "
        "<s> seconds (no arg: until respawn) while it keeps running",
    "xfer.send":
        "one KV-handoff transfer attempt about to be sent "
        "(cluster/kv_transfer.py): 'drop' swallows the frame (the sender "
        "waits out its ack deadline and retries), 'corrupt' flips payload "
        "bytes in flight (the receiver's verify rejects), 'dup' delivers "
        "the frame twice (the import must be idempotent), 'delay:<s>' "
        "stalls the attempt",
    "xfer.recv":
        "one KV_PAGES frame just received by a decode-role engine: 'drop' "
        "ignores it (no ack — the sender times out and retries), "
        "'corrupt' mangles the payload before verification, 'delay:<s>' "
        "stalls the receive path",
    "xfer.verify":
        "KV-handoff payload verification (checksum + chained page "
        "digests): 'corrupt' forces a verification failure — the "
        "receiver NACKs and the sender retries or degrades to colocated "
        "prefill",
    "prefill.crash":
        "a prefill-role replica about to serve a /v1/prefill handoff "
        "request: 'close' (or 'raise') kills the replica abruptly "
        "mid-handoff — the router's degradation ladder must fall back to "
        "colocated prefill on the decode replica",
    "kv.swap_out":
        "a preemption victim about to SWAP its pages to the host tier "
        "(runtime/batcher.py): 'drop' skips the swap (falls back to "
        "exact recompute), 'corrupt' flips a parcel byte in host storage "
        "(checksum verification at restore degrades to recompute); "
        "'stall:<s>' models a slow D2H drill",
    "kv.swap_in":
        "one swap-restore attempt (a swapped request reaching the front "
        "of admission): 'drop' abandons the parcel (the request "
        "recomputes, exactly), 'corrupt' mangles the payload at take "
        "time — verification must catch it and fall back",
    "kv.spill":
        "host-tier spill plane; tag 'out' (cold cached pages about to be "
        "captured ahead of LRU eviction) or 'restore' (a prefix-cache "
        "hit about to restore spilled pages): 'drop' skips the movement "
        "(plain eviction / cold prefill — correct, just slower), "
        "'corrupt' flips spilled bytes so restore verification rejects "
        "them",
    "fleet.scale_up":
        "the autoscaler about to boot one more replica "
        "(cluster/autoscale.py): 'raise' or 'drop' fails the provision — "
        "the controller degrades cleanly (counts the failure, keeps "
        "serving at the current size, retries after its cooldown), the "
        "cloud-API-errored drill",
    "fleet.scale_down":
        "the autoscaler about to drain a replica away (tag = the chosen "
        "replica): 'raise'/'drop' vetoes the drain — the fleet keeps its "
        "size; scale-down is graceful-only, so there is no abrupt leg to "
        "drill here (replica.crash covers that)",
    "tenant.quota":
        "the serving gateway's per-tenant token-rate gate (tag = "
        "tenant): 'exhaust' forces the over-quota path — the request "
        "sheds 429 with the tenant's own Retry-After even under its "
        "rate, the per-tenant-shed drill",
    "router.ledger":
        "the router's fleet-wide tenant-ledger gate, the one admission-"
        "commit point (tag = tenant): 'exhaust' forces the over-quota "
        "path (429 + the tenant's fleet-ledger Retry-After), 'stall:<s>' "
        "wedges the gate (deferred — the admission path slows, never the "
        "event loop), 'drop' BYPASSES the gate and its charge — the "
        "replica gateways' loose backstop must still meter, never a "
        "silent unmetered path",
    "directory.lookup":
        "the router's fleet-wide prefix-digest directory about to answer "
        "a placement lookup: 'drop' makes every entry read stale (a "
        "directory miss — the decode replica recomputes locally, "
        "exactly), 'corrupt' mis-steers the lookup to a sibling that "
        "does not hold the pages (the pull finds nothing exportable and "
        "degrades to local recompute)",
    "xfer.pull":
        "a cross-replica KV pull about to ship off the source replica's "
        "cache (tag = transfer id): 'drop' refuses the export (the "
        "router degrades to local recompute), 'corrupt' flips payload "
        "bytes post-checksum so the pull target's verify NACKs every "
        "attempt, 'dup' ships the verified frame twice (idempotent "
        "absorb), 'delay:<s>' stalls the pull toward the router's "
        "deadline (deferred)",
}


# THE declared fault-action surface per site (FAULT_SITES-style: site ->
# comma-joined actions).  Keys mirror FAULT_SITES exactly (graftmodel's
# GM503 checks both directions); the value is the set of actions the call
# site actually handles — the actions an operator can arm and a chaos
# drill can exercise.  Two tools consume this registry: graftmodel's GM6
# fails the gate when a declared site x action pair has no tier-1 drill
# test (a declared fault nobody injects is an untested recovery path),
# and graftmodel's GM501 pins every fault edge in a PROTOCOL_MODELS
# transition system to a pair declared here.
SITE_ACTIONS: dict[str, str] = {
    "batcher.admit": "raise",
    "batcher.decode": "raise,stall",
    "batcher.page_alloc": "exhaust",
    "batcher.preempt": "raise",
    "batcher.spec_verify": "raise,stall",
    "batcher.mixed_step": "raise,stall",
    "proto.send": "close,delay",
    "proto.recv": "drop",
    "worker.heartbeat": "drop",
    "worker.result": "close",
    "worker.handle": "raise",
    "coordinator.dispatch": "drop",
    "router.place": "drop",
    "replica.crash": "close",
    "replica.stall": "delay",
    "replica.partition": "drop",
    "xfer.send": "drop,corrupt,dup,delay",
    "xfer.recv": "drop,corrupt",
    "xfer.verify": "corrupt",
    "prefill.crash": "close",
    "kv.swap_out": "drop,corrupt",
    "kv.swap_in": "drop,corrupt",
    "kv.spill": "drop,corrupt",
    "fleet.scale_up": "raise,drop",
    "fleet.scale_down": "raise,drop",
    "tenant.quota": "exhaust",
    "router.ledger": "exhaust,stall,drop",
    "directory.lookup": "drop,corrupt",
    "xfer.pull": "drop,corrupt,dup",
}


# THE registry of control-plane protocol models (FAULT_SITES-style: model
# name -> one-line doc).  Each entry names a ``*_MODEL`` transition-system
# literal declared NEXT TO the code it models; ``python -m tools.graftmodel``
# exhaustively enumerates the bounded interleavings of each machine composed
# with its declared fault actions (the SITE_ACTIONS pairs it names) and
# checks the GM1-GM4 safety invariants on every reachable state.  GM503
# fails the gate when this registry and the discovered model literals
# drift in either direction.
PROTOCOL_MODELS: dict[str, str] = {
    "router.ledger":
        "fleet-wide tenant ledger: charge on placement, refund on "
        "shed/failover, bypass metered by the gateway backstop "
        "(LEDGER_MODEL, runtime/router.py)",
    "cluster.kv_handoff":
        "KV handoff + cross-replica pull attempt lifecycle: checksummed "
        "frames, bounded retries, at-most-once adoption, per-reason "
        "fallback (HANDOFF_MODEL, cluster/kv_transfer.py)",
    "kv.parcels":
        "host-tier swap/spill parcel ownership: every parked parcel "
        "owned by exactly one queued resume or freed, budget conserved "
        "(PARCEL_MODEL, runtime/kv_tier.py)",
    "fleet.autoscale":
        "tiered autoscaler drain/respawn + epoch-keyed directory: "
        "size within [min,max], graceful-drain-only downs, stale "
        "epochs dropped (AUTOSCALE_MODEL, cluster/autoscale.py)",
}


# THE declared lock hierarchy, outermost first (FAULT_SITES-style: name ->
# one-line doc; dict order IS the order).  Every nested acquisition in the
# serving core must follow it — tools/graftflow's GF102 builds the global
# lock-acquisition graph (with-nesting + holds() annotations, propagated
# over the call graph) and fails the gate on any edge that contradicts
# this registry, GF101 on any cycle, GF103 on an entry naming a lock no
# class declares.  The order was previously prose ("lock order is
# _submit_lock -> batcher._lock, everywhere", runtime/server.py) — a new
# call path nesting the other way is a deadlock no unit test will find.
LOCK_ORDER: dict[str, str] = {
    "InferenceServer._submit_lock":
        "serving gateway: mailbox registry + cancel flags + the "
        "supervisor's batcher swap (loop and engine threads)",
    "ContinuousBatcher._lock":
        "engine submission queue, rid counter, pending KV imports",
    "PagePool._lock":
        "KV page allocator free list/refcounts + prefix-cache LRU",
    "Metrics._lock":
        "process-wide metrics registry (universal leaf: safe under any "
        "of the above, never holds anything itself)",
}


class InjectedFault(RuntimeError):
    """Raised by a ``raise`` rule.  Deliberately its own type so recovery
    tests can assert the injected path (and only it) was taken."""


@dataclass
class FaultRule:
    """One armed injection point.  ``hits`` counts matching traversals,
    ``fired`` how many times the rule actually triggered."""

    site: str
    action: str
    tag: str | None = None     # None matches any tag at the site
    first: int = 1             # fire from the Nth matching hit ...
    last: int | None = 1       # ... through this one (None = open-ended)
    arg: float | None = None   # seconds for stall/delay
    hits: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def matches(self, site: str, tag: str | None) -> bool:
        return self.site == site and (self.tag is None or self.tag == tag)

    def due(self) -> bool:
        """Whether the CURRENT hit count falls in the firing window."""
        if self.hits < self.first:
            return False
        return self.last is None or self.hits <= self.last

    def describe(self) -> str:
        site = self.site if self.tag is None else f"{self.site}/{self.tag}"
        when = ("*" if (self.first, self.last) == (1, None)
                else f"{self.first}+" if self.last is None
                else str(self.first))
        out = f"{site}:{self.action}@{when}"
        return out if self.arg is None else f"{out}:{self.arg:g}"


def _parse_rule(text: str) -> FaultRule:
    parts = text.strip().split(":")
    if len(parts) < 2:
        raise ValueError(
            f"fault rule {text!r} must look like site[/tag]:action[@when][:arg]"
        )
    site_tag, action_when = parts[0], parts[1]
    site, _, tag = site_tag.partition("/")
    if not site:
        raise ValueError(f"fault rule {text!r}: empty site")
    action, _, when = action_when.partition("@")
    if action not in ACTIONS:
        raise ValueError(
            f"fault rule {text!r}: unknown action {action!r} "
            f"(choose from {sorted(ACTIONS)})"
        )
    first, last = 1, 1
    if when:
        if when == "*":
            first, last = 1, None
        elif when.endswith("+"):
            first, last = int(when[:-1]), None
        else:
            first = last = int(when)
    if first < 1:
        raise ValueError(f"fault rule {text!r}: hit index must be >= 1")
    arg: float | None = None
    if len(parts) > 2:
        arg = float(parts[2])
        if arg < 0:
            raise ValueError(f"fault rule {text!r}: arg must be >= 0")
    if action in ("stall", "delay") and arg is None:
        raise ValueError(
            f"fault rule {text!r}: {action} needs a seconds arg "
            f"(e.g. {site}:{action}@1:0.5)"
        )
    return FaultRule(site=site, action=action, tag=tag or None,
                     first=first, last=last, arg=arg)


class FaultPlane:
    """A set of :class:`FaultRule`\\ s consulted by instrumented hot paths.

    Thread contract: each rule's counters are touched only by the thread(s)
    traversing its site (the engine thread for ``batcher.*``, the event loop
    for ``proto.*``); ``add`` from another thread is a GIL-atomic list
    append, so tests may arm new rules mid-run.
    """

    def __init__(self, rules: list[FaultRule] | None = None) -> None:
        self.rules: list[FaultRule] = list(rules or [])

    @classmethod
    def parse(cls, spec: str | None, strict: bool = False) -> "FaultPlane":
        """Build a plane from the comma-separated spec grammar above.
        ``None``/empty parses to an empty (never-firing) plane.
        ``strict=True`` additionally rejects sites absent from
        :data:`FAULT_SITES` — operator entry points (``dlt-serve
        --fault``) use it so a typo'd site fails loudly instead of
        parsing into a rule that never fires.  Tests exercising the
        grammar itself keep the default and may use synthetic sites."""
        rules = [
            _parse_rule(part)
            for part in (spec or "").split(",") if part.strip()
        ]
        if strict:
            unknown = sorted({r.site for r in rules} - set(FAULT_SITES))
            if unknown:
                raise ValueError(
                    f"unknown fault site(s) {unknown}; wired sites: "
                    f"{sorted(FAULT_SITES)}"
                )
        return cls(rules)

    def add(self, site: str, action: str, when: str = "1",
            arg: float | None = None, tag: str | None = None) -> FaultRule:
        """Arm one rule programmatically (``when`` uses the spec grammar:
        ``"3"``, ``"2+"``, ``"*"``).  Returns the rule for later
        inspection (``rule.fired``)."""
        text = f"{site}{'/' + tag if tag else ''}:{action}@{when}"
        if arg is not None:
            text += f":{arg}"
        rule = _parse_rule(text)
        self.rules.append(rule)
        return rule

    def fire(self, site: str, tag: str | None = None,
             defer_stall: bool = False) -> FaultRule | None:
        """Record a traversal of ``site`` and trigger the first due rule.

        ``raise`` rules raise :class:`InjectedFault`; ``stall`` rules sleep
        ``arg`` seconds here (blocking — they model a wedged device call).
        Every other action is returned as the rule for the call site to
        apply.  Returns ``None`` when nothing fired.

        ``defer_stall=True`` returns a due ``stall`` rule instead of
        sleeping — for sites traversed by an asyncio event loop (the
        fleet's ``replica.*`` ticks), where a blocking sleep would freeze
        every replica's probing and the router itself; the caller applies
        the stall semantics non-blockingly.
        """
        hit: FaultRule | None = None
        for rule in self.rules:
            if not rule.matches(site, tag):
                continue
            rule.hits += 1
            if hit is None and rule.due():
                hit = rule
        if hit is None:
            return None
        hit.fired += 1
        METRICS.inc("faults.fired")
        METRICS.inc(f"faults.fired.{hit.action}")
        log.warning("fault injected: %s (hit %d at %s%s)", hit.describe(),
                    hit.hits, site, f"/{tag}" if tag else "")
        if hit.action == "raise":
            raise InjectedFault(
                f"injected fault at {site}"
                f"{'/' + tag if tag else ''} (rule {hit.describe()})"
            )
        if hit.action == "stall" and not defer_stall:
            # graftlint: ignore[GL401](stall deliberately blocks the engine thread — it models a wedged device call for the watchdog)
            time.sleep(hit.arg or 0.0)
        return hit

    def describe(self) -> str:
        return ",".join(r.describe() for r in self.rules)
