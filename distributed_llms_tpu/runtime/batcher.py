"""Continuous batching: admit requests into an in-flight decode batch.

The reference (and round-2's engine) serve request *groups*: a batch enters
prefill together, decodes together, and the whole batch drains before the
next group starts — short requests wait for the longest one, and free batch
rows ride along empty.  Continuous batching (the scheduling model of modern
serving stacks) keeps a fixed set of batch SLOTS decoding at all times:
when a row finishes, a queued request is prefilled into that row between
decode chunks while the other rows keep generating.

Works single-device or on a GSPMD data/tensor-parallel mesh (VERDICT r3
next-step 5): pass ``parallel=`` (a parallel.api.ParallelModel with no
pipe/seq axes) and the shared KV cache shards over the mesh ('data' on the
batch axis, 'model' on KV heads) while the per-chunk scheduling state
(last_tok, valid, active, budget — a few hundred bytes) is constrained
replicated, then pulled back to HOST numpy mirrors between chunks.  On a
mesh spanning processes every host therefore feeds identical replicated
inputs to the same jit sequence and reads back identical mirrors — the
admission loop stays in lockstep with no cross-host control traffic at
all (pinned by the 2-process mixed-budget leg of
tests/cluster/test_multihost.py).  Pipelined / sequence-parallel meshes
keep their own decode schedules (wavefront, ring) — the batcher rejects
them loudly.

PAGED mode is mesh-native too (ROADMAP item 3): the page pool (and the
int8 QuantKVCache pool) shards its KV-head axis over 'model'
(parallel.specs.page_pool_specs — per-chip pool bytes divide by tp, so
per-chip row capacity multiplies by the mesh), the ragged/paged decode
kernels partition through their own custom_partitioning rules
(ops/decode_attn — each shard runs its local head slice; page tables and
lengths replicate on a pure-TP mesh), and every pool-carrying jit in this
module (admission splices, growth/swap scatter-gathers, KV-import
adoption, the decode carry) re-constrains its pool output so one
placement — and one compile key per bucket — serves the whole engine.
Host-facing semantics (digests, tiering, preemption, temp-0 bytes) are
identical to the single-device paged engine, pinned by
tests/runtime/test_mesh_paged.py.  KV heads must divide over 'model';
batch_slots must divide over 'data'.  Speculative batching stays
single-device contiguous.

TPU-native formulation (everything static-shaped, two compiled functions):

- ``admit_row``: prefill ONE request into batch slot ``i`` of the shared
  KV cache — the row prefills against a transient single-row cache (dense
  causal, flash-eligible) whose K/V then overwrite that batch row via one
  ``dynamic_update_slice`` along the batch axis.  Prompts pad to
  power-of-two buckets so admission compiles once per bucket, not per
  length.
- ``decode_chunk``: K decode steps for ALL slots at once, with PER-ROW
  cache write positions (rows admitted at different times sit at different
  depths).  The per-row single-token forward is ``jax.vmap``-ed over the
  batch axis: each row carries its own position, write slot, and validity
  mask; XLA turns the vmapped ``dynamic_update_slice`` into a scatter and
  re-batches the matmuls onto the MXU.  Inactive rows compute harmlessly
  into never-validated slots (no per-step cache select, which would copy
  the cache) and their outputs are masked to pad.

Invariant pinned by tests/runtime/test_batcher.py: at temperature 0 every
request's tokens are IDENTICAL to running runtime.generate.generate_tokens
on that request alone — continuous batching changes scheduling, never
results.

Scheduling POLICY lives in runtime/scheduler.py (admission order, prefill
chunk sizing against the token budget, victim selection, the pressure
ladder, the overlap sync-trigger list — declared hooks the run loop
delegates through ``self.sched``); this module keeps the MECHANISM.  The
default ``schedule="mixed"`` policy runs chunked-prefill bites INSIDE the
decode dispatch (:func:`mixed_step` — one fused token-budget program), so
resident decode rows never stall for a serialized prefill forward; the
host-RAM KV tier lives in runtime/kv_tier.py (re-exported here).
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import ModelConfig
from ..core.observability import METRICS, get_logger
from ..models import model as model_lib
from ..models.model import KVCache, QuantKVCache
from . import constrain as constrain_lib
from . import sampling
from . import scheduler as scheduler_lib
# Re-export: the host-RAM KV tier lives in kv_tier.py since round 16.
from .kv_tier import HostTier
from .scheduler import make_scheduler
from .shapes import bucket_length as _bucket

log = get_logger("batcher")

# Acceptance-EMA smoothing for the adaptive spec_k downshift: ~5 rounds of
# history — fast enough that a cold draft downshifts within one long row,
# slow enough that a single unlucky round doesn't collapse k.
_SPEC_EMA_ALPHA = 0.2


def _batch_axis(leaf_ndim: int) -> int:
    # KVCache leaves end in [..., B, S, KVH, HD]; batch is 4th from the right.
    return leaf_ndim - 4


def _fwd(pm):
    """The forward to trace: the mesh-parallel one when ``pm`` is set (a
    ParallelModel — hashable frozen dataclass, so jit caches per mesh), else
    the single-device model forward.  Both share the (params, cfg, tokens,
    ...) signature."""
    return model_lib.forward if pm is None else pm._forward_adapter


def _replicated(pm, *xs):
    """Constrain small scheduling state replicated on the mesh: every host
    of a multi-process mesh then mirrors identical values (np.asarray on a
    fully-replicated array is legal and equal everywhere), keeping the
    host-side admission loop in lockstep.  No-op single-device."""
    if pm is None:
        return xs if len(xs) > 1 else xs[0]
    from jax.sharding import NamedSharding, PartitionSpec as P

    s = NamedSharding(pm.mesh, P())
    out = tuple(jax.lax.with_sharding_constraint(x, s) for x in xs)
    return out if len(out) > 1 else out[0]


def _sample_first(logits, last_idx, rng, temperature, top_k, top_p,
                  temp_req=None, topp_req=None, topk_req=None,
                  mask_req=None):
    """Sample the admitted row's first token from the last real position's
    logits — the one sampling tail shared by every admission path.
    ``temp_req``/``topp_req``/``topk_req`` (traced scalars) override the
    static knobs for per-request sampling without a recompile per value.
    ``mask_req`` [V] is a constrained/biased request's start-state token
    mask (runtime/constrain.py): applied before the draw AND the greedy
    argmax, never to the logprob (the logprobs contract stays
    raw-distribution)."""
    next_logits = jnp.take_along_axis(
        logits, jnp.maximum(last_idx - 1, 0)[None, None, None], axis=1
    )[:, 0]
    src = next_logits if mask_req is None else next_logits + mask_req[None, :]
    if temp_req is None:
        tok = sampling.sample(rng, src, temperature, top_k, top_p)[0]
    else:
        tok = sampling.sample_rows(
            rng, src, jnp.reshape(temp_req, (1,)), top_k,
            jnp.reshape(topp_req, (1,)),
            top_k_rows=(None if topk_req is None
                        else jnp.reshape(topk_req, (1,))),
        )[0]
    # Chosen-token logprob under the RAW model distribution (the OpenAI
    # logprobs contract) — one [V] log-softmax, trivial next to the
    # prefill that produced the logits.
    lp = jax.nn.log_softmax(next_logits[0].astype(jnp.float32))[tok]
    return tok, lp


def _prefill_row(fwd, params, cfg, cache_dtype, s, prompt):
    """Dense causal prefill of one request into a transient single-row
    cache (flash-eligible: attn_mask=None) — shared by the contiguous and
    paged admissions.  ``fwd`` is _fwd(pm): the mesh-parallel forward on a
    mesh batcher, the plain model forward otherwise."""
    (tp,) = prompt.shape
    row_cache = model_lib.init_cache(cfg, 1, s, dtype=cache_dtype)
    positions = jnp.arange(tp, dtype=jnp.int32)[None, :]
    return fwd(
        params, cfg, prompt[None, :], positions=positions,
        cache=row_cache, cache_index=jnp.int32(0),
    )


def _prefill_row_with_prefix(fwd, params, cfg, prefix_k, prefix_v, prefix_len,
                             chunk):
    """Prefix-seeded prefill: only the request's suffix runs through the
    model (session-style continuation math) — shared by the contiguous and
    paged prefix admissions."""
    (tc,) = chunk.shape
    s = prefix_k.shape[-3]
    slots = jnp.arange(s, dtype=jnp.int32)
    row_cache = KVCache(k=prefix_k, v=prefix_v)
    positions = (prefix_len + jnp.arange(tc, dtype=jnp.int32))[None, :]
    from .session import continuation_mask

    prefix_valid = (slots < prefix_len)[None, :]  # [1, S]
    mask = continuation_mask(prefix_valid, prefix_len, tc, slots)  # [1,1,Tc,S]
    return fwd(
        params, cfg, chunk[None, :], positions=positions,
        cache=row_cache, cache_index=prefix_len, attn_mask=mask,
    )


def _finish_admission(
    cache, slot, row_cache, logits, last_idx, rng, temperature, top_k, top_p,
    total_len, temp_req=None, topp_req=None, topk_req=None, mask_req=None,
):
    """Shared admission tail (plain and prefix-cached paths): sample the
    first token from the last real position's logits, splice the prefilled
    row into the shared cache, report the row's valid slots."""
    tok, lp = _sample_first(logits, last_idx, rng, temperature, top_k, top_p,
                            temp_req, topp_req, topk_req, mask_req)
    ax = _batch_axis(cache.k.ndim)

    def splice(full, row):
        start = [0] * full.ndim
        start[ax] = slot
        return jax.lax.dynamic_update_slice(
            full, row.astype(full.dtype), tuple(start)
        )

    cache = KVCache(k=splice(cache.k, row_cache.k), v=splice(cache.v, row_cache.v))
    s = cache.k.shape[-3]
    row_valid = jnp.arange(s, dtype=jnp.int32) < total_len
    return cache, tok, row_valid, lp


@partial(
    jax.jit,
    static_argnames=("cfg", "temperature", "top_k", "top_p", "pm"),
    donate_argnames=("cache",),
)
def admit_row(
    params: Any,
    cfg: ModelConfig,
    cache: Any,  # shared KVCache, [L, B, S, KVH, HD] leaves
    slot: jax.Array,  # scalar int32 — batch row to fill
    prompt: jax.Array,  # [Tp] int32, right-padded (bucketed length)
    plen: jax.Array,  # scalar int32 true length
    rng: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    pm: Any = None,  # ParallelModel — GSPMD dp/tp mesh batching
    temp_req: jax.Array | None = None,  # traced per-request overrides
    topp_req: jax.Array | None = None,
    topk_req: jax.Array | None = None,
    mask_req: jax.Array | None = None,  # [V] constrained first-token mask
) -> tuple[Any, jax.Array, jax.Array, jax.Array]:
    """Prefill one request into batch row ``slot``.  Returns
    (cache', first_token, row_valid [S], first_token_logprob) —
    real_lens/budget bookkeeping is the caller's.  The transient row cache is deliberately NOT
    mesh-constrained: batch 1 can't shard over 'data'; XLA places it (TP
    still shards the matmuls via the weights)."""
    logits, row_cache = _prefill_row(
        _fwd(pm), params, cfg, cache.k.dtype, cache.k.shape[-3], prompt
    )
    cache, tok, row_valid, lp = _finish_admission(
        cache, slot, row_cache, logits, plen, rng, temperature, top_k, top_p,
        total_len=plen, temp_req=temp_req, topp_req=topp_req,
        topk_req=topk_req, mask_req=mask_req,
    )
    return (cache, *_replicated(pm, tok, row_valid, lp))


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def admit_row_kv(
    params: Any,
    cfg: ModelConfig,
    cache: Any,  # shared KVCache (the DRAFT's, in speculative mode)
    slot: jax.Array,  # scalar int32
    prompt: jax.Array,  # [Tp] int32 right-padded FULL prompt (prefix+suffix)
    plen: jax.Array,  # scalar int32 true length
) -> Any:
    """KV-only admission: prefill one row and splice it into the shared
    cache, sampling nothing.  Speculative batching uses it to seed the
    DRAFT model's cache for a newly admitted request (prefix caching only
    stores target KV, so the draft prefills the full prompt)."""
    del plen  # the transient prefill writes all Tp slots; masks gate reads
    _, row_cache = _prefill_row(
        model_lib.forward, params, cfg, cache.k.dtype, cache.k.shape[-3],
        prompt,
    )
    ax = _batch_axis(cache.k.ndim)

    def splice(full, row):
        start = [0] * full.ndim
        start[ax] = slot
        return jax.lax.dynamic_update_slice(
            full, row.astype(full.dtype), tuple(start)
        )

    return KVCache(k=splice(cache.k, row_cache.k),
                   v=splice(cache.v, row_cache.v))


@partial(
    jax.jit,
    static_argnames=("cfg", "draft_cfg", "k", "eos_id", "pad_id",
                     "temperature", "top_k", "top_p"),
    donate_argnames=("cache", "draft_cache"),
)
def spec_chunk(
    params: Any,
    cfg: ModelConfig,
    draft_params: Any,
    draft_cfg: ModelConfig,
    cache: Any,        # target shared KVCache
    draft_cache: Any,  # draft shared KVCache (same slot layout)
    last_tok: jax.Array,   # [B] int32
    real_lens: jax.Array,  # [B] int32
    valid: jax.Array,      # [B, S] bool
    active: jax.Array,     # [B] bool
    budget: jax.Array,     # [B] int32
    k: int,
    eos_id: int = -1,
    pad_id: int = 0,
    counts: jax.Array | None = None,  # [B, V] int32 output-token histogram
    pres_row: jax.Array | None = None,  # [B] traced presence penalties
    freq_row: jax.Array | None = None,  # [B] traced frequency penalties
    temperature: float = 0.0,  # 0 => greedy (bit-exact vs decode_chunk);
    #   > 0 => speculative SAMPLING (distribution-preserving, engine-wide
    #   warp — the same Leviathan/Chen rejection scheme as
    #   runtime/speculative.py, one round per call instead of a while_loop)
    top_k: int = 0,
    top_p: float = 1.0,
    rng: jax.Array | None = None,  # required when temperature > 0
    tables: jax.Array | None = None,  # [B, P] page table — the TARGET
    #   cache is a page-pool (KVCache or int8 QuantKVCache) and the
    #   verify window writes through it (the paged spec leg; the draft
    #   cache stays contiguous)
    k_row: jax.Array | None = None,  # [B] int32 adaptive per-row draft
    #   length (acceptance clamped at j < k_row; traced, so the whole
    #   spec_k ladder shares one compiled program)
) -> tuple:
    """ONE speculative round over the batch: draft k tokens per row
    against the draft cache, verify all of them in one (k+1)-token target
    forward, commit each row's accepted prefix + bonus/correction.
    temperature == 0: greedy — tokens bit-identical to decode_chunk's
    greedy output; acceptance only changes how many arrive per round.
    temperature > 0: rejection sampling — draft token d_j ~ q_j accepts
    iff u_j < p_j(d_j)/q_j(d_j), the first rejection draws from
    normalize(max(p - q, 0)), full acceptance draws the bonus from
    p_{k+1} (q zero-extended); the emitted sequence is an exact sample
    from the target's warped distribution, same theorem and residual
    construction as runtime/speculative.py's sampled loop (the RNG stream
    differs from decode_chunk's, so per-seed tokens differ while the
    distribution does not — pinned by the self-calibrated TV test in
    tests/runtime/test_spec_batcher.py).

    Returns (toks [B, k+1] pad-masked, m [B] committed counts, lps
    [B, k+1] chosen-token logprobs, cache', draft_cache', last_tok',
    real_lens', valid', active', budget', counts').  ``lps[b, j]`` is the
    TARGET's raw-distribution log-softmax of the committed token
    ``toks[b, j]`` — the verify forward already computes full logits for
    every position, so serving logprobs costs one log-softmax + gather per
    round.

    Presence/frequency penalties (``counts``+``pres_row``+``freq_row``)
    stay bit-exact vs the penalized plain batcher: verify position j's
    context is [last_tok, d_1..d_j], so its penalty histogram is the base
    counts plus the one-hots of d_1..d_j — and within the accepted lead
    (the only region where greedy[j] can commit) those drafts ARE the
    committed tokens, making the adjusted argmax identical to the
    sequential penalized decode's.  Draft steps penalize with the same
    evolving histogram so acceptance tracks the penalized target.
    Logprobs stay RAW-distribution (pre-penalty), matching decode_chunk.

    Layout: contiguous (slot == position) exactly like decode_chunk; the
    rollback/backfill arguments mirror runtime/speculative.py with the
    frontier convention shifted to the batcher's (a token's KV is written
    by the forward that consumes it, at slot == its position).

    PAGED leg (``tables`` set — the spec x paged tentpole): the TARGET
    cache is the shared page pool and the k-token draft/verify window
    writes THROUGH the page tables (models.model._paged_window_attention
    scatters the k+1 tokens' KV at slots real_lens..real_lens+k and each
    verify query reads its row's prefix through per-offset lengths).
    What the contiguous leg does with the ``+spec_k+1`` headroom slots,
    the paged leg does with per-row SCRATCH-TAIL pages: the growth loop
    provisions pages through slot real_lens+spec_k before every round,
    and rejection rollback is the same pos/length clamp ``commit_clamp``
    applies today — ``real_lens`` only advances by the committed count,
    so the junk KV past the frontier is never read (the kernel's prefix
    contract) and the next round overwrites it.  The small quantized
    self-draft cache stays contiguous; ``valid`` gates only ITS masks
    here.  Temp-0 bytes are identical to the contiguous spec engine and
    to the non-speculative paged engine (tests/runtime/test_spec_paged).

    ``k_row`` (both legs) is the budget-aware adaptive downshift: a
    per-row TRACED draft-length clamp — acceptance stops at j < k_row,
    and the forced stop at j == k_row emits the target's own token for
    that position (greedy: greedy[j]; sampled: a draw from p_j with the
    draft distribution zero-extended past the clamp, which is exactly a
    fresh target sample), so the emitted stream is unchanged at ANY
    clamp — only arrival granularity shrinks, freeing verify-token
    budget for mixed prefill bites.  One compiled program serves the
    whole spec_k ladder (graftcheck GC4 batcher.spec_chunk_paged).

    Chaining contract: like decode_chunk, every returned carry leaf
    (cache', draft_cache', last_tok', real_lens', valid', active',
    budget', counts') is a legal input for the next round — the
    dispatch-ahead engine loop chains speculative rounds device-resident
    exactly as it chains plain decode chunks (both caches are donated;
    the carry vectors are not)."""
    paged = tables is not None
    # Contiguous: draft and target share one slot layout (equal widths),
    # so using the draft's width everywhere leaves the program unchanged;
    # paged: the masks below gate only the contiguous DRAFT cache.
    s = draft_cache.k.shape[-3]
    slots = jnp.arange(s, dtype=jnp.int32)
    penalized = counts is not None
    sampled = temperature > 0.0
    if sampled and rng is None:
        raise ValueError("spec_chunk with temperature > 0 requires rng")
    if sampled:
        rng, kd, ku, kc = jax.random.split(rng, 4)
    else:
        kd = jax.random.key(0)  # uniform scan shape; never consumed

    def _pen(logits, cnt):  # [B(, T), V] logits, [B(, T), V] int32 counts
        if not penalized:
            return logits
        extra = (1,) * (logits.ndim - 2)
        f = freq_row.reshape(-1, *extra, 1)
        p = pres_row.reshape(-1, *extra, 1)
        return (logits - f * cnt.astype(logits.dtype)
                - p * (cnt > 0).astype(logits.dtype))

    def row_mask(hi):  # [B] inclusive frontier -> [B, 1, 1, S]
        own = jnp.logical_and(slots[None, :] >= real_lens[:, None],
                              slots[None, :] <= hi[:, None])
        return jnp.logical_or(valid, own)[:, None, None, :]

    # --- draft: k single-token steps against the draft cache.  Penalized
    # mode carries the evolving histogram (base + drafts so far) so the
    # draft tracks the penalized target; sampled mode also emits each
    # step's full post-warp distribution q_j (the rejection test needs
    # q_j(d_j) and the residual the whole vector).
    def draft_step(dc, inputs):
        draft_cache, cur, cnt = dc
        j, kj = inputs
        idx = real_lens + j
        logits, draft_cache = model_lib.forward(
            draft_params, draft_cfg, cur[:, None], positions=idx[:, None],
            cache=draft_cache, cache_index=idx, attn_mask=row_mask(idx),
        )
        step_logits = _pen(logits[:, 0], cnt)
        if sampled:
            warped = sampling.warp_logits(
                step_logits, temperature, top_k, top_p
            )
            nxt = jax.random.categorical(kj, warped, axis=-1).astype(
                jnp.int32
            )
            out = (nxt, jax.nn.softmax(warped, axis=-1))
        else:
            nxt = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
            out = nxt
        if penalized:
            cnt = cnt.at[jnp.arange(cnt.shape[0]), nxt].add(1)
        return (draft_cache, nxt, cnt), out

    dcnt0 = counts if penalized else jnp.zeros((), jnp.int32)
    (draft_cache, _, _), draft_ys = jax.lax.scan(
        draft_step, (draft_cache, last_tok, dcnt0),
        (jnp.arange(k, dtype=jnp.int32), jax.random.split(kd, k)),
    )
    if sampled:
        drafts, qs = draft_ys
        qs = jnp.moveaxis(qs, 0, 1)  # [B, k, V]
    else:
        drafts, qs = draft_ys, None
    drafts = drafts.T  # [B, k]

    # --- verify: one (k+1)-token target forward.  Paged: the window
    # writes through the page tables and reads per-offset prefixes (no
    # mask — the kernel's length contract is the causality); contiguous:
    # the explicit row masks, exactly as before.
    vtoks = jnp.concatenate([last_tok[:, None], drafts], axis=1)
    voff = jnp.arange(k + 1, dtype=jnp.int32)
    if paged:
        vlogits, cache = model_lib.forward(
            params, cfg, vtoks,
            positions=real_lens[:, None] + voff[None, :],
            cache=cache, cache_index=real_lens, kv_tables=tables,
        )
    else:
        vmask = jnp.concatenate(
            [row_mask(real_lens + q) for q in range(k + 1)], axis=2
        )  # [B, 1, k+1, S]
        vlogits, cache = model_lib.forward(
            params, cfg, vtoks,
            positions=real_lens[:, None] + voff[None, :],
            cache=cache, cache_index=real_lens, attn_mask=vmask,
        )
    if penalized:
        # counts_j = base + one-hots of d_1..d_j (position j consumed
        # [last_tok, d_1..d_j]; last_tok is already in the base histogram).
        v = vlogits.shape[-1]
        oneh = jax.nn.one_hot(drafts, v, dtype=jnp.int32)       # [B, k, V]
        c = jnp.concatenate(
            [jnp.zeros_like(oneh[:, :1]), jnp.cumsum(oneh, axis=1)], axis=1
        )                                                       # [B, k+1, V]
        pen_vlogits = _pen(vlogits, counts[:, None, :] + c)
    else:
        pen_vlogits = vlogits
    # Shared accept/commit bookkeeping (runtime/speculative.py — the ONE
    # definition; only the frontier convention differs between the loops).
    from .speculative import backfill_coords, commit_clamp, greedy_accept_commit

    j_ar = jnp.arange(k + 1, dtype=jnp.int32)
    b = drafts.shape[0]
    if sampled:
        # Rejection sampling over the (penalized, warped) target vs draft
        # distributions — identical math to speculative_generate_tokens'
        # sampled branch; p and q share the same penalty basis per
        # position so the theorem holds against the penalized target.
        ps = jax.nn.softmax(
            sampling.warp_logits(pen_vlogits, temperature, top_k, top_p),
            axis=-1,
        )  # [B, k+1, V]
        p_at = jnp.take_along_axis(
            ps[:, :k], drafts[..., None], axis=-1
        )[..., 0]                                        # [B, k]
        q_at = jnp.take_along_axis(qs, drafts[..., None], axis=-1)[..., 0]
        u = jax.random.uniform(ku, (b, k))
        accept = u * jnp.maximum(q_at, 1e-20) < p_at
        if k_row is not None:
            # Adaptive downshift, sampled leg: force a stop at j == k_row
            # and zero the draft distribution past it — the "residual" at
            # a forced stop is then max(p - 0, 0) = p itself, i.e. a
            # fresh sample from the target (the draft was never consulted
            # there), so the theorem's output distribution is preserved
            # at any per-row clamp.
            accept = jnp.logical_and(
                accept, jnp.arange(k, dtype=jnp.int32)[None, :]
                < k_row[:, None],
            )
        lead = jnp.cumprod(accept.astype(jnp.int32), axis=1)
        a = jnp.sum(lead, axis=1)                        # [B] in 0..k
        # Unified residual: zero-extend q so position k's "residual" is
        # p_{k+1} itself (the bonus draw).
        q_ext = jnp.concatenate([qs, jnp.zeros_like(ps[:, :1])], axis=1)
        if k_row is not None:
            q_ext = q_ext * (
                j_ar[None, :] < k_row[:, None]
            ).astype(q_ext.dtype)[..., None]
        p_a = jnp.take_along_axis(ps, a[:, None, None], axis=1)[:, 0]
        q_a = jnp.take_along_axis(q_ext, a[:, None, None], axis=1)[:, 0]
        resid = jnp.maximum(p_a - q_a, 0.0)
        norm = jnp.sum(resid, axis=-1, keepdims=True)
        # p == q on the whole support leaves an empty residual; fall back
        # to p (any sample from it is valid there).
        resid = jnp.where(norm > 1e-9, resid / jnp.maximum(norm, 1e-9), p_a)
        corr = jax.random.categorical(
            kc,
            jnp.where(resid > 0, jnp.log(jnp.maximum(resid, 1e-30)),
                      -jnp.inf),
            axis=-1,
        ).astype(jnp.int32)                              # [B]
        cand = jnp.where(
            j_ar[None, :] < a[:, None],
            jnp.concatenate([drafts, drafts[:, -1:]], axis=1),
            corr[:, None],
        )                                                # [B, k+1]
        m, has_eos = commit_clamp(cand, a, active, budget, eos_id, k)
    else:
        greedy = jnp.argmax(pen_vlogits, axis=-1).astype(jnp.int32)
        cand, m, has_eos, _ = greedy_accept_commit(
            drafts, greedy, active, budget, eos_id, k, k_row=k_row
        )
    # Chosen-token logprobs for the committed tokens (OpenAI logprobs
    # contract): vlogits[:, j] predicts the token committed at offset j.
    # Greedy: accepted drafts equal greedy[j] by agreement and the bonus
    # at j == a IS greedy[j].  Sampled: accepted drafts are the sampled
    # d_j and j == a holds the residual/bonus draw — either way the
    # committed token's raw-distribution log-softmax under the TARGET at
    # position j is the contract (decode_chunk reports the same basis).
    lps = jnp.take_along_axis(
        jax.nn.log_softmax(vlogits.astype(jnp.float32), axis=-1),
        cand[..., None], axis=-1,
    )[..., 0]  # [B, k+1]

    # Target KVs at slots real_lens .. real_lens+m-1 hold
    # [last_tok, c_1..c_{m-1}] — all committed; slot real_lens+m (holding
    # d_m's KV when the round mismatched there) stays invalid and is
    # overwritten when the next round consumes the true c_m.
    committed = jnp.logical_and(
        slots[None, :] >= real_lens[:, None],
        slots[None, :] <= (real_lens + m - 1)[:, None],
    )
    valid = valid | (committed & (m > 0)[:, None])

    toks = jnp.where(j_ar[None, :] < m[:, None], cand, jnp.int32(pad_id))
    if penalized:
        # Histogram update: every committed token (EOS included, matching
        # decode_chunk's accounting).
        commit_oneh = jax.nn.one_hot(
            cand, counts.shape[1], dtype=jnp.int32
        ) * (j_ar[None, :] < m[:, None])[..., None]
        counts = counts + jnp.sum(commit_oneh, axis=1)
    new_last = jnp.take_along_axis(
        cand, jnp.maximum(m - 1, 0)[:, None], axis=1
    )[:, 0]
    last_tok = jnp.where(m > 0, new_last, last_tok)
    real_lens = real_lens + m
    budget = budget - m
    active = active & ~has_eos & (budget > 0)

    # Draft backfill: only a fully accepted round (m == k+1) leaves the
    # draft missing c_k's KV one slot below the new frontier
    # (speculative.backfill_coords has the full rationale).
    bf_idx, bf_tok = backfill_coords(cand, m, frontier=real_lens)
    bf_own = slots[None, :] == bf_idx[:, None]
    bf_mask = jnp.logical_or(valid, bf_own)[:, None, None, :]
    _, draft_cache = model_lib.forward(
        draft_params, draft_cfg, bf_tok[:, None], positions=bf_idx[:, None],
        cache=draft_cache, cache_index=bf_idx, attn_mask=bf_mask,
    )
    return (toks, m, lps, cache, draft_cache, last_tok, real_lens, valid,
            active, budget, counts if penalized else None)


@partial(
    jax.jit,
    static_argnames=("cfg", "temperature", "top_k", "top_p", "pm"),
    donate_argnames=("cache",),
)
def admit_row_with_prefix(
    params: Any,
    cfg: ModelConfig,
    cache: Any,  # shared KVCache
    slot: jax.Array,  # scalar int32
    prefix_k: jax.Array,  # [..., 1, S, KVH, HD] — a registered prefix's KV
    prefix_v: jax.Array,
    prefix_len: jax.Array,  # scalar int32
    chunk: jax.Array,  # [Tc] int32 — the request's suffix, right-padded
    clen: jax.Array,  # scalar int32 true suffix length
    rng: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    pm: Any = None,  # ParallelModel — GSPMD dp/tp mesh batching
    temp_req: jax.Array | None = None,  # traced per-request overrides
    topp_req: jax.Array | None = None,
    topk_req: jax.Array | None = None,
    mask_req: jax.Array | None = None,  # [V] constrained first-token mask
) -> tuple[Any, jax.Array, jax.Array, jax.Array]:
    """Prefix-cached admission: the shared prefix's KV (computed ONCE by
    ``register_prefix``) seeds the row; only the request's suffix prefills —
    session-style continuation math (runtime/session.py) for one row.
    Returns (cache', first_token, row_valid, first_token_logprob)."""
    logits, row_cache = _prefill_row_with_prefix(
        _fwd(pm), params, cfg, prefix_k, prefix_v, prefix_len, chunk
    )
    cache, tok, row_valid, lp = _finish_admission(
        cache, slot, row_cache, logits, clen, rng, temperature, top_k, top_p,
        total_len=prefix_len + clen, temp_req=temp_req, topp_req=topp_req,
        topk_req=topk_req, mask_req=mask_req,
    )
    return (cache, *_replicated(pm, tok, row_valid, lp))


@partial(jax.jit, static_argnames=("cfg", "pm"),
         donate_argnames=("row_k", "row_v"))
def prefill_chunk_step(
    params: Any,
    cfg: ModelConfig,
    row_k: jax.Array,   # [..., 1, S, KVH, HD] transient single-row KV
    row_v: jax.Array,
    done: jax.Array,    # scalar int32 — prompt tokens already in the row
    chunk: jax.Array,   # [Tc] int32 — next chunk, right-padded (bucketed)
    clen: jax.Array,    # scalar int32 true chunk length
    pm: Any = None,     # ParallelModel — GSPMD dp/tp mesh batching
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One chunk of a CHUNKED prefill: consume ``chunk`` into the transient
    single-row cache at offset ``done`` — the same continuation math as
    prefix-cached admission (the "prefix" is the row's own partial prompt),
    so the accumulated attention is bit-identical to a monolithic prefill.
    row_k/row_v are DONATED (the update happens in place instead of
    copying the full row cache every chunk) — _start_chunked hands this
    step exclusively-owned buffers, copying a registered prefix's KV once
    up front rather than aliasing it.
    Returns (row_k', row_v', last_logits [1, V] at the chunk's last real
    position — the sampling source once the prompt completes; replicated
    on a mesh batcher so the finishing sample runs lockstep)."""
    return _prefill_leg(params, cfg, row_k, row_v, done, chunk, clen, pm)


def _prefill_leg(params, cfg, row_k, row_v, done, chunk, clen, pm):
    """The one prefill-bite definition, shared VERBATIM by the
    serialized :func:`prefill_chunk_step` and the fused
    :func:`mixed_step` — like `_decode_steps` for the decode leg, a
    single definition is what keeps the two schedules trivially
    byte-identical."""
    logits, row_cache = _prefill_row_with_prefix(
        _fwd(pm), params, cfg, row_k, row_v, done, chunk
    )
    last = jnp.take_along_axis(
        logits, jnp.maximum(clen - 1, 0)[None, None, None], axis=1
    )[:, 0]  # [1, V]
    return row_cache.k, row_cache.v, _replicated(pm, last)


@partial(
    jax.jit,
    static_argnames=("cfg", "temperature", "top_k", "top_p", "pm"),
    donate_argnames=("cache",),
)
def finish_chunked_admission(
    cfg: ModelConfig,
    cache: Any,
    slot: jax.Array,
    row_k: jax.Array,
    row_v: jax.Array,
    last_logits: jax.Array,  # [1, V] from the final prefill_chunk_step
    total_len: jax.Array,    # scalar int32 — full prompt length
    rng: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    pm: Any = None,          # ParallelModel — GSPMD dp/tp mesh batching
    temp_req: jax.Array | None = None,
    topp_req: jax.Array | None = None,
    topk_req: jax.Array | None = None,
    mask_req: jax.Array | None = None,  # [V] constrained first-token mask
) -> tuple[Any, jax.Array, jax.Array, jax.Array]:
    """Tail of a chunked admission: sample the first token from the final
    chunk's last-position logits and splice the fully-prefilled transient
    row into the shared cache — the same _finish_admission used by the
    monolithic paths, so results are bit-identical."""
    cache, tok, row_valid, lp = _finish_admission(
        cache, slot, KVCache(k=row_k, v=row_v), last_logits[:, None, :],
        jnp.int32(1), rng, temperature, top_k, top_p, total_len,
        temp_req=temp_req, topp_req=topp_req, topk_req=topk_req,
        mask_req=mask_req,
    )
    return (cache, *_replicated(pm, tok, row_valid, lp))


@partial(
    jax.jit,
    static_argnames=("temperature", "top_k", "top_p", "pm"),
    donate_argnames=("cache",),  # row_k/row_v feed a gather-reshape XLA
    #   cannot alias — donating them only triggers the unused-donation
    #   warning every admission.
)
def finish_chunked_admission_paged(
    cache: Any,              # page-pool KVCache
    page_list: jax.Array,    # [P] int32, scratch-padded
    row_k: jax.Array,        # [L, 1, P*BLK, KVH, HD] fully-prefilled row
    row_v: jax.Array,
    last_logits: jax.Array,  # [1, V] from the final prefill_chunk_step
    rng: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    pm: Any = None,          # ParallelModel — GSPMD dp/tp mesh batching
    temp_req: jax.Array | None = None,
    topp_req: jax.Array | None = None,
    topk_req: jax.Array | None = None,
    mask_req: jax.Array | None = None,  # [V] constrained first-token mask
) -> tuple[Any, jax.Array, jax.Array]:
    """Tail of a chunked admission in PAGED mode: sample the first token
    from the final chunk's logits and scatter the transient row's pages
    into the pool through ``page_list`` — the same _paged_splice every
    monolithic paged admission uses, so results are bit-identical.  Pages
    are allocated only HERE (on-demand: the whole prefill ran pageless),
    so a long prompt never pins pool pages while it chunks in."""
    return _paged_splice(
        cache, page_list, KVCache(k=row_k, v=row_v),
        last_logits[:, None, :], jnp.int32(1), rng, temperature, top_k,
        top_p, temp_req, topp_req, topk_req, mask_req, pm=pm,
    )


@partial(jax.jit, static_argnames=("pm",))
def _import_pages(cache: Any, page_list: jax.Array, k_pages: jax.Array,
                  v_pages: jax.Array, pm: Any = None) -> Any:
    """Scatter HANDED-OFF KV pages into the pool (disaggregated serving:
    a prefill-role engine shipped a finished row's pages over
    cluster/kv_transfer.py and this decode-role engine adopts them).
    ``k_pages``/``v_pages`` are [L, P, BLK, KVH, HD] page stacks in pool
    layout; ``page_list`` [P] names the freshly allocated destination
    pages.  An int8 pool re-quantizes the full-width payload on the way in
    — byte-stable when the payload was itself dequantized from int8 pages
    (kv_quantize's exact round-trip property), which is how a kv-bits-8
    fleet ships pages without a second lossy step.  The cache is NOT
    donated: import is a rare, off-hot-path event and the caller reuses
    the returned pool exactly like the admission splices do."""
    if isinstance(cache, QuantKVCache):
        from ..checkpoint.quantize import kv_quantize

        kq, ks = kv_quantize(k_pages)
        vq, vs = kv_quantize(v_pages)
        return _pool_constrain(pm, QuantKVCache(
            k=cache.k.at[:, page_list].set(kq),
            v=cache.v.at[:, page_list].set(vq),
            k_scale=cache.k_scale.at[:, page_list].set(ks),
            v_scale=cache.v_scale.at[:, page_list].set(vs),
            row_dtype=cache.row_dtype,
        ))
    return _pool_constrain(pm, KVCache(
        k=cache.k.at[:, page_list].set(k_pages.astype(cache.k.dtype)),
        v=cache.v.at[:, page_list].set(v_pages.astype(cache.v.dtype)),
    ))


@jax.jit
def _export_pages_raw(cache: Any, page_list: jax.Array) -> tuple:
    """Gather pages VERBATIM in pool layout and pool dtype — (k, v) page
    stacks, plus the scale stacks on an int8 pool.  This is the host-tier
    parcel format (swap-preemption, prefix-cache spill): re-importing the
    exact bytes via :func:`_import_pages_raw` restores the pool state
    bit-for-bit, which is what makes a swap-restored row's stream
    byte-exact against its never-preempted run at EITHER kv width."""
    if isinstance(cache, QuantKVCache):
        return (cache.k[:, page_list], cache.v[:, page_list],
                cache.k_scale[:, page_list], cache.v_scale[:, page_list])
    return (cache.k[:, page_list], cache.v[:, page_list])


@partial(jax.jit, static_argnames=("pm",))
def _import_pages_raw(cache: Any, page_list: jax.Array, k_pages: jax.Array,
                      v_pages: jax.Array, k_scale: jax.Array | None = None,
                      v_scale: jax.Array | None = None,
                      pm: Any = None) -> Any:
    """Scatter a raw host-tier parcel (``_export_pages_raw`` layout) back
    into freshly allocated pool pages, verbatim — no quantize/dequantize
    hop, so restore is exact by construction."""
    if isinstance(cache, QuantKVCache):
        return _pool_constrain(pm, QuantKVCache(
            k=cache.k.at[:, page_list].set(k_pages),
            v=cache.v.at[:, page_list].set(v_pages),
            k_scale=cache.k_scale.at[:, page_list].set(k_scale),
            v_scale=cache.v_scale.at[:, page_list].set(v_scale),
            row_dtype=cache.row_dtype,
        ))
    return _pool_constrain(pm, KVCache(
        k=cache.k.at[:, page_list].set(k_pages),
        v=cache.v.at[:, page_list].set(v_pages)))


@jax.jit
def _gather_row_pages(cache: Any, read_list: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Gather a row's pages out of the pool into a transient contiguous
    row cache ([L, 1, P*BLK, KVH, HD] k/v pair) — the chunked-prefill
    analogue of admit_row_auto_paged's in-program gather.  A cache-hit
    chunked admission seeds its transient row from the shared pages ONCE
    (the "prefix" is then already resident, exactly as if those chunks had
    run), and only the un-cached suffix chunks through the model.  The
    outputs are fresh buffers, so every later prefill_chunk_step may
    donate them."""
    l, _, blk, kvh, hd = cache.k.shape
    p = read_list.shape[0]

    if isinstance(cache, QuantKVCache):
        # Int8 pool: dequantize the gathered pages to the declared
        # full-width dtype — transient rows always run full-width; only
        # POOL storage is quantized.
        from ..checkpoint.quantize import kv_dequantize

        dt = jnp.dtype(cache.row_dtype)

        def gather_q(pool, scale):
            full = kv_dequantize(pool[:, read_list], scale[:, read_list], dt)
            return full.reshape(l, 1, p * blk, kvh, hd)

        return (gather_q(cache.k, cache.k_scale),
                gather_q(cache.v, cache.v_scale))

    def gather(pool):
        return pool[:, read_list].reshape(l, 1, p * blk, kvh, hd)

    return gather(cache.k), gather(cache.v)


def _pool_constrain(pm, cache):
    """Pin a page pool's leaves to their mesh sharding — KV heads over
    'model' (parallel.specs.page_pool_specs), the layout every paged jit
    in this module produces and consumes on a mesh batcher.  Applied to
    every program output that carries the pool (splice, decode chunk,
    import scatters) so XLA can never hand back a differently-placed pool
    and force a resharding copy (or a fresh compile key) on the next
    call.  No-op single-device."""
    if pm is None:
        return cache
    from jax.sharding import NamedSharding

    from ..parallel.specs import page_pool_specs
    quant = isinstance(cache, QuantKVCache)
    specs = page_pool_specs(
        pm.cfg, pm.mesh, kv_bits=8 if quant else 16,
        row_dtype=cache.row_dtype if quant else None,
    )
    return jax.tree.map(
        lambda x, s: jax.lax.with_sharding_constraint(
            x, NamedSharding(pm.mesh, s)
        ),
        cache, specs,
    )


def _paged_pool(cfg: ModelConfig, num_pages: int, page_size: int, dtype=None,
                kv_bits: int = 16):
    """KV page pools [L, NB, BLK, KVH, HD] (distinct k/v buffers — the
    chunk fns donate the cache).  ``kv_bits=8`` builds an int8
    :class:`~..models.model.QuantKVCache` pool (data int8 + one f32 absmax
    scale per head-dim vector) at roughly half the bytes per token; the
    full-width dtype survives as ``row_dtype`` so gathers/transient rows
    restore to it."""
    l, kvh, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_
    dt = jnp.dtype(dtype) if dtype else jnp.dtype(cfg.dtype)
    shape = (l, num_pages, page_size, kvh, hd)
    if kv_bits == 8:
        sshape = (l, num_pages, page_size, kvh)
        return QuantKVCache(
            k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.ones(sshape, jnp.float32),
            v_scale=jnp.ones(sshape, jnp.float32),
            row_dtype=dt.name,
        )
    return KVCache(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt))


def _row_dtype_of(cache) -> Any:
    """Dtype transient single-row caches (and pool gathers) use: the
    pool's own dtype, or the declared full-width dtype of an int8 pool.
    Safe inside jit — the pytree TYPE of ``cache`` is static."""
    if isinstance(cache, QuantKVCache):
        return jnp.dtype(cache.row_dtype)
    return cache.k.dtype


def pool_page_bytes(cfg: ModelConfig, page_size: int, kv_bits: int = 16,
                    dtype=None) -> int:
    """Bytes one pool page costs (k + v + scales) — the denominator of the
    capacity-per-byte comparison bench.py's kv-tiering row stamps."""
    l, kvh, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_
    elems = l * page_size * kvh * hd
    if kv_bits == 8:
        return 2 * (elems + l * page_size * kvh * 4)
    dt = jnp.dtype(dtype) if dtype else jnp.dtype(cfg.dtype)
    return 2 * elems * dt.itemsize


def _paged_splice(cache, page_list, row_cache, logits, last_idx, rng,
                  temperature, top_k, top_p, temp_req=None, topp_req=None,
                  topk_req=None, mask_req=None, pm=None):
    """Admission tail for the paged pool: sample the first token, then
    scatter the contiguous transient row cache into the row's pages.
    ``page_list`` [P] is padded with the reserved scratch page 0 past the
    allocation, so the fixed-shape scatter stays compiled once — the extra
    writes land in the scratch page, whose contents no LIVE row ever reads
    (freed rows' clamped decode reads do touch it, but their outputs are
    masked to pad).  Prefix-cache-hit admissions also route their CACHED
    positions to the scratch page: the shared pages already hold exactly
    that KV and must never be rewritten while other rows read them.
    On a mesh batcher (``pm``) the pool result is re-constrained to its
    sharding and the sampled token/logprob replicate (lockstep mirrors)."""
    tok, lp = _sample_first(logits, last_idx, rng, temperature, top_k, top_p,
                            temp_req, topp_req, topk_req, mask_req)
    p = page_list.shape[0]
    blk = cache.k.shape[2]

    if isinstance(cache, QuantKVCache):
        # Quantize ONCE at the write: each page's head-dim vectors get
        # int8 data + one f32 absmax scale (checkpoint.quantize
        # machinery); pool storage never sees the full-width row again.
        from ..checkpoint.quantize import kv_quantize

        def qsplice(pool, spool, row):
            l, _, _, kvh, hd = row.shape
            pages = row[:, 0].reshape(l, p, blk, kvh, hd)
            data, scale = kv_quantize(pages)
            return (pool.at[:, page_list].set(data),
                    spool.at[:, page_list].set(scale))

        k, sk = qsplice(cache.k, cache.k_scale, row_cache.k)
        v, sv = qsplice(cache.v, cache.v_scale, row_cache.v)
        cache = QuantKVCache(k=k, v=v, k_scale=sk, v_scale=sv,
                             row_dtype=cache.row_dtype)
        return (_pool_constrain(pm, cache), *_replicated(pm, tok, lp))

    def splice(pool, row):  # row: [L, 1, P*BLK, KVH, HD]
        l, _, _, kvh, hd = row.shape
        pages = row[:, 0].reshape(l, p, blk, kvh, hd).astype(pool.dtype)
        return pool.at[:, page_list].set(pages)

    cache = KVCache(
        k=splice(cache.k, row_cache.k), v=splice(cache.v, row_cache.v)
    )
    return (_pool_constrain(pm, cache), *_replicated(pm, tok, lp))


@partial(
    jax.jit,
    static_argnames=("cfg", "temperature", "top_k", "top_p", "pm"),
    donate_argnames=("cache",),
)
def admit_row_paged(
    params: Any,
    cfg: ModelConfig,
    cache: Any,  # page-pool KVCache, [L, NB, BLK, KVH, HD] leaves
    page_list: jax.Array,  # [P] int32 — the row's pages, scratch-padded
    prompt: jax.Array,  # [Tp] int32, right-padded (bucketed)
    plen: jax.Array,  # scalar int32 true length
    rng: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    pm: Any = None,  # ParallelModel — GSPMD dp/tp mesh batching
    temp_req: jax.Array | None = None,  # traced per-request overrides
    topp_req: jax.Array | None = None,
    topk_req: jax.Array | None = None,
    mask_req: jax.Array | None = None,  # [V] constrained first-token mask
) -> tuple[Any, jax.Array, jax.Array]:
    """Paged admission: dense causal prefill on a transient contiguous row
    cache, then scatter its pages into the pool.
    Returns (cache', tok, logprob)."""
    logits, row_cache = _prefill_row(
        _fwd(pm), params, cfg, _row_dtype_of(cache),
        page_list.shape[0] * cache.k.shape[2], prompt,
    )
    return _paged_splice(
        cache, page_list, row_cache, logits, plen, rng, temperature, top_k,
        top_p, temp_req, topp_req, topk_req, mask_req, pm=pm,
    )


@partial(
    jax.jit,
    static_argnames=("cfg", "temperature", "top_k", "top_p", "pm"),
    donate_argnames=("cache",),
)
def admit_row_with_prefix_paged(
    params: Any,
    cfg: ModelConfig,
    cache: Any,  # page-pool KVCache
    page_list: jax.Array,  # [P] int32, scratch-padded
    prefix_k: jax.Array,  # [L, 1, S, KVH, HD] contiguous prefix KV
    prefix_v: jax.Array,
    prefix_len: jax.Array,  # scalar int32
    chunk: jax.Array,  # [Tc] int32 suffix, right-padded
    clen: jax.Array,  # scalar int32
    rng: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    pm: Any = None,  # ParallelModel — GSPMD dp/tp mesh batching
    temp_req: jax.Array | None = None,  # traced per-request overrides
    topp_req: jax.Array | None = None,
    topk_req: jax.Array | None = None,
    mask_req: jax.Array | None = None,  # [V] constrained first-token mask
) -> tuple[Any, jax.Array, jax.Array]:
    """Prefix-cached paged admission: the prefix KV seeds the transient row
    cache, only the suffix prefills, then the pages scatter into the pool.
    Returns (cache', tok, logprob)."""
    logits, row_cache = _prefill_row_with_prefix(
        _fwd(pm), params, cfg, prefix_k, prefix_v, prefix_len, chunk
    )
    return _paged_splice(
        cache, page_list, row_cache, logits, clen, rng, temperature, top_k,
        top_p, temp_req, topp_req, topk_req, mask_req, pm=pm,
    )


@partial(
    jax.jit,
    static_argnames=("cfg", "temperature", "top_k", "top_p", "pm"),
    donate_argnames=("cache",),
)
def admit_row_auto_paged(
    params: Any,
    cfg: ModelConfig,
    cache: Any,  # page-pool KVCache, [L, NB, BLK, KVH, HD] leaves
    read_list: jax.Array,   # [P] int32 — the row's FULL page table (cached
    #   run first, then freshly allocated pages, scratch-padded)
    write_list: jax.Array,  # [P] int32 — same, but cached positions routed
    #   to the scratch page 0 (shared pages are read-only)
    prefix_len: jax.Array,  # scalar int32 — tokens covered by cached pages
    chunk: jax.Array,  # [Tc] int32 — the un-cached suffix, right-padded
    clen: jax.Array,  # scalar int32 true suffix length
    rng: jax.Array,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    pm: Any = None,  # ParallelModel — GSPMD dp/tp mesh batching
    temp_req: jax.Array | None = None,  # traced per-request overrides
    topp_req: jax.Array | None = None,
    topk_req: jax.Array | None = None,
    mask_req: jax.Array | None = None,  # [V] constrained first-token mask
) -> tuple[Any, jax.Array, jax.Array]:
    """AUTOMATIC prefix-cache admission: the row's cached prefix KV is
    gathered out of its own (shared, refcounted) pool pages into the
    transient contiguous row cache, only the un-cached suffix runs through
    the model (the same continuation math as the named-prefix path), and
    the result scatters back through ``write_list`` — cached positions land
    in the scratch page, so a shared page is never rewritten.  The gather
    reads the pool BEFORE the splice updates it, all inside one donated
    program (an int8 pool dequantizes the gathered run to row_dtype — the
    suffix continues from the same values decode attends to).
    Returns (cache', tok, logprob)."""
    row_k, row_v = _gather_row_pages(cache, read_list)
    logits, row_cache = _prefill_row_with_prefix(
        _fwd(pm), params, cfg, row_k, row_v,
        prefix_len, chunk,
    )
    return _paged_splice(
        cache, write_list, row_cache, logits, clen, rng, temperature, top_k,
        top_p, temp_req, topp_req, topk_req, mask_req, pm=pm,
    )


def _decode_steps(
    params, cfg, cache, last_tok, real_lens, valid, active, budget, rng,
    chunk_steps, temperature, top_k, top_p, eos_id, pad_id, pm, tables,
    temp_row, topp_row, topk_row, counts, pres_row, freq_row, mask_stack,
    next_stack, dfa_state,
):
    """The K-step decode scan shared VERBATIM by :func:`decode_chunk` and
    the fused :func:`mixed_step` — one definition of the decode leg is
    what keeps ``schedule=mixed`` trivially byte-identical to the
    alternating loop's decode math."""
    if tables is None:
        s = cache.k.shape[-3]
        slots = jnp.arange(s, dtype=jnp.int32)

    def step(carry, rng_step):
        (cache, last_tok, real_lens, valid, active, budget, cnts,
         dstate) = carry
        # One batched forward with PER-ROW write slots (models.model accepts
        # a [B] cache_index: only the KV write scatters; all matmuls stay
        # batched).  Paged mode: the page table routes each row's read and
        # write; the prefix mask is implicit.  Contiguous mode: the mask
        # admits each row's valid slots plus the slot its own token was
        # just written to.
        if tables is not None:
            logits, cache = _fwd(pm)(
                params, cfg, last_tok[:, None], positions=real_lens[:, None],
                cache=cache, cache_index=real_lens, kv_tables=tables,
            )
        else:
            mask = (valid | (slots[None, :] == real_lens[:, None]))[:, None, None, :]
            logits, cache = _fwd(pm)(
                params, cfg, last_tok[:, None], positions=real_lens[:, None],
                cache=cache, cache_index=real_lens, attn_mask=mask,
            )
        logits = logits[:, 0]
        # The row just wrote last_tok's K/V at slot real_lens; mark it valid
        # for rows that were active (inactive rows wrote junk into a slot
        # that stays invalid — harmless, and re-prefilled on admission).
        # Paged mode has no mask to maintain: validity is implicit in
        # real_lens (the kernel's prefix contract).
        if tables is None:
            valid = valid | (
                active[:, None] & (slots[None, :] == real_lens[:, None])
            )
        real_lens = real_lens + active.astype(jnp.int32)
        if cnts is not None:
            sample_from = (
                logits
                - freq_row[:, None] * cnts.astype(logits.dtype)
                - pres_row[:, None] * (cnts > 0).astype(logits.dtype)
            )
        else:
            sample_from = logits
        # Grammar/bias mask: gather each row's state mask AFTER penalties
        # (the -1e30 forbidden entries dominate any finite adjustment;
        # free rows gather state 0's all-zero row — exact identity).
        bias = (constrain_lib.gather_bias(mask_stack, dstate)
                if dstate is not None else None)
        if temp_row is None:
            src = sample_from if bias is None else sample_from + bias
            tok = sampling.sample(rng_step, src, temperature, top_k,
                                  top_p)
        else:
            tok = sampling.sample_rows(
                rng_step, sample_from, temp_row, top_k,
                1.0 if topp_row is None else topp_row,
                top_k_rows=topk_row, mask_rows=bias,
            )
        if dstate is not None:
            # Advance each (pre-step-)active row's automaton on its
            # sampled token — one gather, device-resident, so a chained
            # dispatch-ahead chunk consumes the advanced state directly.
            dstate = jnp.where(
                carry[4],
                constrain_lib.advance_states(next_stack, dstate, tok),
                dstate,
            )
        if cnts is not None:
            cnts = cnts.at[
                jnp.arange(cnts.shape[0]), tok
            ].add(active.astype(jnp.int32))
        budget = budget - active.astype(jnp.int32)
        if eos_id >= 0:
            active = active & (tok != eos_id)
        active = active & (budget > 0)
        out = jnp.where(
            carry[4], tok, jnp.int32(pad_id)
        )  # mask with PRE-step active
        # Chosen-token logprob under the raw distribution (serving's
        # OpenAI logprobs field) — one log-softmax reduction per step.
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1),
            tok[:, None], axis=-1,
        )[:, 0]
        lp = jnp.where(carry[4], lp, 0.0)
        last_tok = jnp.where(carry[4], tok, last_tok)
        return (
            (cache, last_tok, real_lens, valid, active, budget, cnts,
             dstate),
            (out, lp),
        )

    rngs = jax.random.split(rng, chunk_steps)
    carry0 = (cache, last_tok, real_lens, valid, active, budget, counts,
              dfa_state)
    ((cache, last_tok, real_lens, valid, active, budget, counts,
      dfa_state), (toks, lps)) = jax.lax.scan(step, carry0, rngs)
    toks, lps, last_tok, real_lens, valid, active, budget = _replicated(
        pm, toks.T, lps.T, last_tok, real_lens, valid, active, budget
    )
    if counts is not None:
        # The histogram is scheduling state too: replicated, so every host
        # of a multi-process mesh applies identical penalty adjustments.
        counts = _replicated(pm, counts)
    if dfa_state is not None:
        # The automaton state is replicated scheduling state like the rest
        # of the carry: every host syncs identical states at span end.
        dfa_state = _replicated(pm, dfa_state)
    if tables is not None:
        # Mesh paged decode: pin the pool carry back to its sharding (KV
        # heads over 'model') so chained dispatch-ahead chunks and the
        # scatter/gather jits all consume one placement (no-op off-mesh).
        cache = _pool_constrain(pm, cache)
    return (toks, cache, last_tok, real_lens, valid, active, budget, lps,
            counts, dfa_state)


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "chunk_steps", "temperature", "top_k", "top_p", "eos_id",
        "pad_id", "pm",
    ),
    donate_argnames=("cache",),
)
def decode_chunk(
    params: Any,
    cfg: ModelConfig,
    cache: Any,  # shared KVCache
    last_tok: jax.Array,  # [B] int32 — each row's most recent token
    real_lens: jax.Array,  # [B] int32 — tokens resident per row (write pos)
    valid: jax.Array,  # [B, S] bool — per-row valid cache slots
    active: jax.Array,  # [B] bool
    budget: jax.Array,  # [B] int32 — tokens this row may still emit
    rng: jax.Array,
    chunk_steps: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int = -1,
    pad_id: int = 0,
    pm: Any = None,  # ParallelModel — GSPMD dp/tp mesh batching
    tables: jax.Array | None = None,  # [B, P] page table — cache is a pool
    temp_row: jax.Array | None = None,  # [B] traced per-row temperature
    topp_row: jax.Array | None = None,  # [B] traced per-row top-p
    topk_row: jax.Array | None = None,  # [B] traced per-row top-k
    counts: jax.Array | None = None,  # [B, V] int32 output-token histogram
    pres_row: jax.Array | None = None,  # [B] traced presence penalties
    freq_row: jax.Array | None = None,  # [B] traced frequency penalties
    mask_stack: jax.Array | None = None,  # [S, V] f32 per-state token mask
    #   (constrain.build_stack: state 0 free, padded up a closed ladder)
    next_stack: jax.Array | None = None,  # [S, V] int32 DFA transitions
    dfa_state: jax.Array | None = None,  # [B] int32 DFA state (0 = free)
) -> tuple[jax.Array, Any, jax.Array, jax.Array, jax.Array, jax.Array,
           jax.Array, jax.Array, jax.Array | None, jax.Array | None]:
    """K decode steps with per-row positions.  Returns
    (toks [B, K], cache', last_tok', real_lens', valid', active', budget',
    logprobs [B, K], counts', dfa_state').  ``temp_row``/``topp_row``/``topk_row``
    switch sampling to the per-row path (sampling.sample_rows) —
    per-request sampling in one shared batch.  ``counts``+``pres_row``+``freq_row`` engage OpenAI
    presence/frequency penalties: logits adjust by
    ``- freq*count - pres*(count > 0)`` per row BEFORE sampling, and the
    histogram tracks every emitted token (rows with zero penalties read
    garbage counts harmlessly — the adjustment multiplies to zero).
    ``mask_stack``+``next_stack``+``dfa_state`` engage grammar-constrained
    structured output (runtime/constrain.py): each row gathers its
    state's token mask, adds it to the sampling logits (after penalties —
    the mask dominates any finite adjustment), and advances its automaton
    state on the sampled token INSIDE this jitted program, so the state
    carry stays device-resident across dispatch-ahead chunks and
    constrained and free rows share one compiled decode step (graftcheck
    GC4 batcher.decode_chunk_constrained).  Free rows ride state 0, whose
    mask row is all zeros — their sampled bytes are untouched.
    Logprobs stay RAW-distribution (pre-penalty, pre-mask), matching the
    logprobs contract elsewhere.

    Chaining contract (the dispatch-ahead engine loop): every returned
    carry leaf (cache', last_tok', real_lens', valid', active', budget',
    counts') is a legal INPUT for the next call — same shapes, same
    dtypes, device-resident — so chunk N+1 can dispatch directly from
    chunk N's outputs with no host round-trip, hitting the same compiled
    program host-mirror inputs would (graftcheck GC4's
    batcher.decode_chunk_overlap case pins this to one compile key).
    Only ``cache`` is donated; the small carry vectors are read-only
    inputs and safe to hold across the chained dispatch."""
    return _decode_steps(
        params, cfg, cache, last_tok, real_lens, valid, active, budget,
        rng, chunk_steps, temperature, top_k, top_p, eos_id, pad_id, pm,
        tables, temp_row, topp_row, topk_row, counts, pres_row, freq_row,
        mask_stack, next_stack, dfa_state,
    )


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "pcfg", "chunk_steps", "temperature", "top_k", "top_p",
        "eos_id", "pad_id", "pm",
    ),
    donate_argnames=("cache", "row_k", "row_v"),
)
def mixed_step(
    params: Any,
    cfg: ModelConfig,   # decode-leg config (ragged decode where enabled)
    pcfg: ModelConfig,  # prefill-leg config (the plain forward)
    cache: Any,
    last_tok: jax.Array,
    real_lens: jax.Array,
    valid: jax.Array,
    active: jax.Array,
    budget: jax.Array,
    rng: jax.Array,
    chunk_steps: int,
    row_k: jax.Array,   # [..., 1, S, KVH, HD] the head pending prefill's
    row_v: jax.Array,   # transient row (DONATED — updated in place)
    done: jax.Array,    # scalar int32 — prompt tokens already in the row
    pchunk: jax.Array,  # [Tw] int32 — the bite, right-padded to the policy's
    #   FIXED bucket width (compile key mix-independent — GC4 mixed_step)
    pclen: jax.Array,   # scalar int32 true bite length
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int = -1,
    pad_id: int = 0,
    pm: Any = None,
    tables: jax.Array | None = None,
    temp_row: jax.Array | None = None,
    topp_row: jax.Array | None = None,
    topk_row: jax.Array | None = None,
    counts: jax.Array | None = None,
    pres_row: jax.Array | None = None,
    freq_row: jax.Array | None = None,
    mask_stack: jax.Array | None = None,
    next_stack: jax.Array | None = None,
    dfa_state: jax.Array | None = None,
) -> tuple:
    """ONE fused token-budget step (``schedule=mixed``): the K-step decode
    scan for every active slot AND one prefill bite of the head pending
    chunked prefill, in the same compiled program — so resident decode
    rows never wait on a separately-dispatched serialized prefill forward
    (the Sarathi-Serve coalescing at Orca's iteration granularity).  The
    prefill leg is :func:`prefill_chunk_step`'s exact math (the segment
    enters variable-length, right-padded up the shared bucket ladder;
    continuation masking keeps pad columns unattended) against the
    prefill's own transient row cache; the decode leg is
    :func:`_decode_steps` verbatim — the legs touch disjoint buffers
    (transient row vs shared pool/cache), so fusion changes dispatch
    count, never bytes, and temp-0 streams are identical to the
    alternating loop.

    Returns :func:`decode_chunk`'s 10-tuple extended with
    ``(row_k', row_v', last_logits [1, V])`` — every leaf is a legal
    input for the next fused call (the dispatch-ahead chaining contract:
    the decode carry AND the prefill row both stay device-resident across
    a span)."""
    prow_k, prow_v, plast = _prefill_leg(
        params, pcfg, row_k, row_v, done, pchunk, pclen, pm
    )
    out = _decode_steps(
        params, cfg, cache, last_tok, real_lens, valid, active, budget,
        rng, chunk_steps, temperature, top_k, top_p, eos_id, pad_id, pm,
        tables, temp_row, topp_row, topk_row, counts, pres_row, freq_row,
        mask_stack, next_stack, dfa_state,
    )
    return (*out, prow_k, prow_v, plast)


def _writable(a: np.ndarray) -> np.ndarray:
    """A writable host array from a ``jax.device_get`` result: the CPU
    backend may hand back a read-only zero-copy view, and admission writes
    into the scheduling mirrors (the copy is paid only when needed)."""
    return a if a.flags.writeable else np.array(a)


@partial(jax.jit, donate_argnames=("counts",))
def _reset_count_row(counts, slot, tok):
    """Zero one row of the output-token histogram and count the admission
    token — a penalized request's penalties see exactly its own output."""
    v = counts.shape[1]
    row = jnp.zeros((v,), jnp.int32).at[tok].set(1)
    return counts.at[slot].set(row)


# _bucket (runtime/shapes.py bucket_length): admission prompt/suffix widths
# pad up the shared decode-shape ladder so compile keys stay bounded;
# tools.graftcheck's GC4 gate traces this path against shapes.bucket_count.


@dataclass(eq=False)  # identity equality: deque.remove/queue scans then
#   compare C-level object pointers instead of running a generated Python
#   __eq__ per element — the engine thread's queue scans stay atomic under
#   the GIL against the serving loop thread's concurrent submit() appends.
class _Request:
    rid: int
    ids: list[int]  # suffix ids when prefix is set, else the full prompt
    max_new_tokens: int
    prefix: str | None = None
    temperature: float | None = None  # None -> the batcher's config
    top_p: float | None = None
    top_k: int | None = None
    presence_penalty: float = 0.0   # OpenAI-style, applied to output tokens
    frequency_penalty: float = 0.0
    # Grammar-constrained structured output / logit bias / banned tokens
    # (runtime/constrain.py): ONE compiled token-mask automaton covers all
    # three.  The row's automaton state is a pure function of its emitted
    # tokens, so preemption/resume carries nothing extra — re-admission
    # replays the emitted prefix through the automaton on the host.
    constraint: Any = None  # constrain.TokenDFA | None
    prefix_cache: bool = True  # per-request opt-out of AUTOMATIC caching
    digests: list | None = None  # memoized page digests — a back-pressured
    #   request retries admission every round; its prompt hash never changes
    # Overload plane (PR 3): admission order is (priority desc, rid asc) —
    # higher priority admits first and is preempted last; rid breaks ties
    # FIFO (and lets a preempted request resume ahead of later arrivals).
    priority: int = 0
    # Absolute time.perf_counter() deadline: a request still QUEUED past it
    # is shed (results empty, shed[rid] set) instead of admitted doomed.
    deadline: float | None = None
    # Multi-tenant QoS (runtime/scheduler.py TenantScheduler): the tenant
    # this request bills against.  None = the anonymous bucket.  The
    # weighted-fair admission order, virtual token counters, and
    # resident-row caps all key on it; a preempted resume keeps it.
    tenant: str | None = None
    # Preemption-with-recompute state: tokens this request already emitted
    # (and streamed) in a previous residency.  ``ids`` then holds
    # prompt + resume_emitted, so re-admission prefills the full context
    # and the admission token CONTINUES the sequence (temp-0 exact).
    resume_emitted: list[int] | None = None
    resume_lps: list[float] | None = None
    # Swap-preemption state (host-RAM KV tier): the victim's raw pages are
    # parked in the HostTier under ``swap_handle`` and restore scatters
    # them back instead of recomputing — ``swap_pages``/``swap_last_tok``/
    # ``swap_pos`` rebuild the row's device scheduling state verbatim, and
    # ``max_new_tokens`` already holds the remaining budget (no admission
    # token is sampled on restore).  A failed restore (budget dry, drop
    # drill, checksum mismatch) clears swap_handle and falls through to
    # the recompute path above — ``ids`` is prompt + emitted either way.
    swap_handle: int | None = None
    swap_pages: int = 0
    swap_last_tok: int = 0
    swap_pos: int = 0


@dataclass
class _Prefix:
    ids: list[int]
    k: Any  # [..., 1, S, KVH, HD] single-row KV holding the prefix
    v: Any


class PrefixCache:
    """Content-addressed index of pool pages for AUTOMATIC prefix caching
    (vLLM/SGLang-style): every FULL page of an admitted prompt is keyed by
    a chained content digest (a page's digest commits to every token before
    it, so equal digests mean equal full prefixes), and later admissions
    reuse the longest cached page-run copy-free through their page tables.

    Ownership model: refcounts live with the batcher's pool allocator; this
    class only maps digests <-> pages and keeps the LRU of UNREFERENCED
    pages whose cached content is still resident — those are reclaimable
    (evicted oldest-first under pool pressure) but serve hits until then.
    Stats are cumulative per batcher and mirrored into the process-wide
    METRICS registry (gateway /metrics)."""

    def __init__(self) -> None:
        self.by_hash: dict[bytes, int] = {}
        self.page_hash: dict[int, bytes] = {}
        self.lru: OrderedDict[int, None] = OrderedDict()  # oldest first
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.lookups = 0
        self.hits = 0
        self.evictions = 0

    @staticmethod
    def page_digests(ids: list[int], page_size: int, n_pages: int,
                     kv_bits: int = 16) -> list[bytes]:
        """Chained blake2b digests of the first ``n_pages`` full pages:
        digest_i = H(digest_{i-1} || tokens of page i).  ``kv_bits`` salts
        the chain seed: a page's stored bytes are a deterministic function
        of (token prefix, kv width), so folding the width into the digest
        keeps sharing content-addressed over the QUANTIZED bytes — an int8
        page can never alias a bf16 page (locally, across a handoff, or in
        router affinity), while all default-width digests stay unchanged."""
        digests: list[bytes] = []
        prev = (b"dlt-prefix-cache-v1" if kv_bits == 16
                else b"dlt-prefix-cache-v1:kv%d" % kv_bits)
        # ONE token-id conversion for the whole prompt, sliced per page —
        # the old per-page np.asarray paid a fresh list->array
        # materialization inside every blake2b update; the chain bytes
        # are identical (tests/runtime/test_overlap.py pins equality
        # against the per-page construction).
        flat = np.asarray(ids[: n_pages * page_size], np.int64)
        for i in range(n_pages):
            h = hashlib.blake2b(prev, digest_size=16)
            h.update(flat[i * page_size: (i + 1) * page_size].tobytes())
            prev = h.digest()
            digests.append(prev)
        return digests

    def match(self, digests: list[bytes]) -> list[int]:
        """Pages of the longest cached run from the start (maybe empty)."""
        pages: list[int] = []
        for d in digests:
            p = self.by_hash.get(d)
            if p is None:
                break
            pages.append(p)
        return pages

    def register(self, page: int, digest: bytes) -> None:
        """Publish ``page`` as the holder of ``digest``.  First writer wins:
        if another page already holds this content, the new page stays
        private (it frees normally when its row releases it)."""
        if digest not in self.by_hash:
            self.by_hash[digest] = page
            self.page_hash[page] = digest

    def forget(self, page: int) -> None:
        """Drop a page's cache entry (eviction): its content is no longer
        addressable and the page returns to plain-allocator life."""
        d = self.page_hash.pop(page, None)
        if d is not None:
            self.by_hash.pop(d, None)
        self.lru.pop(page, None)

    def record_lookup(self, hit_tokens: int, miss_tokens: int) -> None:
        self.lookups += 1
        self.hits += hit_tokens > 0
        self.hit_tokens += hit_tokens
        self.miss_tokens += miss_tokens
        METRICS.inc("batcher.prefix_cache.lookups")
        if hit_tokens > 0:
            METRICS.inc("batcher.prefix_cache.hits")
        METRICS.inc("batcher.prefix_cache.hit_tokens", hit_tokens)
        METRICS.inc("batcher.prefix_cache.miss_tokens", miss_tokens)
        total = self.hit_tokens + self.miss_tokens
        if total:
            METRICS.set_gauge(
                "batcher.prefix_cache.hit_rate", self.hit_tokens / total
            )



class PagePool:
    """Refcounted KV page allocator for paged mode.  Owns the free list and
    per-page refcounts, and cooperates with an optional :class:`PrefixCache`
    whose LRU parks unreferenced-but-content-cached pages (still serving
    hits, reclaimable under pressure).  Page 0 is the permanent scratch
    page: never allocated, never freed, never read by a live row.

    Extracted from the batcher so the invariants have one owner and one
    audit (:meth:`assert_consistent`) — the recovery path's leak class
    (dangling refcounts / pinned cache pages after a crashed ``run``) is
    exactly a violation of these invariants, and the serving supervisor
    runs the audit after every engine restart."""

    def __init__(self, num_pages: int,
                 prefix_cache: "PrefixCache | None" = None,
                 host_tier: "HostTier | None" = None) -> None:
        self.num_pages = num_pages
        # Optional host-RAM tier BEHIND the pool (KV tiering): the batcher
        # spills eviction candidates into it before alloc reclaims them,
        # and swap-preemption parks whole rows there.  The pool itself
        # only audits and reports it — all data movement is the batcher's
        # (device calls never run under the allocator lock).
        self.host_tier = host_tier
        # Allocator lock: mutation happens on the engine thread, but the
        # occupancy view (stats/publish_gauges behind /metrics, the
        # supervisor's audit) reads from the serving loop thread — PR 3
        # published those gauges off GIL-atomic len() reads, the pattern
        # graftlint's GL101 now rejects.  The PrefixCache LRU is covered by
        # THIS lock too: every lru mutation goes through alloc/retain/
        # release (engine thread), every cross-thread read through stats().
        self._lock = threading.Lock()
        self.free_pages: list[int] = list(range(1, num_pages))  # guarded-by: self._lock
        # Refcounts of allocated pages (prefix-cache hits share pages
        # across rows; a page returns to free/LRU only at refcount 0).
        self.page_refs: dict[int, int] = {}  # guarded-by: self._lock
        self.prefix_cache = prefix_cache
        # Watermarks: the least headroom an admission has ever seen and the
        # most pages rows have ever held at once — the two numbers that say
        # whether a production pool is sized right (a min_available of 0
        # means admissions back-pressured or preempted; a peak_held far
        # under num_pages means the pool is over-provisioned).
        self.min_available = num_pages - 1  # guarded-by: self._lock
        self.peak_held = 0  # guarded-by: self._lock

    # graftlint: holds(self._lock)
    def _note_watermarks(self) -> None:
        avail = self._available_locked()
        if avail < self.min_available:
            self.min_available = avail
        held = len(self.page_refs)
        if held > self.peak_held:
            self.peak_held = held

    def stats(self) -> dict[str, int]:
        """Occupancy snapshot: every usable page is exactly one of free /
        LRU-cached / row-held (the partition assert_consistent audits).
        Safe from any thread (the /metrics scrape path)."""
        pc = self.prefix_cache
        with self._lock:
            return {
                "total_pages": self.num_pages - 1,  # page 0 is scratch
                "free_pages": len(self.free_pages),
                "cached_pages": len(pc.lru) if pc is not None else 0,
                "held_pages": len(self.page_refs),
                "min_available": self.min_available,
                "peak_held": self.peak_held,
            }

    def publish_gauges(self) -> None:
        """Mirror the occupancy view into the process-wide METRICS registry
        (rendered as batcher_pool_* on the gateway's /metrics); the host
        tier's occupancy rides along as batcher_host_tier_*."""
        METRICS.set_gauges({
            f"batcher.pool.{k}": float(v) for k, v in self.stats().items()
        })
        if self.host_tier is not None:
            METRICS.set_gauges({
                f"batcher.host_tier.{k}": float(v)
                for k, v in self.host_tier.stats().items()
            })

    def eviction_candidates(self, n: int) -> list[tuple[int, bytes]]:
        """The (page, digest) pairs :meth:`alloc`\\ (n) would evict from
        the LRU, oldest first — the spill plane reads these BEFORE the
        alloc so their content can move to the host tier.  Engine thread
        only: nothing may mutate the pool between this and the alloc."""
        pc = self.prefix_cache
        with self._lock:
            if pc is None:
                return []
            m = max(0, n - len(self.free_pages))
            out: list[tuple[int, bytes]] = []
            for p in pc.lru:
                if len(out) >= m:
                    break
                out.append((p, pc.page_hash[p]))
            return out

    # graftlint: holds(self._lock)
    def _available_locked(self) -> int:
        pc = self.prefix_cache
        return len(self.free_pages) + (len(pc.lru) if pc else 0)

    def available(self) -> int:
        """Pages an admission could obtain: the free list plus every
        LRU-parked cached page (reclaimable under pressure)."""
        with self._lock:
            return self._available_locked()

    def alloc(self, n: int) -> list[int]:
        """Allocate ``n`` pages at refcount 1, evicting LRU-cold cached
        pages when the free list runs dry (the caller checked
        :meth:`available` first)."""
        pc = self.prefix_cache
        out: list[int] = []
        with self._lock:
            for _ in range(n):
                if self.free_pages:
                    p = self.free_pages.pop()
                else:
                    p, _ = pc.lru.popitem(last=False)  # the coldest entry
                    pc.forget(p)
                    pc.evictions += 1
                    METRICS.inc("batcher.prefix_cache.evicted_pages")
                self.page_refs[p] = 1
                out.append(p)
            self._note_watermarks()
        return out

    def retain(self, p: int) -> None:
        """Take a reference on a cached page (a prefix-cache hit): pages
        referenced by live rows bump their refcount; LRU-parked ones come
        back referenced (their content stays addressable)."""
        with self._lock:
            if p in self.page_refs:
                self.page_refs[p] += 1
            else:
                del self.prefix_cache.lru[p]
                self.page_refs[p] = 1
            self._note_watermarks()

    def release(self, pages: list[int]) -> None:
        """Drop one reference per page.  At refcount 0 a content-cached
        page parks at the LRU's most-recently-used end — still serving
        hits until pool pressure reclaims it — while an uncached page
        returns straight to the free list."""
        pc = self.prefix_cache
        with self._lock:
            for p in pages:
                left = self.page_refs[p] - 1
                if left:
                    self.page_refs[p] = left
                    continue
                del self.page_refs[p]
                if pc is not None and p in pc.page_hash:
                    pc.lru[p] = None
                else:
                    self.free_pages.append(p)

    def publish_prefix(self, page: int, digest: bytes) -> None:
        """Publish a page's cached content (:meth:`PrefixCache.register`)
        under the allocator lock: the hash maps are engine-thread-written,
        but :meth:`assert_consistent` snapshots them from any thread —
        every cross-thread-visible PrefixCache mutation rides this lock
        (``forget`` runs inside the locked :meth:`alloc`)."""
        with self._lock:
            self.prefix_cache.register(page, digest)

    def assert_consistent(self, live_rows=(), swap_handles=()) -> None:
        """Audit the allocator's partition invariants; AssertionError on
        the first violation.  ``live_rows`` is the page lists of currently
        resident rows — every reference comes from exactly one row hold,
        so per-page refcounts must EQUAL the row-hold counts (a dangling
        ref or a pinned cache page after a crashed run fails here).
        With a host tier attached the audit extends across tiers:
        ``swap_handles`` is the swap handles of queued resume requests,
        and every parked parcel must be owned by exactly one of them
        (:meth:`HostTier.assert_consistent`) — a stranded handle is the
        host-RAM analogue of a dangling refcount.
        Takes one consistent snapshot under the allocator lock; callable
        from any thread."""
        if self.host_tier is not None:
            self.host_tier.assert_consistent(swap_handles)
        pc = self.prefix_cache
        with self._lock:
            lru = set(pc.lru) if pc is not None else set()
            hashed = set(pc.page_hash) if pc is not None else set()
            free_list = list(self.free_pages)
            refs = dict(self.page_refs)
        free = set(free_list)
        refed = set(refs)
        assert len(free) == len(free_list), (
            f"free list holds duplicates: {sorted(free_list)}"
        )
        assert 0 not in (free | refed | lru), "scratch page 0 escaped the pool"
        for a, b, what in ((free, refed, "free and refcounted"),
                           (free, lru, "free and LRU-parked"),
                           (refed, lru, "refcounted and LRU-parked")):
            assert not (a & b), f"pages both {what}: {sorted(a & b)}"
        accounted = free | refed | lru
        expect = set(range(1, self.num_pages))
        assert accounted == expect, (
            f"pages leaked (neither free, refcounted, nor LRU-parked): "
            f"{sorted(expect - accounted)}; "
            f"foreign pages: {sorted(accounted - expect)}"
        )
        assert all(v >= 1 for v in refs.values()), (
            f"non-positive refcounts: {refs}"
        )
        holds: dict[int, int] = {}
        for pages in live_rows:
            for p in pages:
                holds[p] = holds.get(p, 0) + 1
        assert holds == refs, (
            f"refcounts diverge from live-row holds: refs={refs} "
            f"holds={holds}"
        )
        for p in lru:
            assert p in hashed, (
                f"LRU-parked page {p} has no cached content"
            )


@dataclass
class _PendingPrefill:
    """A chunked prefill in flight: the request's prompt enters the row's
    TRANSIENT single-row cache ``prefill_chunk`` tokens per scheduling
    round (decode rounds interleave), splicing into the shared cache only
    when complete."""

    req: _Request
    row_k: Any          # transient [..., 1, S, KVH, HD] accumulating KV
    row_v: Any
    done: int           # prompt tokens already consumed (incl. prefix)
    ids: list[int]      # the request's own ids (prefix KV pre-seeded)
    total_len: int      # prefix + prompt length
    last_logits: Any | None = None  # [1, V] after the latest chunk
    # Automatic prefix-cache hit (paged mode): the cached page run seeding
    # the transient row.  The pages are RETAINED for the whole prefill
    # (mirrored into the reserving _RowState's ``pages`` so cancel/preempt
    # release them and the pool audit sees the references); the finishing
    # splice routes their positions to the scratch page — shared pages are
    # never rewritten.
    cached_pages: list[int] = field(default_factory=list)
    cached_len: int = 0
    digests: list = field(default_factory=list)


@dataclass
class _RowState:
    rid: int | None = None
    prefilling: bool = False  # chunked prefill in flight: the slot is
    #                     reserved but must not publish or decode yet
    req: "_Request | None" = None  # the request as admitted — preemption
    #                     rebuilds a resume request from it
    priority: int = 0   # mirror of req.priority (victim selection)
    admit_seq: int = 0  # monotone admission stamp: among equal priorities
    #                     the MOST recently admitted row is preempted first
    #                     (its lost work is smallest, vLLM's policy)
    emitted: list[int] = field(default_factory=list)
    lps: list[float] = field(default_factory=list)  # per-token logprobs
    #                     (raw TARGET distribution), aligned with emitted —
    #                     speculative mode gathers them from verify logits
    remaining: int = 0  # decode tokens this row may still emit (host mirror
    #                     of the device budget — distinguishes real pad-id
    #                     tokens from post-deactivation padding)
    pages: list[int] = field(default_factory=list)  # paged mode: the pool
    #                     pages this row owns (freed on completion)
    streamed: int = 0  # tokens already delivered to run()'s on_tokens


class ContinuousBatcher:
    """Slot-based continuous batching — single-device, or GSPMD dp/tp mesh
    when built with ``parallel=`` (see module docstring).

    Usage::

        batcher = ContinuousBatcher(cfg, params, tokenizer, batch_slots=8,
                                    max_len=512)
        rids = [batcher.submit(p, max_new_tokens=64) for p in prompts]
        results = batcher.run()   # {rid: token list}

    ``run`` drives admit/decode chunks until the queue drains and every row
    finishes.  Scheduling policy is FIFO admission into the first free slot.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: Any,
        tokenizer: Any = None,
        batch_slots: int = 8,
        max_len: int = 512,
        chunk_steps: int = 8,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_id: int = -1,
        pad_id: int = 0,
        kv_dtype: Any = None,
        seed: int = 0,
        parallel: Any = None,  # parallel.api.ParallelModel (GSPMD dp/tp)
        paged_pages: int | None = None,  # KV page-pool size (pages) — paged
        #   mode: rows admit with pages for the PROMPT plus one decode page
        #   and GROW on demand at chunk boundaries (vLLM's on-demand block
        #   allocation), so the pool can be far smaller than
        #   batch_slots * max_len; a dry pool evicts LRU-cold cached pages,
        #   then preempts the lowest-priority / most-recently-admitted row
        #   (freed pages now, recompute later — temp-0 streams stay exact),
        #   then back-pressures admission instead of OOMing.
        page_size: int = 64,
        # Automatic prefix caching (paged mode only): every full page of an
        # admitted prompt is content-hashed into a PrefixCache; later
        # requests reuse the longest cached page-run COPY-FREE through
        # their page tables (pages are refcounted; unreferenced cached
        # pages persist in an LRU and are evicted only under pool
        # pressure), so only the un-cached suffix prefills.  Transparent:
        # no register_prefix call needed; per-request opt-out via
        # submit(prefix_cache=False).  Tokens at temperature 0 stay
        # identical to solo decodes (tests/runtime/test_prefix_cache.py).
        prefix_cache: bool = False,
        # Speculative batching: every scheduling round drafts spec_k
        # tokens per row with the draft model and verifies them in ONE
        # target forward.  temperature == 0: tokens stay bit-identical to
        # the plain batcher (acceptance only changes how many arrive per
        # round); engine-wide temperature > 0: distribution-preserving
        # rejection sampling (spec_chunk docstring).  Single-device
        # contiguous mode (no mesh, no paging).
        draft_params: Any = None,
        draft_cfg: ModelConfig | None = None,
        spec_k: int = 4,
        # Adaptive spec_k downshift (greedy engines, schedule=mixed): a
        # per-row acceptance-rate EMA feeds the scheduler's spec_round_k
        # hook, which clamps each row's draft length — a cold draft stops
        # burning n_active*(spec_k+1) verify tokens of the step budget on
        # rounds that commit one token.  The clamp is a TRACED input
        # (one compiled program across the whole ladder) and the forced
        # stop emits the target's own token, so streams stay byte-exact
        # at any clamp; only arrival granularity changes.
        spec_adaptive_k: bool = True,
        # Chunked prefill: admission consumes at most this many prompt
        # tokens per scheduling round PER PENDING PREFILL (up to
        # ``prefill_concurrency`` advance concurrently), so a long prompt
        # never stalls in-flight decodes for its whole prefill — the
        # serving-QoS lever for mixed long/short traffic.  None =
        # monolithic admission.  Results stay token-identical (the chunk
        # steps are the prefix-continuation math against the row's own
        # partial prompt; logprob values agree to float drift — the same
        # attention reduced in different shapes).  Single-device
        # contiguous plain mode.
        prefill_chunk: int | None = None,
        # How many chunked prefills may be in flight at once: two long
        # prompts interleave their admission chunks instead of serializing
        # head-of-line (strict FIFO still gates STARTING one — the queue
        # front waits for a free prefill slot, never jumps it).
        prefill_concurrency: int = 2,
        # Deterministic fault injection (runtime/faults.py FaultPlane):
        # sites batcher.admit / batcher.decode / batcher.page_alloc are
        # consulted each scheduling round, so tests and operator drills can
        # crash, stall, or dry-pool the engine at an exact chunk.  None
        # disables (zero overhead beyond one attribute check per round).
        faults: Any = None,
        # KV memory tiering (paged mode): kv_bits=8 stores pool pages as
        # int8 with blockwise absmax scales (half the bytes/token -> ~1.9x
        # concurrent rows per pool byte; dequant fuses into the decode
        # attention read, greedy outputs are parity-bounded vs bf16, not
        # bit-exact).  host_pages > 0 arms a host-RAM tier behind the
        # pool: preemption SWAPS victims' raw pages out (restore is
        # byte-exact, cheaper than recompute for long prefixes; falls back
        # to exact recompute when the budget is dry) and the prefix-cache
        # LRU spills cold pages there before hard-evicting (a later hit
        # restores instead of re-prefilling).
        kv_bits: int = 16,
        host_pages: int = 0,
        # Dispatch-ahead engine loop: while no scheduling work is pending
        # (nothing queued, no chunked prefill / KV import / growth /
        # cancel), chunk N+1 dispatches DIRECTLY from chunk N's
        # device-resident carry (JAX async dispatch) and chunk N's host
        # work — token D2H, delivery/streaming callbacks, digest hashing,
        # metrics — runs while N+1 executes on device.  The host
        # scheduling mirrors refresh lazily at the next sync trigger, so
        # admission/growth/preemption semantics are byte-for-byte
        # unchanged and temp-0 outputs are byte-identical to overlap=False
        # (tests/runtime/test_overlap.py).  Mesh-legal, multi-process
        # included: the device-resident carry is replicated scheduling
        # state (every chunk fn constrains it P()), so a deferred sync
        # reads identical mirrors on every process and the lockstep
        # contract holds with the overlap on.
        overlap: bool = True,
        # Scheduling policy (runtime/scheduler.py): "mixed" (default)
        # fuses pending prefill-chunk bites into the decode step as one
        # compiled token-budget program so decode rows never stall for a
        # serialized prefill forward and a pending prefill no longer
        # parks the dispatch-ahead span; "alternate" keeps the serialized
        # prefill_chunk_step rounds.  Temp-0 bytes identical either way.
        schedule: str = "mixed",
        # Per-step token budget the mixed policy sizes prefill bites
        # against: each fused step runs one decode leg per active slot
        # plus up to token_budget - n_active prompt tokens.  None = bites
        # stay prefill_chunk-sized; set, it also auto-chunks any prompt
        # longer than the budget even when prefill_chunk is unset.
        token_budget: int | None = None,
        # Multi-tenant weighted-fair admission (runtime/scheduler.py
        # TenantScheduler): "gold:4,free:1"-style weights (or a parsed
        # dict; "*" sets the default weight) turn the mixed policy into
        # per-tenant virtual-token-counter fairness — submit(tenant=)
        # bills each request against its tenant's counter.  None keeps
        # the tenant-blind policies.
        tenant_weights: "str | dict | None" = None,
        # Per-tenant RESIDENT-row cap: a tenant at the cap defers
        # admission (others admit past it), so one tenant can never hold
        # every batch slot.  None = uncapped.
        tenant_max_rows: int | None = None,
        # The LOCKSTEP CLOCK: the one time source scheduling DECISIONS
        # may consult (today: queue-deadline shedding in
        # _shed_expired_queued — submit(deadline=) timestamps are read
        # against it).  Defaults to time.perf_counter for single-process
        # engines; a multi-process harness injects a deterministic clock
        # (e.g. derived from the scheduling round counter) so every
        # process sheds the same requests in the same round — decision
        # paths reading the wall clock directly are a graftsync GS101
        # finding (LOCKSTEP_DECISIONS, runtime/scheduler.py).  Metrics
        # and timer stamps (_t_complete, host-lag) are observability,
        # not decisions, and stay on the wall clock at the declared
        # HOST_SYNC_SITES.
        clock: "Callable[[], float] | None" = None,
    ) -> None:
        # Snapshot the constructor arguments FIRST (before any local
        # variables or normalization appear) so respawn() can rebuild an
        # identical fresh batcher after an engine crash — params/tokenizer/
        # fault plane are shared by reference; caches and pools are rebuilt.
        self._ctor_args = {
            k: v for k, v in locals().items() if k not in ("self", "__class__")
        }
        # Injectable lockstep clock (see the ``clock`` parameter note):
        # decisions read self._clock(), never time.perf_counter() —
        # the reference (not a call) below is the single default-wiring
        # point.
        self._clock = clock if clock is not None else time.perf_counter
        if max_len > cfg.max_seq_len:
            raise ValueError(
                f"max_len {max_len} exceeds model max_seq_len {cfg.max_seq_len}"
            )
        if kv_bits not in (16, 8):
            raise ValueError(f"kv_bits must be 16 or 8, got {kv_bits}")
        if kv_bits == 8 and paged_pages is None:
            raise ValueError(
                "int8 KV pages live in the paged pool; pass paged_pages "
                "(contiguous caches stay full-width)"
            )
        if host_pages < 0:
            raise ValueError(f"host_pages must be >= 0, got {host_pages}")
        if host_pages and paged_pages is None:
            raise ValueError(
                "the host-RAM KV tier backs the paged pool; pass paged_pages"
            )
        if paged_pages is not None:
            if parallel is not None and not (
                parallel.pipelined or parallel.seq_parallel
            ):
                # Mesh-native paged serving: the pool shards its KV-head
                # axis over 'model' (parallel.specs.page_pool_specs) and
                # the paged decode kernel partitions through its SPMD rule
                # (ops/decode_attn._paged_spmd) — each shard holds whole
                # heads, so the head count must divide.  Pipelined /
                # seq-parallel meshes fall through to the generic
                # rejection below (paged x pipelined stays unsupported
                # with the same message every batching mode gets).
                tp = parallel.mesh.shape.get("model", 1)
                if tp > 1 and cfg.num_kv_heads % tp:
                    raise ValueError(
                        f"paged KV on a tensor-parallel mesh shards the "
                        f"pool on the KV-head axis: num_kv_heads "
                        f"{cfg.num_kv_heads} must divide over 'model' "
                        f"({tp})"
                    )
            if cfg.sliding_window is not None:
                raise ValueError(
                    "paged KV cannot serve sliding-window models (the paged "
                    "decode kernel attends the full cache prefix); use "
                    "contiguous mode, which serves windowed models single-"
                    "device or on dp/tp meshes via the ragged kernel's "
                    "window band"
                )
            if max_len % page_size:
                raise ValueError(
                    f"max_len {max_len} must be a multiple of page_size "
                    f"{page_size}"
                )
            # Speculative rows need scratch-TAIL pages past max_len (the
            # verify window writes up to spec_k+1 slots beyond the
            # frontier — the paged analogue of the contiguous engine's
            # headroom slots), so a full-depth spec row holds a bit more
            # than max_len/page_size pages.
            _tail = spec_k + 1 if draft_params is not None else 0
            if paged_pages < -(-(max_len + _tail) // page_size) + 1:
                raise ValueError(
                    f"paged_pages {paged_pages} cannot hold even one "
                    f"full-depth row (+1 scratch page)"
                )
        if parallel is not None:
            if parallel.pipelined or parallel.seq_parallel:
                raise ValueError(
                    "continuous batching supports pure data/tensor-parallel "
                    "meshes; pipelined (wavefront) and sequence-parallel "
                    "(ring) meshes bring their own decode schedules"
                )
            dp = parallel.mesh.shape.get("data", 1)
            if batch_slots % dp:
                raise ValueError(
                    f"batch_slots {batch_slots} must divide over the mesh "
                    f"'data' axis ({dp})"
                )
        self.speculative = draft_params is not None
        if self.speculative:
            if draft_cfg is None:
                raise ValueError("draft_params needs draft_cfg")
            if parallel is not None:
                # The TARGET's KV rides the shared (shardable) pool in
                # paged mode, but the draft/verify chain itself has no
                # SPMD rule — spec x mesh stays fenced with a clear error
                # while spec x paged (prefix cache, int8 pages, the swap
                # tier, mixed budgets) composes since round 17.
                raise ValueError(
                    "speculative batching runs single-device (contiguous "
                    "or paged); serve mesh engines through the plain "
                    "batcher — the draft/verify chain has no SPMD rule"
                )
            # Engine-wide temperature/top_k/top_p compose with speculation
            # (distribution-preserving rejection sampling in spec_chunk);
            # only PER-REQUEST overrides are rejected (submit) — the
            # rejection test warps p and q with one static config.
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size}"
                )
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if prefill_chunk is not None:
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}"
                )
            if self.speculative:
                # Paged mode composes since PR 3 (the prefill runs against
                # the pageless transient row; pages are allocated only at
                # the finishing splice) and dp/tp meshes compose since the
                # chunk step threads the mesh forward (pm) with its
                # last-logits replicated.  Only the speculative draft's
                # monolithic full-prompt admission remains incompatible.
                raise ValueError(
                    "chunked prefill does not compose with speculative "
                    "batching (the draft admission prefills the full "
                    "prompt monolithically)"
                )
        if prefill_concurrency < 1:
            # Validated regardless of prefill_chunk: a bad value must not
            # pass construction just because chunking happens to be off.
            raise ValueError(
                f"prefill_concurrency must be >= 1, got "
                f"{prefill_concurrency}"
            )
        if prefix_cache and paged_pages is None:
            raise ValueError(
                "automatic prefix caching runs over the paged KV pool; "
                "pass paged_pages (or use register_prefix for the "
                "contiguous named-prefix path)"
            )
        # The dispatch-ahead loop is mesh-legal, multi-process included
        # (PR 10 degraded it there with a warning): the device carry is
        # small scheduling state every chunk fn returns CONSTRAINED
        # REPLICATED (_replicated, like _fwd's mirrors), so a deferred
        # _sync_carry reads identical values on every process, and the
        # sync triggers themselves (_overlap_ok) consult only
        # deterministic host state the lockstep contract already keeps
        # identical (queue contents, prefills, imports, pool accounting —
        # never wall clocks).  No degrade needed.
        self.prefill_chunk = prefill_chunk
        self.prefill_concurrency = prefill_concurrency
        # THE scheduling policy (runtime/scheduler.py): every decision the
        # run loop takes — admission order, chunk sizing against the token
        # budget, victim selection, the pressure ladder, the overlap
        # sync-trigger list — delegates to this object's declared hooks.
        self.sched = make_scheduler(
            schedule, chunk_steps=chunk_steps, prefill_chunk=prefill_chunk,
            prefill_concurrency=prefill_concurrency,
            token_budget=token_budget, speculative=self.speculative,
            spec_adaptive=bool(spec_adaptive_k),
            tenant_weights=tenant_weights, tenant_max_rows=tenant_max_rows,
        )
        self._prefills: dict[int, _PendingPrefill] = {}  # slot -> pending
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.spec_k = spec_k
        # Per-row acceptance-rate EMA (fraction of drafted tokens accepted
        # recently; optimistic 1.0 at admission so a fresh row drafts the
        # full k) + cumulative spec accounting for bench/tests — a pure
        # function of the committed stream, so downshifts are
        # deterministic run to run.
        self.spec_ema = np.ones((batch_slots,), np.float64)
        self.spec_stats = {
            "rounds": 0, "accepted": 0, "rejected": 0, "downshifts": 0,
        }
        self.pm = parallel
        self.cfg = cfg
        # Decode-chunk variant of the config: ragged decode attention (row b
        # reads only its cache prefix — ops/decode_attn.py) when the kernel
        # would actually run (TPU, or DLT_RAGGED_DECODE=kernel/interpret).
        # Meshes included: the ragged/paged kernels carry their own SPMD
        # partitioning rules now (ops/decode_attn._ragged_spmd/_paged_spmd
        # — each shard runs its local head slice; DLT_DECODE_ATTN_SPMD=0
        # is the kill-switch).  Not on the CPU "fallback" mode, whose
        # dense math is a different op from the masked dot path (the
        # exact-token invariant is against the latter).
        import dataclasses

        from ..ops import decode_attn

        # (Sliding-window models ride the ragged kernel too: it takes the
        # window bound and reads only [length - window, length) per row —
        # slot == position in this contiguous layout, so the slot-space
        # band equals the position-space window exactly.)
        self.cfg_decode = (
            dataclasses.replace(cfg, ragged_decode=True)
            if decode_attn._mode() != "fallback"
            else cfg
        )
        self.params = params
        self.tokenizer = tokenizer
        self.b = batch_slots
        self.s = max_len
        self.chunk_steps = chunk_steps
        self.sampling = dict(temperature=temperature, top_k=top_k, top_p=top_p)
        self.eos_id = eos_id
        self.pad_id = pad_id
        # Speculative mode reserves k+1 HEADROOM cache slots past max_len:
        # a near-capacity row's verify forward writes up to k+1 slots
        # beyond its frontier, and dynamic_update_slice CLAMPS an
        # overflowing start — without headroom the last committed slot's KV
        # would be silently overwritten with misaligned values (admission
        # capacity checks still enforce max_len; the extra slots are never
        # valid, never committed, only overwritten).
        cache_len = max_len + (spec_k + 1 if self.speculative else 0)
        if parallel is not None:
            # Mesh-sharded shared cache: 'data' on the batch axis, 'model'
            # on KV heads.  An explicit kv_dtype must not be silently
            # dropped: thread it onto the (frozen, so value-hashed — jit
            # keys stay stable) ParallelModel when it carries none, and
            # reject a conflict loudly.
            if kv_dtype is not None:
                want = jnp.dtype(kv_dtype).name
                if parallel.kv_dtype is None:
                    import dataclasses

                    parallel = self.pm = dataclasses.replace(
                        parallel, kv_dtype=want
                    )
                elif jnp.dtype(parallel.kv_dtype).name != want:
                    raise ValueError(
                        f"kv_dtype {want!r} conflicts with the mesh's "
                        f"kv_dtype {parallel.kv_dtype!r}"
                    )
            if paged_pages is not None:
                # Mesh-sharded PAGE POOL: every leaf [L, NB, BLK, KVH, HD]
                # (and the int8 scale stacks) shards its KV-head axis over
                # 'model' — per-chip pool bytes divide by tp, so per-chip
                # row capacity multiplies by the mesh.  Built under jit so
                # zeros+constraint materialize the GLOBAL sharded pool
                # directly (same reasoning as the contiguous mesh cache
                # below).  Pages are shared across rows (prefix cache,
                # handoff imports), so no axis shards over 'data'.
                pm_built = parallel

                def build_pool():
                    return _pool_constrain(pm_built, _paged_pool(
                        cfg, paged_pages, page_size,
                        dtype=(jnp.dtype(parallel.kv_dtype)
                               if parallel.kv_dtype else None),
                        kv_bits=kv_bits,
                    ))

                self.cache = jax.jit(build_pool)()
            else:
                # Under jit so the zeros+constraint build the GLOBAL
                # sharded cache directly — on a mesh spanning processes an
                # eager host-local zeros could not be constrained onto it.
                self.cache = jax.jit(
                    lambda: parallel.init_cache(batch_slots, max_len)
                )()
        elif paged_pages is not None:
            self.cache = _paged_pool(
                cfg, paged_pages, page_size,
                dtype=jnp.dtype(kv_dtype) if kv_dtype else None,
                kv_bits=kv_bits,
            )
        else:
            self.cache = model_lib.init_cache(
                cfg, batch_slots, cache_len,
                dtype=jnp.dtype(kv_dtype) if kv_dtype else None,
            )
        if self.speculative:
            self.draft_cache = model_lib.init_cache(
                draft_cfg, batch_slots, cache_len,
                dtype=jnp.dtype(kv_dtype) if kv_dtype else None,
            )
        self.page_size = page_size
        self.paged = paged_pages is not None
        self.kv_bits = kv_bits
        self.prefix_cache: PrefixCache | None = None
        self.pool: PagePool | None = None
        self.host_tier: HostTier | None = None
        self.faults = faults  # FaultPlane | None (runtime/faults.py)
        if self.paged:
            # Speculative page tables carry the scratch-tail pages too:
            # the verify window writes through slot real_lens + spec_k,
            # so a full-depth row's table must reach past max_len by the
            # k+1-token window (the contiguous engine's headroom slots,
            # as pages).
            self.pages_per_row = (
                -(-(max_len + spec_k + 1) // page_size)
                if self.speculative else max_len // page_size
            )
            if prefix_cache:
                self.prefix_cache = PrefixCache()
            if host_pages:
                self.host_tier = HostTier(host_pages)
            # Page 0 is the permanent scratch page: fixed-shape admissions
            # pad their page lists with it, and no row ever reads it.
            self.pool = PagePool(paged_pages, prefix_cache=self.prefix_cache,
                                 host_tier=self.host_tier)
            self.tables = np.zeros((batch_slots, self.pages_per_row), np.int32)
        # Scheduling state lives as HOST numpy mirrors: every process holds
        # the same values (the jitted chunk fns return them constrained
        # replicated, and np.asarray of a replicated output is legal on all
        # processes), and feeding numpy back in treats it as a replicated
        # input — no eager device ops on global arrays anywhere, which is
        # what keeps a multi-process mesh in lockstep.
        self.last_tok = np.zeros((batch_slots,), np.int32)
        self.real_lens = np.zeros((batch_slots,), np.int32)
        # Sized to the CACHE width (speculative mode pads k+1 headroom slots
        # past max_len; admission row_valid vectors come back cache-sized).
        # Paged mode keeps per-row logical width (the cache is a page pool).
        # Paged mode keeps per-row logical width (the target cache is a
        # page pool) — EXCEPT under speculation, where ``valid`` gates the
        # contiguous DRAFT cache's masks and must span its headroom slots.
        self.valid = np.zeros(
            (batch_slots,
             cache_len if (self.speculative or not self.paged) else max_len),
            bool,
        )
        self.active = np.zeros((batch_slots,), bool)
        self.budget = np.zeros((batch_slots,), np.int32)
        # Per-row sampling mirrors: rows admitted with explicit per-request
        # knobs diverge from the batcher config; decode chunks switch to
        # the traced per-row sampling path only while such a row is live.
        self.temp_row = np.full((batch_slots,), temperature, np.float32)
        self.topp_row = np.full((batch_slots,), top_p, np.float32)
        self.topk_row = np.full((batch_slots,), top_k, np.int32)
        self.pres_row = np.zeros((batch_slots,), np.float32)
        self.freq_row = np.zeros((batch_slots,), np.float32)
        # Constrained-decoding mirrors: each constrained row's automaton
        # state LOCAL to its own TokenDFA (the span plan rebases to
        # absolute stack indices), synced back from the device carry at
        # span end.  ``_con_stack`` memoizes the span's (bias, next,
        # offsets) stack across spans with an unchanged constraint mix.
        self.dfa_row = np.zeros((batch_slots,), np.int32)
        self._con_stack: tuple | None = None  # (key, bias_j, next_j, offs)
        self._dfa_carry: jax.Array | None = None  # device [B] abs states
        # Output-token histogram [B, V], allocated on the first penalized
        # admission (1 MB at 32k vocab — but zero cost for servers that
        # never see a penalty).
        self.tok_counts: jax.Array | None = None
        self.rows = [_RowState() for _ in range(batch_slots)]
        # Dispatch-ahead engine loop (overlap): per-batcher counters the
        # bench and tests read directly (mirrored into METRICS as they
        # accrue).  ``_cancel_dirty`` flags a resident-row cancel taken
        # while the decode carry was device-resident — the next chunk
        # boundary must SYNC so the cancelled row actually stops;
        # ``_t_complete`` stamps when the host last observed a chunk
        # complete (the device-gap metric's reference point).
        self.overlap = bool(overlap)
        self.overlap_stats = {
            "chunks": 0, "dispatched_ahead": 0, "carry_syncs": 0,
            "host_lag_s": 0.0, "device_gap_s": 0.0, "gap_samples": 0,
        }
        self._cancel_dirty = False
        self._tables_dirty = False
        self._t_complete: float | None = None
        # Submission lock: the ONE cross-thread boundary of this class.
        # Serving front-ends submit() from their own thread while the
        # engine thread scans/admits; PR 3 relied on GIL-atomic deque ops
        # and list() snapshots for this, which graftlint's lock-discipline
        # rule (GL101) now rejects — every queue/_next_rid access below
        # holds this lock instead.  Held only for host bookkeeping, never
        # across a device call or a user callback.
        self._lock = threading.Lock()
        self.queue: deque[_Request] = deque()  # guarded-by: self._lock
        # Overload plane: rids shed while still queued (deadline expired
        # before admission) with the reason — serving front-ends read it at
        # the done delivery to answer 503 instead of a bare empty result.
        self.shed: dict[int, str] = {}
        self.preemptions = 0  # rows preempted for pool pressure (cumulative)
        self._admit_seq = 0   # monotone admission stamp (victim selection)
        self.results: dict[int, list[int]] = {}
        # Per-token logprobs of each finished request; same lifecycle as
        # ``results`` (speculative mode gathers them from verify logits).
        self.result_logprobs: dict[int, list[float]] = {}
        # Prompt tokens served from the automatic prefix cache, per rid —
        # set at admission, read by serving front-ends for usage reporting
        # (OpenAI prompt_tokens_details.cached_tokens); same lifecycle as
        # ``results``.
        self.prefix_cached_tokens: dict[int, int] = {}
        self.prefixes: dict[str, _Prefix] = {}
        self._rng = jax.random.key(seed)
        self._next_rid = 0  # guarded-by: self._lock
        self._on_tokens = None  # set per run() call (streaming callback)
        # KV-handoff plane (disaggregated serving): verified transfers
        # queued by the serving loop thread, adopted by the ENGINE thread
        # at the next scheduling-round boundary — the pool scatter is a
        # device call and the pool/prefix-cache bookkeeping is
        # engine-owned, exactly like admission.
        self._kv_imports: deque = deque()  # guarded-by: self._lock
        # Cross-replica pull plane: cached-run export requests queued by
        # the serving loop (/v1/kv_export), gathered by the ENGINE thread
        # at the next round boundary — the pool gather is a device call,
        # same ownership rule as imports.
        self._kv_exports: deque = deque()  # guarded-by: self._lock

    # -- prefix caching ------------------------------------------------------

    def register_prefix(self, name: str, prefix: str | list[int]) -> None:
        """Prefill a shared prefix (e.g. a system prompt) ONCE; requests
        submitted with ``prefix=name`` reuse its KV instead of recomputing
        it — admission then prefills only the request's suffix."""
        ids = (
            self.tokenizer.encode(prefix)
            if isinstance(prefix, str)
            else list(prefix)
        )
        if len(ids) >= self.s:
            raise ValueError(
                f"prefix ({len(ids)} tokens) does not fit slot capacity {self.s}"
            )
        # Contiguous mode: CACHE width, not self.s — speculative mode pads
        # headroom slots and the admission splice needs shape-matched rows.
        # Paged mode: the TABLE width (pages_per_row * page_size — equal to
        # self.s except under speculation, whose tables carry scratch-tail
        # pages), since _paged_splice reshapes the row into exactly the
        # page-list's pages.
        width = (self.pages_per_row * self.page_size if self.paged
                 else self.cache.k.shape[-3])
        row_cache = model_lib.init_cache(
            self.cfg, 1, width, dtype=_row_dtype_of(self.cache)
        )
        positions = jnp.arange(len(ids), dtype=jnp.int32)[None, :]
        _, row_cache = _fwd(self.pm)(
            self.params, self.cfg, jnp.asarray([ids], jnp.int32),
            positions=positions, cache=row_cache, cache_index=jnp.int32(0),
        )
        self.prefixes[name] = _Prefix(ids, jax.block_until_ready(row_cache.k), row_cache.v)

    # -- paged pool allocator (PagePool; refcounted, prefix-cache LRU) -----

    @property
    def free_pages(self) -> list[int]:
        """The pool's free list (paged mode) — kept as a property so tests
        and callers that predate the PagePool extraction keep working."""
        return self.pool.free_pages

    @property
    def page_refs(self) -> dict[int, int]:
        return self.pool.page_refs

    def _pages_available(self) -> int:
        return self.pool.available()

    def _alloc_pages(self, n: int) -> list[int]:
        """Pool allocation with the spill tier in front: any LRU-cached
        page this alloc would hard-evict first has its content moved to
        the host tier (content-addressed by digest), so a later
        prefix-cache hit restores it instead of re-prefilling.  Best
        effort: a dry host budget (or a kv.spill drop drill) degrades to
        plain eviction — correct, just cold."""
        if self.host_tier is not None and n:
            self._spill_cold_pages(n)
        return self.pool.alloc(n)

    def _spill_cold_pages(self, n: int) -> None:
        """ENGINE THREAD, immediately before an alloc(n): park the
        eviction candidates' raw page bytes in the host tier.  The device
        gather is dispatched here; the D2H copy runs on the tier's worker
        thread — the pressure path never blocks on a host transfer."""
        cand = self.pool.eviction_candidates(n)
        if not cand:
            return
        if not self.host_tier.can_fit(1):
            # Saturated with swap parcels (never evicted for spills):
            # don't pay the device gather just for park_spill to refuse.
            return
        rule = (self.faults.fire("kv.spill", tag="out")
                if self.faults is not None else None)
        if rule is not None and rule.action == "drop":
            return
        corrupt = rule is not None and rule.action == "corrupt"
        pages = [p for p, _ in cand]
        payload = _export_pages_raw(
            self.cache, jnp.asarray(self._padded_page_list(pages))
        )
        parked = self.host_tier.park_spill(
            [d for _, d in cand], payload, corrupt=corrupt
        )
        if parked:
            METRICS.inc("batcher.host_tier.spilled_pages", parked)

    def _page_digests(self, ids: list[int], n_pages: int) -> list[bytes]:
        """This pool's content digests: chained over token ids AND the KV
        width (kv_bits salts the chain), so an int8 page can never alias
        a bf16 page across engines or tiers."""
        return PrefixCache.page_digests(ids, self.page_size, n_pages,
                                        kv_bits=self.kv_bits)

    def _padded_page_list(self, pages: list[int]) -> np.ndarray:
        """Pages padded with the scratch page 0 up the shared bucket
        ladder — the raw export/import jits take the padded width as a
        compile dimension, so page counts must walk the same closed
        ladder prompt lengths do (graftcheck GC4's discipline): a
        preemption storm over varied row lengths must never pay a fresh
        XLA compile per count on the engine thread.  Padded slots gather
        /scatter the scratch page, which no live row ever reads."""
        nb = min(_bucket(len(pages)), self.pages_per_row)
        out = np.zeros((nb,), np.int32)
        out[: len(pages)] = pages
        return out

    def _retain_page(self, p: int) -> None:
        self.pool.retain(p)

    def _release_pages(self, pages: list[int]) -> None:
        self.pool.release(pages)

    def capacity_tokens(self) -> int:
        """KV capacity in tokens: the denominator of the serving gateway's
        estimated-cost admission gate.  Paged mode counts usable pool pages
        (page 0 is scratch); contiguous mode counts slot-owned width."""
        if self.paged:
            return (self.pool.num_pages - 1) * self.page_size
        return self.b * self.s

    def assert_pool_consistent(self) -> None:
        """Audit the page pool against the resident rows (no-op in
        contiguous mode), and the host tier against the queued resume
        requests when one is armed — every swap parcel must be owned by
        exactly one queued request, or host RAM leaked.  The serving
        supervisor runs this after every engine restart; paged tests run
        it after each workload — a failure means refcounts, cache pins,
        or host parcels leaked, the recovery-path bug class this audit
        exists to catch."""
        if self.pool is not None:
            self.pool.assert_consistent(
                [r.pages for r in self.rows if r.pages],
                swap_handles=[
                    r.swap_handle for r in self.queue_snapshot()
                    if r.swap_handle is not None
                ],
            )

    # -- KV handoff (disaggregated prefill/decode) -------------------------

    def export_prefix_pages(
        self, ids: list[int]
    ) -> "tuple[list[bytes], np.ndarray, np.ndarray] | None":
        """ENGINE THREAD: gather the prompt's longest cached full-page run
        out of the pool for handoff to a decode-role engine.  Returns
        (chained page digests, k pages [L, P, BLK, KVH, HD], v pages) in
        host numpy, or None when nothing exportable is resident (prompt
        shorter than a page, caching off, or the run was evicted).  The
        run is capped one page short of the prompt — the importer's
        matcher caps hits the same way, so shipping the last partial page
        would be dead weight.  Pages are retained across the gather so
        pool pressure cannot reclaim them mid-export."""
        pc = self.prefix_cache
        if self.pool is None or pc is None:
            return None
        blk = self.page_size
        n = (len(ids) - 1) // blk
        if n < 1:
            return None
        digests = self._page_digests(ids, n)
        pages = pc.match(digests)
        if not pages:
            return None
        for p in pages:
            self._retain_page(p)
        try:
            row_k, row_v = _gather_row_pages(
                self.cache, jnp.asarray(np.asarray(pages, np.int32))
            )
            l, _one, _w, kvh, hd = row_k.shape
            k = np.asarray(row_k).reshape(l, len(pages), blk, kvh, hd)
            v = np.asarray(row_v).reshape(l, len(pages), blk, kvh, hd)
        finally:
            self._release_pages(pages)
        METRICS.inc("batcher.kv_pages_exported", len(pages))
        return digests[: len(pages)], k, v

    def has_kv_imports(self) -> bool:
        """Whether a verified handoff awaits adoption (any thread)."""
        with self._lock:
            return bool(self._kv_imports)

    def submit_kv_import(self, digests: list[bytes], k_pages, v_pages,
                         on_done) -> None:
        """Queue a VERIFIED transfer's pages for adoption (any thread —
        the decode server's KV listener calls this from the event loop).
        The engine thread applies it at its next round boundary and calls
        ``on_done(ok, reason)`` from there; the caller is responsible for
        waking the engine."""
        with self._lock:
            self._kv_imports.append((digests, k_pages, v_pages, on_done))

    def _drain_kv_imports(self) -> None:
        """ENGINE THREAD, at a scheduling-round boundary: adopt every
        queued handoff into the pool.  Device work and pool bookkeeping
        happen outside the submission lock (the lock is host-bookkeeping
        only, never held across a device call)."""
        while True:
            with self._lock:
                if not self._kv_imports:
                    return
                digests, k_pages, v_pages, on_done = \
                    self._kv_imports.popleft()
            ok, reason = self._import_kv_pages(digests, k_pages, v_pages)
            try:
                on_done(ok, reason)
            except Exception:
                log.exception("kv-import completion callback raised")

    def has_kv_exports(self) -> bool:
        """Whether a cached-run export awaits the engine (any thread)."""
        with self._lock:
            return bool(self._kv_exports)

    def submit_kv_export(self, ids: list[int], on_done) -> None:
        """Queue a cached-run export for the engine thread (any thread —
        the serving loop's /v1/kv_export handler calls this).  The engine
        gathers the prompt's longest cached full-page run at its next
        round boundary and calls ``on_done(payload_or_None)`` from there
        (the :meth:`export_prefix_pages` result); the caller is
        responsible for waking the engine."""
        with self._lock:
            self._kv_exports.append((list(ids), on_done))

    def _drain_kv_exports(self) -> None:
        """ENGINE THREAD, at a scheduling-round boundary: serve every
        queued cross-replica export.  Purely a cache read — nothing is
        admitted, no row state changes; a prompt whose run is not
        resident answers None (the puller recomputes locally)."""
        while True:
            with self._lock:
                if not self._kv_exports:
                    return
                ids, on_done = self._kv_exports.popleft()
            payload = self.export_prefix_pages(ids)
            try:
                on_done(payload)
            except Exception:
                log.exception("kv-export completion callback raised")

    def _import_kv_pages(self, digests, k_pages, v_pages):
        """Adopt one transfer: allocate pool pages, scatter the payload,
        publish the digests, and park the pages in the prefix-cache LRU —
        content-addressed and unreferenced, exactly like a completed local
        prompt's pages.  The handed-off request's admission then RETAINS
        them through the ordinary cache-hit path (refcounted on its
        _RowState, released on completion/cancel/preempt), and only its
        un-shipped suffix prefills.  Idempotent: digests already resident
        ack "duplicate" without touching the pool."""
        pc = self.prefix_cache
        if self.pool is None or pc is None:
            return False, "not a decode-role engine"
        l, _nb, blk, kvh, hd = self.cache.k.shape
        if (k_pages.shape != (l, len(digests), blk, kvh, hd)
                or v_pages.shape != k_pages.shape):
            return False, "pool shape mismatch"
        # Import only the pages whose content is NOT already addressable:
        # a duplicate delivery (retry racing a delayed ack) acks without
        # touching the pool, and a PARTIAL overlap (another transfer or a
        # local prompt already published a prefix of this chain) neither
        # demands capacity for pages it does not need nor pays a scatter
        # for content that would lose first-writer-wins anyway.
        missing = [i for i, d in enumerate(digests) if d not in pc.by_hash]
        if not missing:
            return True, "duplicate"
        if self._pages_available() < len(missing):
            return False, "no capacity"
        pages = self._alloc_pages(len(missing))
        # The scatter's page count is a compile dimension; distinct
        # overlap widths compile distinct (tiny) programs — bounded by
        # pages_per_row, and imports sit far off the decode hot path.
        self.cache = _import_pages(
            self.cache, jnp.asarray(np.asarray(pages, np.int32)),
            jnp.asarray(np.ascontiguousarray(k_pages[:, missing])),
            jnp.asarray(np.ascontiguousarray(v_pages[:, missing])),
            pm=self.pm,
        )
        for p, i in zip(pages, missing):
            # First writer wins: a digest published since the scan above
            # leaves ours private (it frees on the release below).
            self.pool.publish_prefix(p, digests[i])
        self._release_pages(pages)
        METRICS.inc("batcher.kv_pages_imported", len(pages))
        log.info("imported %d handed-off KV page(s) (%d already resident)",
                 len(pages), len(digests) - len(pages))
        return True, "imported"

    # -- crash recovery ----------------------------------------------------

    def respawn(self) -> "ContinuousBatcher":
        """A fresh batcher built from this one's construction arguments:
        new KV pool/cache and prefix cache, empty queue and rows, zeroed
        scheduling state.  This is the crash-recovery primitive: after
        ``run`` raises, the device state is unreconstructable (the jitted
        chunk programs donate the cache), so the supervisor discards the
        instance wholesale and re-admits work into a respawn.  Weights,
        tokenizer, and the fault plane carry over by reference; rid
        continuity (``_next_rid``) and named-prefix KV are the caller's to
        transplant."""
        return ContinuousBatcher(**self._ctor_args)

    # -- submission --------------------------------------------------------

    @property
    def next_rid(self) -> int:
        """The rid the next ``submit`` call will return.  Serving front-ends
        register their delivery state under this id BEFORE submitting:
        once ``submit`` appends to the queue, an engine thread already
        inside ``run()`` may admit the request and fire ``on_tokens``
        immediately — registering afterwards would race it.  Only valid
        when all submissions happen on one thread."""
        with self._lock:
            return self._next_rid

    def has_queued(self) -> bool:
        """Whether any request is waiting for admission (any thread)."""
        with self._lock:
            return bool(self.queue)

    def queue_snapshot(self) -> "list[_Request]":
        """Point-in-time copy of the submission queue, safe from any
        thread — serving front-ends read queued work (healthz, the
        estimated-cost gate) while the engine admits concurrently."""
        with self._lock:
            return list(self.queue)

    def submit(
        self, prompt: str | list[int], max_new_tokens: int = 32,
        prefix: str | None = None, temperature: float | None = None,
        top_p: float | None = None, top_k: int | None = None,
        presence_penalty: float = 0.0,
        frequency_penalty: float = 0.0, prefix_cache: bool = True,
        priority: int = 0, deadline: float | None = None,
        response_format: dict | None = None,
        logit_bias: dict | None = None,
        banned_tokens: list[int] | None = None,
        constraint: Any = None,  # pre-compiled constrain.TokenDFA — a
        #   serving front-end that already compiled OFF its event loop
        #   passes the automaton itself, closing the window where an LRU
        #   eviction between its compile and this submit would force a
        #   synchronous rebuild on the caller's thread
        tenant: str | None = None,  # multi-tenant QoS: the tenant this
        #   request bills against (weighted-fair admission order, virtual
        #   token counters, resident-row caps — runtime/scheduler.py
        #   TenantScheduler).  None = the anonymous bucket.
    ) -> int:
        """Queue a request.  ``temperature``/``top_p``/``top_k`` override
        the batcher's sampling config FOR THIS REQUEST (serving
        front-ends: per-request sampling in a shared batch; per-row top_k
        rides a traced mask, no recompile per value).  None keeps the
        config value.  ``presence_penalty``/``frequency_penalty`` (OpenAI
        semantics, [-2, 2]) adjust logits against this request's own
        output tokens before sampling.  ``prefix_cache=False`` opts this
        request out of AUTOMATIC prefix caching (its prompt is neither
        matched against nor published into the shared page cache).

        ``response_format`` constrains the OUTPUT to a grammar
        (``{"type": "json_schema", "json_schema": {...}}`` or
        ``{"type": "regex", "regex": ...}``): the constraint compiles to
        a token-mask automaton (runtime/constrain.py; LRU-cached per
        (constraint, tokenizer) pair) applied as a traced per-row mask
        inside the shared decode step — constrained and free rows share
        one compiled program, and free neighbors' outputs are
        byte-identical to a constraint-free batch.  ``logit_bias``
        (token id -> [-100, 100]) and ``banned_tokens`` ride the SAME
        mask mechanism.  Malformed constraints raise
        :class:`~.constrain.ConstraintError` (a ValueError) here, before
        anything is queued.

        ``priority`` orders admission (higher first; FIFO within a
        priority) and shields the row from preemption by lower-priority
        work.  ``deadline`` is an ABSOLUTE time.perf_counter() timestamp:
        a request still queued past it is shed (``shed[rid]`` records the
        reason, results stay empty) instead of admitted doomed —
        single-device only; multi-process meshes ignore deadlines (clocks
        diverge across hosts and the admission loop must stay lockstep)."""
        ids = (
            self.tokenizer.encode(prompt)
            if isinstance(prompt, str)
            else list(prompt)
        )
        if not ids:
            # admit_row would sample the "first token" from a pad position.
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if temperature is not None:
            import math

            if not (math.isfinite(temperature) and temperature >= 0.0):
                raise ValueError(f"temperature must be >= 0, got {temperature}")
            if self.speculative and temperature != self.sampling["temperature"]:
                raise ValueError(
                    "speculative batching samples with the engine-wide "
                    f"temperature ({self.sampling['temperature']}); "
                    "per-request overrides are not supported (the rejection "
                    "test warps target and draft with one static config)"
                )
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if (top_p is not None and self.speculative
                and top_p != self.sampling["top_p"]):
            raise ValueError(
                "speculative batching samples with the engine-wide top_p "
                f"({self.sampling['top_p']}); per-request overrides are "
                "not supported"
            )
        if top_k is not None:
            # Upper bound: the per-row override rides an int32 traced
            # scalar — an unbounded Python int would overflow jnp.int32 at
            # admission and crash the engine thread instead of 400-ing.
            if isinstance(top_k, bool) or not isinstance(top_k, int) \
                    or not 0 <= top_k <= 2**31 - 1:
                raise ValueError(
                    f"top_k must be an int in [0, 2**31), got {top_k!r}"
                )
            if self.speculative and top_k != self.sampling["top_k"]:
                raise ValueError(
                    "speculative batching samples with the engine-wide "
                    f"top_k ({self.sampling['top_k']}); per-request "
                    "overrides are not supported"
                )
            eff_t = (self.sampling["temperature"] if temperature is None
                     else temperature)
            if eff_t == 0.0:
                # A greedy row takes the argmax regardless of top_k;
                # dropping the no-op override keeps the static decode
                # program (the traced per-row mask pays a per-step [B, V]
                # sort for output that cannot change).
                top_k = None
        if not isinstance(prefix_cache, bool):
            raise ValueError(
                f"prefix_cache must be a bool, got {prefix_cache!r}"
            )
        if isinstance(priority, bool) or not isinstance(priority, int) \
                or not -(2**31) <= priority < 2**31:
            raise ValueError(
                f"priority must be an int in [-2**31, 2**31), got {priority!r}"
            )
        if tenant is not None and (
            not isinstance(tenant, str) or not tenant or len(tenant) > 64
        ):
            raise ValueError(
                f"tenant must be a non-empty string of <= 64 chars, "
                f"got {tenant!r}"
            )
        if deadline is not None:
            import math

            if isinstance(deadline, bool) \
                    or not isinstance(deadline, (int, float)) \
                    or not math.isfinite(float(deadline)):
                raise ValueError(
                    f"deadline must be a finite perf_counter timestamp, "
                    f"got {deadline!r}"
                )
            deadline = float(deadline)
        for name, pen in (("presence_penalty", presence_penalty),
                          ("frequency_penalty", frequency_penalty)):
            if not -2.0 <= pen <= 2.0:  # also rejects NaN/inf
                raise ValueError(f"{name} must be in [-2, 2], got {pen}")
        if (response_format is not None or logit_bias is not None
                or banned_tokens is not None or constraint is not None):
            if self.speculative:
                raise ValueError(
                    "speculative batching does not support constrained or "
                    "biased sampling (response_format/logit_bias/"
                    "banned_tokens) yet — the draft/verify chain would "
                    "need the mask on both models; serve constrained "
                    "traffic through a plain engine"
                )
            if constraint is None:
                # Compiles (or LRU-hits — serving front-ends pre-compile
                # off this thread and pass ``constraint=``) the request's
                # token-mask automaton; malformed input raises
                # ConstraintError (a ValueError) here, before anything is
                # queued.
                constraint = constrain_lib.compile_request(
                    response_format, logit_bias, banned_tokens,
                    tokenizer=self.tokenizer,
                    vocab_size=self.cfg.vocab_size, eos_id=self.eos_id,
                )
        # Presence/frequency penalties serve everywhere the batcher does:
        # single-device, speculative, and GSPMD dp/tp meshes (the [B, V]
        # histogram rides decode_chunk replicated, like the rest of the
        # scheduling state).
        pfx_len = 0
        if prefix is not None:
            if prefix not in self.prefixes:
                raise KeyError(f"unknown prefix {prefix!r} (register_prefix first)")
            pfx_len = len(self.prefixes[prefix].ids)
        if pfx_len + len(ids) + max_new_tokens > self.s:
            raise ValueError(
                f"prompt ({pfx_len}+{len(ids)} tokens) + {max_new_tokens} new "
                f"exceeds slot capacity {self.s}"
            )
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            self.queue.append(_Request(
                rid, ids, max_new_tokens, prefix=prefix,
                temperature=temperature, top_p=top_p, top_k=top_k,
                presence_penalty=float(presence_penalty),
                frequency_penalty=float(frequency_penalty),
                constraint=constraint,
                prefix_cache=prefix_cache, priority=priority,
                deadline=deadline, tenant=tenant,
            ))
        return rid

    def _drop_req_swap(self, req: "_Request") -> None:
        """Free a queued resume request's host swap parcel (cancel/shed:
        nothing will ever restore it)."""
        if req.swap_handle is not None and self.host_tier is not None:
            self.host_tier.drop_swap(req.swap_handle)
            req.swap_handle = None

    def cancel_row(self, rid: int) -> bool:
        """Cancel a submitted request (serving front-ends: client went away,
        or a stop sequence hit mid-row).  A queued request is dropped; an
        admitted row is deactivated and its slot freed for the next
        admission.  Either way ``results[rid]`` records whatever tokens had
        been committed (possibly none) and NO ``done=True`` callback fires
        for the rid — the canceller initiated this and already knows.

        Thread contract: call from ``run()``'s ``on_tokens`` callback
        (which executes between device chunks, on the thread driving
        ``run``) or while ``run()`` is not executing.  On a multi-process
        mesh every process must cancel the same rid in the same scheduling
        round, or the host scheduling mirrors diverge.

        Returns True if the rid was found queued or resident."""
        # Queue scan under the submission lock: a serving front-end may
        # append from its own thread mid-scan.
        dropped: _Request | None = None
        with self._lock:
            for req in self.queue:
                if req.rid == rid:
                    dropped = req
                    break
            if dropped is not None:
                self.queue.remove(dropped)
        if dropped is not None:
            # A preempted request waiting for recompute already emitted
            # (and streamed) a prefix — that IS its partial result.  A
            # swap-preempted one also frees its host parcel (nothing will
            # ever restore it — the tier audit would catch the leak).
            self._drop_req_swap(dropped)
            self.results[rid] = list(dropped.resume_emitted or [])
            self.result_logprobs[rid] = list(dropped.resume_lps or [])
            METRICS.inc("batcher.cancelled")
            return True
        for i in range(self.b):
            row = self.rows[i]
            if row.rid == rid:
                if self.eos_id >= 0 and self.eos_id in row.emitted:
                    cut = row.emitted.index(self.eos_id) + 1
                    row.emitted = row.emitted[:cut]
                    row.lps = row.lps[:cut]
                self.results[rid] = row.emitted
                self.result_logprobs[rid] = row.lps
                if row.pages:
                    self._release_pages(row.pages)
                    self.tables[i] = 0
                # A chunked prefill in flight just drops its transient row
                # cache — nothing was spliced into the shared cache yet.
                self._prefills.pop(i, None)
                if row.req is not None:
                    self.sched.note_freed(row.req, len(row.emitted))
                self.rows[i] = _RowState()
                self.active[i] = False
                self.budget[i] = 0
                # If the decode carry is device-resident (dispatch-ahead
                # in flight), the device still believes this row is
                # active — force a carry sync at the next chunk boundary
                # so the cancel takes effect there, exactly as it does on
                # the synchronous path.
                self._cancel_dirty = True
                METRICS.inc("batcher.cancelled")
                return True
        return False

    # -- scheduling loop ---------------------------------------------------

    def _split_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _free_slot(self) -> int | None:
        for i in range(self.b):
            if not self.active[i] and self.rows[i].rid is None:
                return i
        return None

    def _next_request(self) -> "_Request | None":
        """Admission order — the scheduler's ``admission_order`` hook,
        consulted under the submission lock (the serving loop thread
        appends concurrently).  Returns None on an empty queue."""
        with self._lock:
            return self.sched.admission_order(self.queue)

    def _unqueue(self, req: "_Request") -> None:
        """Remove an admitted request from the queue (identity compare —
        _Request is eq=False) under the submission lock.  This is the
        ONE admission-commit point (plain, chunked-start, and swap-
        restore paths all pass through it), so the scheduler's tenant
        accounting charges exactly once per residency here — the paired
        ``note_freed`` fires wherever the row later releases its slot
        (completion sweep, cancel, preemption)."""
        with self._lock:
            self.queue.remove(req)
        self.sched.note_admitted(req, len(req.ids) + req.max_new_tokens)

    def _shed_expired_queued(self) -> None:
        """Drop queued requests whose deadline has already passed: a
        request that cannot possibly deliver a token before its deadline
        must be SHED (the client gets 503 + Retry-After from the serving
        gateway) rather than admitted doomed — admitting it would burn a
        prefill plus pool pages on work nobody is waiting for.  A
        PREEMPTED request waiting for recompute is different: it already
        streamed tokens, so it finishes with that partial output (the
        serving layer's own deadline reports ``finish_reason: "timeout"``)
        — shedding it would discard delivered work and falsely tell the
        client a retry is safe.  Reads the INJECTED lockstep clock
        (``self._clock``, default perf_counter), never the wall clock
        directly — the graftsync GS101 contract for this declared
        decision (LOCKSTEP_DECISIONS).  Multi-process meshes still skip
        it outright: the default clock diverges across hosts, and the
        admission loop must stay lockstep unless the harness injected a
        deterministic clock AND owns the deadline semantics."""
        if self.pm is not None:
            return
        now = self._clock()
        # Collect expired requests under the submission lock, then deliver
        # OUTSIDE it: the on_tokens callback may re-enter this class
        # (serving's cancel sweep calls cancel_row), which takes the lock.
        expired: list[_Request] = []
        with self._lock:
            for req in list(self.queue):
                if req.deadline is None or req.deadline > now:
                    continue
                self.queue.remove(req)
                expired.append(req)
        for req in expired:
            self._drop_req_swap(req)
            self.results[req.rid] = list(req.resume_emitted or [])
            self.result_logprobs[req.rid] = list(req.resume_lps or [])
            if req.resume_emitted:
                # Mid-generation expiry (preempted, then the deadline
                # lapsed while requeued): finish with the tokens already
                # streamed — they ARE the response.
                METRICS.inc("batcher.cancelled")
                log.info(
                    "finished preempted request %d at deadline with %d "
                    "token(s)", req.rid, len(req.resume_emitted),
                )
            else:
                self.shed[req.rid] = "queue deadline expired before admission"
                METRICS.inc("batcher.shed_total")
                log.info("shed queued request %d (deadline expired)", req.rid)
            if self._on_tokens is not None:
                self._on_tokens(req.rid, [], True, None)

    # -- overload plane: preemption + on-demand growth (paged mode) --------

    def _pick_victim(self, below_priority: int | None = None) -> int | None:
        """Victim selection — the scheduler's ``select_victim`` hook over
        the preemptable rows.  Rows holding no pool pages (chunked
        prefills in flight) are excluded — preempting them frees nothing.
        INACTIVE rows are excluded too: a row that finished at admission
        (max_new_tokens == 1, or EOS as its first token) still holds rid
        and pages until _collect's publish sweep — preempting it would
        requeue a COMPLETED request with a fresh 1-token budget and emit
        a token past its max_tokens/EOS; its pages free at the chunk
        boundary anyway."""
        cands = [
            (i, r.priority, r.admit_seq) for i, r in enumerate(self.rows)
            if r.rid is not None and r.pages and self.active[i]
        ]
        return self.sched.select_victim(cands, below_priority=below_priority)

    def _preempt_row(self, i: int, reason: str) -> None:
        """Preempt resident row ``i``: free its pages NOW, keep the tokens
        it already emitted, and requeue it for RECOMPUTE — the resume
        request prefills prompt + emitted prefix (cheap when the automatic
        prefix cache still holds the prompt pages; a resume long enough to
        take the CHUNKED prefill path consults the cache too and chunks
        only the un-cached suffix) and its admission token
        continues the sequence, so at temperature 0 the reunited stream is
        token-identical to an unpreempted run (pinned by
        tests/runtime/test_overload.py)."""
        if self.faults is not None:
            # Injection site "batcher.preempt": one hit per preemption —
            # a "raise" rule crashes mid-preemption (the supervisor-restart
            # drill for this path); tests read rule.fired for determinism.
            self.faults.fire("batcher.preempt")
        row = self.rows[i]
        req = row.req
        pp = self._prefills.pop(i, None)
        if pp is not None or row.prefilling:
            # Chunked prefill in flight: nothing reached the pool yet —
            # drop the transient row cache and requeue the request as-is.
            resume = req
        else:
            prior = list(req.resume_emitted or [])
            base_ids = (req.ids[: len(req.ids) - len(prior)]
                        if prior else req.ids)
            resume = _Request(
                req.rid, list(base_ids) + list(row.emitted),
                max(1, row.remaining), prefix=req.prefix,
                temperature=req.temperature, top_p=req.top_p,
                top_k=req.top_k, presence_penalty=req.presence_penalty,
                frequency_penalty=req.frequency_penalty,
                # The compiled automaton rides the resume request; its
                # state rebuilds from the emitted prefix at re-admission
                # (TokenDFA.advance), so the reunited stream stays
                # byte-exact under the same masks.
                constraint=req.constraint,
                prefix_cache=req.prefix_cache, priority=req.priority,
                deadline=req.deadline, tenant=req.tenant,
                resume_emitted=list(row.emitted),
                resume_lps=list(row.lps),
            )
            # SWAP tier (host_pages): park the victim's raw pages on the
            # host instead of throwing the prefix away — restore scatters
            # them back (byte-exact, no recompute).  A dry host budget or
            # a kv.swap_out drill leaves swap_handle None and the request
            # takes the recompute path above unchanged.  The swap rung is
            # the scheduler's to declare: a policy without it sends every
            # victim straight to exact recompute.
            handle = (self._swap_out_row(i, row)
                      if "swap_preempt" in self.sched.pressure_rungs()
                      else None)
            if handle is not None:
                resume.swap_handle = handle
                resume.swap_pages = len(row.pages)
                resume.swap_last_tok = int(self.last_tok[i])
                resume.swap_pos = int(self.real_lens[i])
        freed = len(row.pages)
        if row.pages:
            self._release_pages(row.pages)
            self.tables[i] = 0
        # Tenant accounting: this residency ends (the requeued resume
        # re-charges at its own re-admission).
        self.sched.note_freed(req, len(row.emitted))
        self.rows[i] = _RowState()
        self.active[i] = False
        self.budget[i] = 0
        with self._lock:
            self.queue.append(resume)
        self.preemptions += 1
        METRICS.inc("batcher.preemptions_total")
        log.info(
            "preempted rid %d from slot %d (%s): freed %d page(s), "
            "%d token(s) kept for %s", resume.rid, i, reason, freed,
            len(resume.resume_emitted or []),
            "swap restore" if resume.swap_handle is not None else "recompute",
        )

    def _swap_out_row(self, i: int, row: "_RowState") -> int | None:
        """Try to park resident row ``i``'s raw pages in the host tier
        (swap-preemption).  Returns the parcel handle, or None to fall
        back to exact recompute (no tier, budget dry, or an injected
        kv.swap_out drop).  The device gather is dispatched here; the
        D2H copy runs on the tier's worker thread."""
        tier = self.host_tier
        if tier is None or not row.pages:
            return None
        rule = (self.faults.fire("kv.swap_out")
                if self.faults is not None else None)
        corrupt = False
        if rule is not None:
            if rule.action == "drop":
                METRICS.inc("batcher.kv_swaps.fallback")
                return None
            corrupt = rule.action == "corrupt"
        if not tier.can_fit(len(row.pages)):
            METRICS.inc("batcher.kv_swaps.fallback")
            return None
        payload = _export_pages_raw(
            self.cache, jnp.asarray(self._padded_page_list(row.pages))
        )
        handle = tier.park_swap(payload, len(row.pages), corrupt=corrupt)
        if handle is None:  # lost the budget race to nothing — advisory check
            METRICS.inc("batcher.kv_swaps.fallback")
            return None
        METRICS.inc("batcher.kv_swaps.out")
        return handle

    def _try_restore_swapped(self, i: int, req: "_Request") -> bool | None:
        """Restore a swap-preempted request into free slot ``i`` by
        scattering its parked raw pages back into freshly allocated pool
        pages — no model call, no token sampled: the row's device
        scheduling state is rebuilt verbatim and decode continues from
        ``swap_last_tok``, so the reunited stream is byte-exact against
        the never-preempted run at either KV width.

        Returns True on restore, False after degrading the request to
        exact recompute (parcel dropped/corrupted/missing — swap_handle
        cleared, request stays queued), None on back-pressure (nothing
        consumed; the caller stops admitting this round)."""
        tier = self.host_tier
        rule = (self.faults.fire("kv.swap_in")
                if self.faults is not None else None)
        if tier is None or (rule is not None and rule.action == "drop"):
            if tier is not None:
                tier.drop_swap(req.swap_handle)
            req.swap_handle = None
            METRICS.inc("batcher.kv_swaps.fallback")
            log.warning("swap restore for rid %d dropped; recomputing",
                        req.rid)
            return False
        corrupt = rule is not None and rule.action == "corrupt"
        n = req.swap_pages
        if not self._ensure_pages(n, "admit", below_priority=req.priority):
            return None  # parcel stays parked; retry next round
        payload = tier.take_swap(req.swap_handle, corrupt=corrupt)
        req.swap_handle = None
        if payload is None:
            METRICS.inc("batcher.kv_swaps.fallback")
            log.warning(
                "swap restore for rid %d failed verification; recomputing",
                req.rid,
            )
            return False
        self._unqueue(req)
        page_list = np.zeros((self.pages_per_row,), np.int32)
        pages = self._alloc_pages(n)
        page_list[:n] = pages
        self.tables[i] = page_list
        # The parcel was exported bucket-padded; scatter through the same
        # padded list (pad slots rewrite the scratch page — never read).
        self.cache = _import_pages_raw(
            self.cache, jnp.asarray(self._padded_page_list(pages)),
            *(jnp.asarray(a) for a in payload), pm=self.pm,
        )
        req_t = (self.sampling["temperature"] if req.temperature is None
                 else float(req.temperature))
        req_p = (self.sampling["top_p"] if req.top_p is None
                 else float(req.top_p))
        req_k = (self.sampling["top_k"] if req.top_k is None
                 else int(req.top_k))
        self.temp_row[i] = req_t
        self.topp_row[i] = req_p
        self.topk_row[i] = req_k
        self.pres_row[i] = req.presence_penalty
        self.freq_row[i] = req.frequency_penalty
        emitted = list(req.resume_emitted or [])
        if req.presence_penalty or req.frequency_penalty:
            # The penalty histogram must see every token this request has
            # emitted across residencies — identical rebuild to the
            # recompute-resume path in _activate_row.
            if self.tok_counts is None:
                self.tok_counts = jnp.zeros(
                    (self.b, self.cfg.vocab_size), jnp.int32
                )
            rowc = np.zeros((self.cfg.vocab_size,), np.int32)
            np.add.at(rowc, np.asarray(emitted, np.int64), 1)
            self.tok_counts = self.tok_counts.at[i].set(jnp.asarray(rowc))
        if req.constraint is not None:
            # Rebuild the row's automaton state by replaying the tokens it
            # already emitted — the state is a pure function of them, so a
            # swap-restored constrained row continues under the exact
            # masks the unpreempted run would have seen.
            self.dfa_row[i] = req.constraint.advance(0, emitted)
        if self.speculative:
            # Rebuild the DRAFT cache from prompt + emitted: the draft is
            # never swapped (small, quantized, contiguous) — one KV-only
            # prefill of the first swap_pos tokens restores exactly the
            # resident-KV invariant (the newest emitted token's KV is
            # written by the round that consumes it, for both caches), so
            # the reunited spec stream is byte-exact vs the never-
            # preempted run.  req.ids already holds prompt + emitted.
            seed_ids = req.ids[: req.swap_pos]
            td = min(_bucket(len(seed_ids)), self.s)
            dprompt = np.full((td,), self.pad_id, np.int32)
            dprompt[: len(seed_ids)] = seed_ids
            self.draft_cache = admit_row_kv(
                self.draft_params, self.draft_cfg, self.draft_cache,
                jnp.int32(i), jnp.asarray(dprompt),
                jnp.int32(len(seed_ids)),
            )
            self.spec_ema[i] = 1.0
        self.last_tok[i] = req.swap_last_tok
        self.real_lens[i] = req.swap_pos
        self.valid[i] = np.arange(self.valid.shape[1]) < req.swap_pos
        self.active[i] = True
        # No admission token on a swap restore: max_new_tokens IS the
        # remaining budget (set by _preempt_row from row.remaining).
        self.budget[i] = req.max_new_tokens
        self._admit_seq += 1
        self.rows[i] = _RowState(
            rid=req.rid, emitted=emitted, lps=list(req.resume_lps or []),
            remaining=req.max_new_tokens, pages=pages, req=req,
            priority=req.priority, admit_seq=self._admit_seq,
            streamed=len(emitted),
        )
        METRICS.inc("batcher.kv_swaps.in")
        log.info("restored swapped rid %d into slot %d (%d page(s))",
                 req.rid, i, n)
        return True

    def _ensure_pages(self, need: int, tag: str,
                      below_priority: int | None = None,
                      self_slot: int | None = None) -> bool:
        """THE pressure loop (one definition for admission, chunked-finish
        and growth): fire the ``batcher.page_alloc`` fault site (an
        ``exhaust`` rule simulates a dry pool), then preempt victims until
        :meth:`available` covers ``need`` pages.  ``below_priority``
        restricts victims to STRICTLY lower priority (the admission paths:
        a newcomer never preempts its own class, which would livelock two
        requests trading the same pages); ``self_slot`` is the growth
        path's fallback — with no other victim the grower itself yields
        (requeued for recompute so higher-priority residents keep their
        pages).  Returns True when ``need`` pages are obtainable (the
        caller allocs); False on back-pressure or self-preemption
        (nothing was allocated)."""
        rule = (self.faults.fire("batcher.page_alloc", tag=tag)
                if self.faults is not None else None)
        avail = (0 if rule is not None and rule.action == "exhaust"
                 else self._pages_available())
        while avail < need:
            v = self._pick_victim(below_priority=below_priority)
            if v is None:
                if self_slot is None:
                    return False
                v = self_slot  # no other victim: the grower itself yields
            self._preempt_row(
                v, "admission" if below_priority is not None else "growth"
            )
            if v == self_slot:
                return False
            avail = self._pages_available()
        return True

    def _host_restorable(self, digests: list[bytes], start: int,
                         cap: int) -> list[bytes]:
        """The consecutive digest run PAST the device-cached run whose
        pages are parked in the host spill tier — candidates for restore
        instead of re-prefill."""
        if self.host_tier is None:
            return []
        out: list[bytes] = []
        for d in digests[start:cap]:
            if not self.host_tier.has_spill(d):
                break
            out.append(d)
        return out

    def _match_tiered(self, digests: list[bytes], cap: int,
                      n_init: int = 0) -> list[int]:
        """The longest cached page run across BOTH tiers: alternate
        device matches (retained) and host-tier restores until the chain
        breaks.  LRU eviction reclaims a run's OLDEST (head) pages first,
        so the common spill shape is a host-parked head in front of a
        still-resident tail — a device-only match would miss the whole
        run.  Restores use spare capacity only (``n_init`` is what the
        admission itself still needs; restores never preempt live rows),
        and restored pages come back row-held + published, so a
        back-pressured caller releasing them simply parks them in the
        device LRU — addressable again, nothing leaks."""
        pc = self.prefix_cache
        pages = pc.match(digests[:cap])
        for p in pages:
            self._retain_page(p)
        k = len(pages)
        while k < cap:
            run = self._host_restorable(digests, k, cap)
            if not run:
                break
            spare = self._pages_available() - max(
                0, n_init - k - len(run)
            )
            restored = self._restore_spilled_run(run[: max(spare, 0)])
            if not restored:
                break
            pages += restored
            k += len(restored)
            if len(restored) < len(run):
                break
            more = pc.match(digests[k:cap])
            if not more:
                break
            for p in more:
                self._retain_page(p)
            pages += more
            k += len(more)
        return pages

    def _restore_spilled_run(self, run: list[bytes]) -> list[int]:
        """Scatter a host-spilled digest run back into freshly allocated
        pool pages and publish the digests — the pages come back exactly
        as they left (raw bytes), so a hit over them is byte-identical to
        a hit over never-evicted pages.  The caller guarantees pool
        availability (restores never preempt live rows: a cache restore
        must not evict live work).  Returns the restored page list (a
        prefix of ``run``; empty when a kv.spill restore drill drops it
        or verification fails on the first page)."""
        if not run:
            return []
        rule = (self.faults.fire("kv.spill", tag="restore")
                if self.faults is not None else None)
        if rule is not None and rule.action == "drop":
            return []
        payloads = []
        for d in run:
            got = self.host_tier.take_spill(d)
            if got is None:
                break
            payloads.append(got)
        if not payloads:
            return []
        pages = self._alloc_pages(len(payloads))
        # Stack the per-page parcels and pad BOTH the payload and the
        # destination list up the bucket ladder (pad slots target the
        # scratch page) — restore counts must not compile per width.
        padded = self._padded_page_list(pages)
        nb = padded.shape[0]

        def stack(j):
            s = np.stack([p[j] for p in payloads], axis=1)
            if nb > s.shape[1]:
                s = np.concatenate(
                    [s, np.zeros((s.shape[0], nb - s.shape[1]) + s.shape[2:],
                                 s.dtype)], axis=1,
                )
            return jnp.asarray(s)

        self.cache = _import_pages_raw(
            self.cache, jnp.asarray(padded),
            *(stack(j) for j in range(len(payloads[0]))), pm=self.pm,
        )
        for pg, d in zip(pages, run):
            self.pool.publish_prefix(pg, d)
        METRICS.inc("batcher.host_tier.restored_pages", len(pages))
        METRICS.inc("batcher.host_tier.hits")
        return pages

    def _reserve_row_pages(self, i, req, total_len, pfx):
        """Paged admission reservation, ON-DEMAND: pages for the prompt
        plus one decode page — NOT the full prompt+budget footprint (PR 1's
        policy), which left most reserved pages empty while the queue
        back-pressured.  The chunk-boundary growth loop (:meth:`_grow_rows`)
        allocates the rest only as the row actually reaches them.  A dry
        pool first evicts LRU-cold cached pages (inside alloc, spilling
        their content to the host tier first when one is armed), then
        preempts a STRICTLY lower-priority victim (swap-out when the host
        budget allows, exact recompute otherwise), then back-pressures.
        A prompt whose cached run was evicted to the HOST tier restores
        those pages here (no preemption — restores only use spare
        capacity) and counts them as cache hits.
        Returns (page_list, pages, cached_pages, cached_len, digests), or
        None on back-pressure (nothing allocated, hits released)."""
        blk = self.page_size
        n_full = -(-(total_len + req.max_new_tokens) // blk)
        n_init = min(n_full, -(-total_len // blk) + 1)
        pc = self.prefix_cache
        auto = pc is not None and pfx is None and req.prefix_cache
        cached_pages: list[int] = []
        cached_len = 0
        digests: list[bytes] = []
        if auto:
            # Hash every FULL prompt page (chained digests, memoized on
            # the request — a back-pressured admission retries every round
            # and must not rehash a long prompt each time); hits are
            # capped one page short of the whole prompt so at least one
            # real suffix token always prefills (the admission samples the
            # first token from its logits).  The match walks BOTH tiers
            # (device hits retained, host-spilled pages restored), so an
            # LRU-evicted head no longer hides a resident tail.
            if req.digests is None:
                req.digests = self._page_digests(
                    req.ids, len(req.ids) // blk
                )
            digests = req.digests
            cap = (len(req.ids) - 1) // blk
            cached_pages = self._match_tiered(digests, cap, n_init=n_init)
            cached_len = len(cached_pages) * blk
        need = n_init - len(cached_pages)
        if not self._ensure_pages(need, "admit", below_priority=req.priority):
            # Restored pages release to the device LRU (still
            # addressable); retained hits just drop our reference.
            self._release_pages(cached_pages)
            return None
        if auto:
            pc.record_lookup(cached_len, total_len - cached_len)
        # Build the table BEFORE allocating: the allocation is the last
        # thing that can raise, so no exception path exists between the
        # pool handing out pages and the row table owning them (graftflow
        # GF301 — the refcount-leak shape the pool audit only catches
        # after the fact).
        page_list = np.zeros((self.pages_per_row,), np.int32)
        page_list[: len(cached_pages)] = cached_pages
        pages = self._alloc_pages(need)
        page_list[len(cached_pages): n_init] = pages  # + scratch pad
        self.tables[i] = page_list
        return page_list, pages, cached_pages, cached_len, digests

    def _grow_rows(self) -> None:
        """Chunk-boundary page growth (paged mode): before each decode
        chunk, every active row that will write past its allocated pages
        this chunk gets the missing pages — evicting LRU-cold cached pages
        first, then preempting the lowest-priority / most-recently-admitted
        victim (possibly the growing row itself: it requeues for recompute
        and higher-priority residents keep their pages)."""
        blk = self.page_size
        for i in range(self.b):
            row = self.rows[i]
            if row.rid is None or not self.active[i] or row.prefilling:
                continue
            if self.speculative:
                # The verify window writes slots real_lens..real_lens+k
                # REGARDLESS of budget (rollback clamps commits, not
                # writes) — pages must cover the whole window before the
                # round dispatches, exactly the contiguous engine's
                # headroom contract.
                horizon = int(self.real_lens[i]) + self.spec_k + 1
            else:
                horizon = int(self.real_lens[i]) + min(
                    self.chunk_steps, int(self.budget[i])
                )
            need_pages = -(-horizon // blk)
            have = len(row.pages)
            if need_pages <= have:
                continue
            n = need_pages - have
            # The fault site (tag "grow") fires only when a row actually
            # needs new pages, so rule windows count real allocation
            # attempts.
            if not self._ensure_pages(n, "grow", self_slot=i):
                continue  # the grower itself was preempted
            fresh = self._alloc_pages(n)
            row.pages.extend(fresh)
            self.tables[i][have:need_pages] = fresh
            METRICS.inc("batcher.pages_grown", n)

    def _admit_pending(self) -> None:
        if self.faults is not None:
            # Injection site "batcher.admit": one hit per admission round.
            self.faults.fire("batcher.admit")
        # Adopt handed-off KV pages FIRST: a transfer that raced this
        # round's admissions should be matchable by them.  Then serve
        # cross-replica export requests — after imports, so a freshly
        # landed run is immediately re-exportable.
        self._drain_kv_imports()
        self._drain_kv_exports()
        self._shed_expired_queued()
        # Advance pending chunked prefills.  ALTERNATE: one serialized
        # prefill_chunk_step bite per prefill per round (up to
        # prefill_concurrency * prefill_chunk stall tokens).  MIXED:
        # while decode rows are live, bites ride the fused span instead
        # (_decode_span), so only completed prompts run their finishing
        # splice here; with no decode rows live the classic advance
        # runs.  Re-evaluated per slot: a finishing splice earlier in
        # this loop activates a decode row, and later bites must then
        # ride the span, not stall it.
        for slot in list(self._prefills):
            fused = self.sched.fuse_prefill() and bool(self.active.any())
            self._advance_chunk(slot, advance=not fused)
        while True:
            i = self._free_slot()
            if i is None:
                return
            req = self._next_request()
            if req is None:
                return
            if req.swap_handle is not None:
                # Swap-preempted resume: scatter the parked pages back
                # instead of recomputing the prefix.  True = restored
                # (next loop iteration admits more); False = the parcel
                # was unusable and the request fell through to recompute
                # (still queued, swap_handle cleared — re-selected next
                # iteration); None = back-pressure, stop this round.
                got = self._try_restore_swapped(i, req)
                if got is None:
                    return
                continue
            pfx = self.prefixes[req.prefix] if req.prefix is not None else None
            pfx_len = len(pfx.ids) if pfx else 0
            total_len = pfx_len + len(req.ids)
            thr = self.sched.chunk_threshold()
            if thr is not None and len(req.ids) > thr:
                if len(self._prefills) >= self.prefill_concurrency:
                    # Prefill slots full, and strict admission order: stop
                    # admitting (the selected request never gets jumped).
                    return
                self._unqueue(req)
                self._start_chunked(i, req, pfx)
                continue
            pages: list[int] = []
            cached_pages: list[int] = []
            cached_len = 0
            digests: list[bytes] = []
            if self.paged:
                got = self._reserve_row_pages(i, req, total_len, pfx)
                if got is None:
                    # Dry pool with no preemptable victim: back-pressure.
                    # The request stays queued (never removed), admission
                    # stops for this round.
                    return
                page_list, pages, cached_pages, cached_len, digests = got
            self._unqueue(req)
            # Bucket for compile reuse, but never past what fits after the
            # prefix: forward's contract is cache_index + T <= max_len, and
            # dynamic_update_slice CLAMPS an overflowing start — the suffix
            # K/V would land misaligned with its mask/positions, silently
            # corrupting the row.  (submit() guaranteed the real prompt fits.)
            tp = min(_bucket(len(req.ids)), self.s - pfx_len)
            prompt = np.full((tp,), self.pad_id, np.int32)
            prompt[: len(req.ids)] = req.ids
            # Per-request sampling: traced scalar overrides (no recompile
            # per value) only when the request diverges from the config.
            req_t = (self.sampling["temperature"] if req.temperature is None
                     else float(req.temperature))
            req_p = (self.sampling["top_p"] if req.top_p is None
                     else float(req.top_p))
            req_k = (self.sampling["top_k"] if req.top_k is None
                     else int(req.top_k))
            custom = (req_t != self.sampling["temperature"]
                      or req_p != self.sampling["top_p"]
                      or req_k != self.sampling["top_k"])
            extra = (
                dict(temp_req=jnp.float32(req_t), topp_req=jnp.float32(req_p))
                if custom else {}
            )
            if custom and req_k != self.sampling["top_k"]:
                extra["topk_req"] = jnp.int32(req_k)
            if req.constraint is not None:
                # The first output token draws under the automaton's
                # start-state mask (a resumed request replays its emitted
                # prefix to recover the state first).
                st0 = req.constraint.advance(0, req.resume_emitted or [])
                extra["mask_req"] = jnp.asarray(req.constraint.bias[st0])
            if self.paged and pfx is not None:
                self.cache, tok, lp = admit_row_with_prefix_paged(
                    self.params, self.cfg, self.cache, jnp.asarray(page_list),
                    pfx.k, pfx.v, jnp.int32(pfx_len),
                    jnp.asarray(prompt), jnp.int32(len(req.ids)),
                    self._split_rng(), pm=self.pm, **self.sampling, **extra,
                )
                row_valid = np.arange(self.valid.shape[1]) < total_len
            elif self.paged and cached_len:
                # Prefix-cache HIT: the cached run seeds the row through a
                # pool gather; only the suffix prefills.  Writes for the
                # cached positions are routed to the scratch page — shared
                # pages are read-only while any row references them.
                write_list = page_list.copy()
                write_list[: len(cached_pages)] = 0
                suffix = req.ids[cached_len:]
                tc = min(_bucket(len(suffix)), self.s - cached_len)
                chunk = np.full((tc,), self.pad_id, np.int32)
                chunk[: len(suffix)] = suffix
                self.cache, tok, lp = admit_row_auto_paged(
                    self.params, self.cfg, self.cache,
                    jnp.asarray(page_list), jnp.asarray(write_list),
                    jnp.int32(cached_len), jnp.asarray(chunk),
                    jnp.int32(len(suffix)), self._split_rng(),
                    pm=self.pm, **self.sampling, **extra,
                )
                row_valid = np.arange(self.valid.shape[1]) < total_len
            elif self.paged:
                self.cache, tok, lp = admit_row_paged(
                    self.params, self.cfg, self.cache, jnp.asarray(page_list),
                    jnp.asarray(prompt), jnp.int32(len(req.ids)),
                    self._split_rng(), pm=self.pm, **self.sampling, **extra,
                )
                row_valid = np.arange(self.valid.shape[1]) < total_len
            elif pfx is not None:
                self.cache, tok, row_valid, lp = admit_row_with_prefix(
                    self.params, self.cfg, self.cache, jnp.int32(i),
                    pfx.k, pfx.v, jnp.int32(pfx_len),
                    jnp.asarray(prompt), jnp.int32(len(req.ids)),
                    self._split_rng(), pm=self.pm, **self.sampling, **extra,
                )
            else:
                self.cache, tok, row_valid, lp = admit_row(
                    self.params, self.cfg, self.cache, jnp.int32(i),
                    jnp.asarray(prompt), jnp.int32(len(req.ids)),
                    self._split_rng(), pm=self.pm, **self.sampling, **extra,
                )
            if digests:
                # Publish the row's full prompt pages (first writer wins;
                # a digest another page already holds leaves ours private).
                # Pages inside the cached run are already published; the
                # fresh ones now hold exactly the hashed content — the
                # admission scatter just wrote it.
                for j in range(len(cached_pages), len(digests)):
                    self.pool.publish_prefix(int(page_list[j]), digests[j])
            if self.speculative:
                # Seed the DRAFT cache for this row: full prompt (prefix
                # caching stores only target KV, so the draft prefills
                # prefix + suffix; bucketed for compile reuse).
                full_ids = (pfx.ids if pfx else []) + req.ids
                td = min(_bucket(len(full_ids)), self.s)
                dprompt = np.full((td,), self.pad_id, np.int32)
                dprompt[: len(full_ids)] = full_ids
                self.draft_cache = admit_row_kv(
                    self.draft_params, self.draft_cfg, self.draft_cache,
                    jnp.int32(i), jnp.asarray(dprompt),
                    jnp.int32(len(full_ids)),
                )
            self._activate_row(i, req, tok, lp, row_valid, total_len,
                               req_t, req_p, cached_pages + pages,
                               req_k=req_k, cached_len=cached_len)

    def _activate_row(self, i, req, tok, lp, row_valid, total_len,
                      req_t, req_p, pages, req_k=None, cached_len=0):
        """Host bookkeeping tail of EVERY admission (monolithic and
        chunked): record the sampled first token, arm the row's scheduling
        state, stream the token."""
        tok = int(tok)  # replicated scalar — identical on every process
        self.last_tok[i] = tok
        self.spec_ema[i] = 1.0  # fresh rows draft the full k (optimistic)
        if req.constraint is not None:
            # Automaton state after the admission token: replay (resumed
            # prefix +) the token on the host — the state is a pure
            # function of the emitted stream.
            self.dfa_row[i] = req.constraint.advance(
                0, list(req.resume_emitted or []) + [tok]
            )
            METRICS.inc("batcher.constrain.rows")
        else:
            self.dfa_row[i] = 0
        self.temp_row[i] = req_t
        self.topp_row[i] = req_p
        self.topk_row[i] = (self.sampling["top_k"] if req_k is None
                            else req_k)
        self.pres_row[i] = req.presence_penalty
        self.freq_row[i] = req.frequency_penalty
        if self.prefix_cache is not None:
            self.prefix_cached_tokens[req.rid] = cached_len
        prior = list(req.resume_emitted or [])
        prior_lps = list(req.resume_lps or [])
        if req.presence_penalty or req.frequency_penalty:
            if self.tok_counts is None:
                self.tok_counts = jnp.zeros(
                    (self.b, self.cfg.vocab_size), jnp.int32
                )
            if prior:
                # Resumed after preemption: the penalty histogram must see
                # every token THIS request has emitted across residencies,
                # or the recompute would sample from differently-penalized
                # logits than the unpreempted run.
                rowc = np.zeros((self.cfg.vocab_size,), np.int32)
                np.add.at(rowc, np.asarray(prior + [tok], np.int64), 1)
                self.tok_counts = self.tok_counts.at[i].set(
                    jnp.asarray(rowc)
                )
            else:
                self.tok_counts = _reset_count_row(
                    self.tok_counts, jnp.int32(i), jnp.int32(tok)
                )
        self.real_lens[i] = total_len
        self.valid[i] = np.asarray(row_valid)
        self.active[i] = True
        # The first token came out of admission; the row may emit
        # budget-1 more from decode chunks.
        self.budget[i] = req.max_new_tokens - 1
        self._admit_seq += 1
        self.rows[i] = _RowState(
            rid=req.rid, emitted=prior + [tok], lps=prior_lps + [float(lp)],
            remaining=req.max_new_tokens - 1, pages=pages,
            req=req, priority=req.priority, admit_seq=self._admit_seq,
        )
        log.debug("admitted request %d into slot %d", req.rid, i)
        if req.max_new_tokens == 1 or tok == self.eos_id:
            self.active[i] = False
        if self._on_tokens is not None:
            # Stream the admission token; completion (done=True) is
            # always announced by _collect's publish sweep.  State
            # advances BEFORE the callback so a raising callback can
            # never cause a re-delivery on a later run().  A resumed row's
            # prior tokens were streamed in its previous residency —
            # streamed starts past them, so nothing re-delivers.
            self.rows[i].streamed = len(prior) + 1
            self._on_tokens(req.rid, [tok], False, [float(lp)])
        METRICS.inc("batcher.admitted")

    # -- chunked prefill ---------------------------------------------------

    def _start_chunked(self, i: int, req: _Request, pfx) -> None:
        """Reserve slot ``i`` and begin a chunked prefill (first chunk runs
        this round).  Prefix-cached requests seed the transient row with a
        COPY of the registered prefix KV — one copy up front makes the
        buffers exclusively ours, so every chunk step can donate them
        (update in place) instead of copying the row cache per chunk.

        AUTOMATIC prefix caching composes too (closes the PR-3 TODO): the
        prompt's full pages are content-hashed, the longest cached run is
        retained and gathered out of the pool into the transient row ONCE,
        and only the un-cached suffix chunks through the model — the same
        continuation math as the monolithic cache-hit admission, so tokens
        stay temp-0 identical while a long shared prompt skips most of its
        chunked prefill.  Hits are capped one page short of the prompt so
        at least one real token prefills (the finish samples the first
        token from its logits)."""
        cached_pages: list[int] = []
        cached_len = 0
        digests: list[bytes] = []
        pc = self.prefix_cache
        if pfx is not None:
            row_k, row_v, done = jnp.copy(pfx.k), jnp.copy(pfx.v), len(pfx.ids)
            total_len = done + len(req.ids)
        else:
            total_len = len(req.ids)
            if pc is not None and req.prefix_cache:
                blk = self.page_size
                if req.digests is None:
                    req.digests = self._page_digests(
                        req.ids, len(req.ids) // blk
                    )
                digests = req.digests
                cap = (len(req.ids) - 1) // blk
                # Tiered match: device hits are retained for the WHOLE
                # prefill (eviction must never reclaim a run the pending
                # chunks continue from) and host-spilled pages restore
                # with spare capacity (a chunked prefill holds no pool
                # pages of its own yet) — the pending chunks then start
                # past them, exactly as if they had never been evicted.
                cached_pages = self._match_tiered(digests, cap)
                cached_len = len(cached_pages) * blk
                pc.record_lookup(cached_len, total_len - cached_len)
            if cached_pages:
                read_list = np.zeros((self.pages_per_row,), np.int32)
                read_list[: len(cached_pages)] = cached_pages
                row_k, row_v = _gather_row_pages(
                    self.cache, jnp.asarray(read_list)
                )
                done = cached_len
            else:
                rc = model_lib.init_cache(self.cfg, 1, self.s,
                                          dtype=_row_dtype_of(self.cache))
                row_k, row_v, done = rc.k, rc.v, 0
        self._admit_seq += 1
        # The reserving row holds the cached pages so cancel_row /
        # _preempt_row release them and the pool audit sees the references
        # (a prefilling row stays inactive, so it is never a victim).
        self.rows[i] = _RowState(rid=req.rid, prefilling=True,
                                 remaining=req.max_new_tokens,
                                 req=req, priority=req.priority,
                                 admit_seq=self._admit_seq,
                                 pages=list(cached_pages))
        self._prefills[i] = _PendingPrefill(
            req=req, row_k=row_k, row_v=row_v, done=done,
            ids=list(req.ids), total_len=total_len,
            cached_pages=cached_pages, cached_len=cached_len,
            digests=digests,
        )
        # Alternate runs the first bite NOW (serialized); mixed defers it
        # to the fused span whenever decode rows are live to stall.
        self._advance_chunk(
            i, advance=not (self.sched.fuse_prefill()
                            and bool(self.active.any())),
        )

    def _advance_chunk(self, i: int, advance: bool = True) -> None:
        """Consume one scheduler-sized bite of slot ``i``'s pending
        prompt (``advance=False`` — the mixed policy's fused span already
        runs the bites on device — only checks for the finishing splice);
        finish the admission when the prompt completes.  In paged mode
        the finish ALLOCATES the row's pages on demand (prompt + one
        decode page) — a dry pool preempts a strictly-lower-priority
        victim, else the finish retries next round (the prefilled transient
        row is kept; no work is lost)."""
        pp = self._prefills[i]
        if advance and pp.done < pp.total_len:
            pfx_len = pp.total_len - len(pp.ids)
            clen = self._clamp_bite(
                pp.done,
                self.sched.prefill_bite(pp.total_len - pp.done,
                                        int(self.active.sum())),
                pp.total_len,
            )
            off = pp.done - pfx_len
            # Bucket for compile reuse, capped so cache_index + T <= width
            # (forward's contract; dynamic_update_slice clamps overflows).
            tc = min(_bucket(clen), self.s - pp.done)
            chunk = np.full((tc,), self.pad_id, np.int32)
            chunk[:clen] = pp.ids[off: off + clen]
            pp.row_k, pp.row_v, pp.last_logits = prefill_chunk_step(
                self.params, self.cfg, pp.row_k, pp.row_v, jnp.int32(pp.done),
                jnp.asarray(chunk), jnp.int32(clen), pm=self.pm,
            )
            pp.done += clen
            METRICS.inc("batcher.prefill_chunks")
            METRICS.inc("batcher.sched.prefill_tokens", clen)
            if bool(self.active.any()):
                # Live decode rows just waited out this serialized prefill
                # forward — the alternating loop's inter-token-latency
                # spike the mixed schedule exists to remove (it keeps
                # this counter at zero by fusing the bite instead).
                METRICS.inc("batcher.sched.stall_rounds")
        if pp.done < pp.total_len:
            return
        req = pp.req
        req_t = (self.sampling["temperature"] if req.temperature is None
                 else float(req.temperature))
        req_p = (self.sampling["top_p"] if req.top_p is None
                 else float(req.top_p))
        req_k = (self.sampling["top_k"] if req.top_k is None
                 else int(req.top_k))
        custom = (req_t != self.sampling["temperature"]
                  or req_p != self.sampling["top_p"]
                  or req_k != self.sampling["top_k"])
        extra = (
            dict(temp_req=jnp.float32(req_t), topp_req=jnp.float32(req_p))
            if custom else {}
        )
        if custom and req_k != self.sampling["top_k"]:
            extra["topk_req"] = jnp.int32(req_k)
        if req.constraint is not None:
            # Same first-token masking as the monolithic admissions.
            st0 = req.constraint.advance(0, req.resume_emitted or [])
            extra["mask_req"] = jnp.asarray(req.constraint.bias[st0])
        if self.paged:
            blk = self.page_size
            n_cached = len(pp.cached_pages)
            n_full = -(-(pp.total_len + req.max_new_tokens) // blk)
            n_init = min(n_full, -(-pp.total_len // blk) + 1)
            if not self._ensure_pages(n_init - n_cached, "admit",
                                      below_priority=req.priority):
                return  # retry the finish next round; prefill is kept
            # Table first, allocation last (graftflow GF301): nothing
            # between the pool handing out pages and the table owning
            # them may raise.
            page_list = np.zeros((self.pages_per_row,), np.int32)
            page_list[:n_cached] = pp.cached_pages
            pages = self._alloc_pages(n_init - n_cached)
            page_list[n_cached:n_init] = pages
            self.tables[i] = page_list
            # Cache-hit positions scatter to the scratch page: the shared
            # pages already hold exactly that KV and other rows may be
            # reading them (same write routing as admit_row_auto_paged).
            write_list = page_list.copy()
            write_list[:n_cached] = 0
            self.cache, tok, lp = finish_chunked_admission_paged(
                self.cache, jnp.asarray(write_list), pp.row_k, pp.row_v,
                pp.last_logits, self._split_rng(), pm=self.pm,
                **self.sampling, **extra,
            )
            # Publish the freshly-written full prompt pages (first writer
            # wins) — the cached run is already published.
            for j in range(n_cached, len(pp.digests)):
                self.pool.publish_prefix(int(page_list[j]), pp.digests[j])
            row_valid = np.arange(self.s) < pp.total_len
            pages = pp.cached_pages + pages
        else:
            pages = []
            self.cache, tok, row_valid, lp = finish_chunked_admission(
                self.cfg, self.cache, jnp.int32(i), pp.row_k, pp.row_v,
                pp.last_logits, jnp.int32(pp.total_len), self._split_rng(),
                pm=self.pm, **self.sampling, **extra,
            )
        del self._prefills[i]
        self._activate_row(i, req, tok, lp, row_valid, pp.total_len,
                           req_t, req_p, pages=pages, req_k=req_k,
                           cached_len=pp.cached_len)

    def _collect(
        self, toks: np.ndarray, was_active: np.ndarray,
        counts: np.ndarray | None = None, lps: np.ndarray | None = None,
        active_host: np.ndarray | None = None,
    ) -> None:
        # ``active_host``: the post-chunk activity vector.  The dispatch-
        # ahead path passes the fetched chunk output directly (the host
        # mirrors are stale while the carry is device-resident); the
        # synchronous path leaves it None and reads the freshly-synced
        # mirror, exactly as before.
        for i in range(self.b):
            row = self.rows[i]
            if row.rid is None or not was_active[i]:
                continue
            # Speculative rounds emit a VARIABLE count per row; columns past
            # counts[i] are padding, not tokens (a legit pad-id token inside
            # the count still collects).  decode_chunk's fixed-step output
            # keeps the remaining-guarded full sweep.
            row_toks = toks[i] if counts is None else toks[i][: counts[i]]
            for j, t in enumerate(row_toks):
                if row.remaining <= 0:
                    break
                t = int(t)
                row.emitted.append(t)
                if lps is not None:
                    row.lps.append(float(lps[i][j]))
                row.remaining -= 1
                if t == self.eos_id:
                    break
        # Rows that finished this chunk publish their result and free up.
        # (Chunked prefills in flight are inactive but NOT finished.)
        if active_host is None:
            active_host = self.active
        for i in range(self.b):
            row = self.rows[i]
            if row.rid is not None and not active_host[i] and not row.prefilling:
                # Trim anything emitted past the row's EOS.
                if self.eos_id >= 0 and self.eos_id in row.emitted:
                    cut = row.emitted.index(self.eos_id) + 1
                    row.emitted = row.emitted[:cut]
                    row.lps = row.lps[:cut]
                self.results[row.rid] = row.emitted
                self.result_logprobs[row.rid] = row.lps
                rid, final = row.rid, row.emitted[row.streamed:]
                if row.pages:  # paged: drop the row's page references
                    self._release_pages(row.pages)
                    self.tables[i] = 0
                final_lps = row.lps[row.streamed:]
                if row.req is not None:  # tenant true-up at completion
                    self.sched.note_freed(row.req, len(row.emitted))
                self.rows[i] = _RowState()
                METRICS.inc("batcher.completed")
                if self._on_tokens is not None:
                    # Final delivery: whatever landed since the last stream
                    # (possibly nothing), with done=True exactly once.  Row
                    # state is already reset, so a raising callback cannot
                    # cause a duplicate done on a later run().
                    self._on_tokens(rid, final, True, final_lps)
        if self._on_tokens is not None:
            # Still-active rows stream this chunk's new tokens (streamed
            # advances before the callback — same raise-safety).
            for i in range(self.b):
                row = self.rows[i]
                if row.rid is not None and len(row.emitted) > row.streamed:
                    new = row.emitted[row.streamed:]
                    new_lps = row.lps[row.streamed:]
                    row.streamed = len(row.emitted)
                    self._on_tokens(row.rid, new, False, new_lps)

    def run(self, on_tokens=None) -> dict[int, list[int]]:
        """Drive until every submitted request has a result.

        ``on_tokens(rid, new_tokens, done, logprobs)`` streams
        incrementally: called with each request's newly committed token ids
        as scheduling chunks complete (admission token first, then
        per-chunk), and exactly once with ``done=True`` carrying any final
        tokens — the concatenation of all deliveries for a rid equals its
        entry in the returned dict.  ``logprobs`` aligns 1:1 with
        ``new_tokens`` (raw-distribution chosen-token logprobs — in
        speculative mode gathered from the verify pass's logits, identical
        to the plain batcher's at temperature 0).
        Exceptions from the callback propagate (and abort the run).

        With ``overlap`` on (the default) the loop dispatches ahead:
        while no scheduling work is pending, chunk N+1 runs on device
        concurrently with chunk N's host work (callbacks included), so a
        callback observes each chunk one dispatch later than the
        synchronous loop would — the token STREAM per rid is unchanged,
        and temp-0 bytes are identical either way.  After ``run`` raises
        (an injected crash, a callback exception) the host scheduling
        mirrors may be stale; recover through :meth:`respawn`, the
        supervisor contract.
        """
        self._on_tokens = on_tokens
        try:
            return self._run_loop()
        finally:
            self._on_tokens = None

    def _run_loop(self) -> dict[int, list[int]]:
        # Publish any 1-token requests finished by admission alone.
        self._t_complete = None  # device-gap timing: a fresh run's first
        #                          chunk follows no observed completion
        while self.has_queued() or bool(self.active.any()) or any(
            r.rid is not None for r in self.rows
        ) or self.has_kv_imports() or self.has_kv_exports():
            self._admit_pending()
            if self.paged:
                # Chunk-boundary growth: rows about to write past their
                # allocated pages get them NOW (or preempt / yield) — the
                # decode chunk below must never scatter a live row's KV
                # into the scratch page.  (Occupancy gauges are published
                # at /metrics scrape time, not here: the decode loop is
                # the latency-critical path.)
                self._grow_rows()
            was_active = self.active.copy()
            if not was_active.any():
                self._t_complete = None  # idle boundary: no chunk to gap
                self._collect(
                    np.zeros((self.b, 0), np.int32), was_active
                )
                if not self.has_queued() and not self.has_kv_imports() \
                        and not self.has_kv_exports() \
                        and all(r.rid is None for r in self.rows):
                    break
                continue
            self._decode_span(was_active)
        return dict(self.results)

    # -- dispatch-ahead decode (the overlap plane) -------------------------

    def _span_plan(self) -> dict:
        """The traced-argument plan for ONE decode span, decided once from
        the (fresh) host mirrors at span start and reused for every chunk
        the span dispatches ahead: the per-row sampling / penalty kwargs
        select which COMPILED PROGRAM runs, and a dispatched-ahead chunk
        must reuse the first chunk's program (graftcheck GC4 pins the
        chained decode to one compile key).  Row sampling state only
        changes at admission — a span never admits — so the snapshot stays
        valid for the whole span; a row finishing mid-span merely keeps
        the (correct, slightly wider) program engaged until the sync."""
        plan: dict = {
            "tables": jnp.asarray(self.tables) if self.paged else None,
            "constrain": None,
        }
        self._tables_dirty = False  # plan holds the current snapshot
        pen_live = self.active & (
            (self.pres_row != 0.0) | (self.freq_row != 0.0)
        )
        # Penalized path only while a penalized row is live — the
        # all-default batch keeps the smaller static program.
        plan["counts"] = bool(pen_live.any())
        if self.speculative:
            per_spec = {}
            if plan["counts"]:
                per_spec["pres_row"] = jnp.asarray(self.pres_row)
                per_spec["freq_row"] = jnp.asarray(self.freq_row)
            # The adaptive k_row clamp is NOT part of the span-frozen
            # plan: it is a traced [B] input (values never touch the
            # compile key — graftcheck GC4 batcher.spec_chunk_paged), so
            # _dispatch_chunk re-plans it per dispatch from the freshest
            # EMA mirrors and ``k_hist`` pairs each dispatched clamp with
            # its fetch (FIFO — chunks fetch in dispatch order) for the
            # acceptance accounting.
            plan["k_hist"] = deque()
            plan["per_spec"] = per_spec
        else:
            # Per-row sampling path only while a custom-sampled row is
            # live: the all-default batch keeps the static program
            # (greedy compiles to a bare argmax — no per-step vocab
            # sort paid for traffic that never asked for sampling).
            rows_live = self.active & (
                (self.temp_row != self.sampling["temperature"])
                | (self.topp_row != self.sampling["top_p"])
                | (self.topk_row != self.sampling["top_k"])
            )
            per_row = {}
            if bool(rows_live.any()):
                per_row["temp_row"] = jnp.asarray(self.temp_row)
                if not bool((self.topp_row[self.active] == 1.0).all()):
                    # All-1.0 top_p skips the per-step [B, V] sort+
                    # softmax+cumsum mask entirely (sample_rows takes
                    # the static keep-everything path).
                    per_row["topp_row"] = jnp.asarray(self.topp_row)
                if not bool((
                    self.topk_row[self.active] == self.sampling["top_k"]
                ).all()):
                    # Engaged only while a row's top_k diverges from
                    # the engine-wide static value — the traced mask
                    # pays a per-step [B, V] sort the static path
                    # doesn't.
                    per_row["topk_row"] = jnp.asarray(self.topk_row)
            if plan["counts"]:
                per_row["pres_row"] = jnp.asarray(self.pres_row)
                per_row["freq_row"] = jnp.asarray(self.freq_row)
            # Constrained structured output: stack the live rows' token
            # automata into ONE (bias, next) pair the decode step gathers
            # from; the per-row state vector rides the DEVICE carry
            # (self._dfa_carry — _dispatch_chunk chains it chunk to
            # chunk) and syncs back to the dfa_row mirrors at span end.
            # The state axis pads up the shared bucket ladder so the
            # compile key is independent of the live schema mix.
            con = [
                i for i in range(self.b)
                if self.active[i] and self.rows[i].req is not None
                and self.rows[i].req.constraint is not None
            ]
            if con:
                # Memo key: (slot, rid) per constrained row.  rids are
                # minted monotonically and a row's constraint is fixed
                # for its whole residency, so the pair identifies the
                # stacked automata exactly — and deterministically,
                # unlike the id()-based key this replaces (object
                # addresses diverge across lockstep processes; graftsync
                # GS101 audits _span_plan as a declared decision).
                key = tuple(
                    (i, self.rows[i].rid) for i in con
                )
                if self._con_stack is None or self._con_stack[0] != key:
                    dfas = [self.rows[i].req.constraint for i in con]
                    total = 1 + sum(d.n_states for d in dfas)
                    bias, nxt, offs = constrain_lib.build_stack(
                        dfas, self.cfg.vocab_size,
                        pad_states_to=_bucket(total),
                    )
                    # The memo keeps the automata alongside the device
                    # tables (the rid key no longer needs an id pin;
                    # they document what the stack was built from).
                    self._con_stack = (
                        key, jnp.asarray(bias), jnp.asarray(nxt), offs,
                        dfas,
                    )
                _, bias_j, nxt_j, offs, _dfas = self._con_stack
                abs_state = np.zeros((self.b,), np.int32)
                for off, i in zip(offs, con):
                    abs_state[i] = off + int(self.dfa_row[i])
                per_row["mask_stack"] = bias_j
                per_row["next_stack"] = nxt_j
                self._dfa_carry = jnp.asarray(abs_state)
                plan["constrain"] = [
                    (i, off, self.rows[i].rid) for off, i in zip(offs, con)
                ]
            else:
                # Constrained traffic drained: release the memoized stack
                # (device tables + pinned automata) — it rebuilds on the
                # next constrained span at the same cost it was built.
                self._con_stack = None
            plan["per_row"] = per_row
        # Fused token-budget step (schedule=mixed): the HEAD pending
        # prefill rides every chunk this span dispatches; bites are
        # sized per dispatch against the span-start live row count.
        # "Head" = the FIRST (start-order) prefill with prompt work left
        # — a completed head whose finishing splice is back-pressured
        # must not starve a later prefill of its bites (the finish
        # itself retries at the round boundaries the prefill_finish
        # sync trigger forces).
        plan["n_active"] = int(self.active.sum())
        plan["mixed"] = None
        if self.sched.fuse_prefill() and not self.speculative:
            for slot, pp in self._prefills.items():
                if pp.done < pp.total_len:
                    plan["mixed"] = slot
                    break
        return plan

    def _dispatch_chunk(self, plan: dict, carry: tuple) -> tuple:
        """Dispatch one decode/speculative chunk (JAX async dispatch —
        returns immediately with device futures).  ``carry`` is the
        scheduling carry (last_tok, real_lens, valid, active, budget):
        host mirrors for the first chunk of a span, the PREVIOUS chunk's
        device-resident outputs for a dispatched-ahead chunk — both feed
        the same compiled program.  Returns (toks, lps, m, carry') with
        ``m`` the speculative per-row commit counts (None on the plain
        path); ``self.cache``/``self.draft_cache``/``self.tok_counts``
        advance to the new chunk's (not-yet-materialized) outputs."""
        last_tok, real_lens, valid, active, budget = carry
        self.overlap_stats["chunks"] += 1
        m = None
        dfa_out = None
        if self.speculative:
            per_spec = dict(plan["per_spec"])
            if plan["counts"]:
                per_spec["counts"] = self.tok_counts
            if self.sampling["temperature"] > 0.0:
                # Sampled rounds consume RNG; greedy rounds must not
                # (greedy spec stays bit-stable across configs).
                per_spec["rng"] = self._split_rng()
            if self.faults is not None:
                # Injection site "batcher.spec_verify": the round is ONE
                # compiled draft+verify program, so both tags fire at its
                # dispatch — the tag selects which drill phase a rule
                # targets ('draft' = the k draft steps, 'verify' = the
                # (k+1)-token target pass).  A 'raise' here is the
                # supervisor-restart drill for the speculative leg.
                self.faults.fire("batcher.spec_verify", tag="draft")
                self.faults.fire("batcher.spec_verify", tag="verify")
            # Per-dispatch adaptive clamp (the scheduler's spec_round_k
            # hook: token-budget clamp + acceptance-EMA downshift).
            # Greedy engines only: the sampled forced-stop draw is
            # distribution-preserving but changes the per-seed stream,
            # and flipping the downshift on must never change sampled
            # outputs.  The clamp is ALWAYS passed as a traced [B]
            # vector (full k when inert) so one compiled program serves
            # every value.  Mid-span the activity mirrors are stale by
            # construction — stale the same way every run, so the
            # downshift schedule stays deterministic.
            live = self.active & np.asarray(
                [r.rid is not None for r in self.rows]
            )
            emas = tuple(
                float(self.spec_ema[i]) if live[i] else 1.0
                for i in range(self.b)
            )
            if self.sampling["temperature"] == 0.0:
                ks = self.sched.spec_round_k(
                    self.spec_k, emas, int(live.sum())
                )
            else:
                ks = [self.spec_k] * self.b
            kh = np.clip(np.asarray(ks, np.int32), 1, self.spec_k)
            plan["k_hist"].append(kh)
            per_spec["k_row"] = jnp.asarray(kh)
            METRICS.inc("batcher.spec.rounds")
            self.spec_stats["rounds"] += 1
            # Budget accounting: a round charges (k_row+1) COMMITTABLE
            # tokens per live row against the ledger (spec_round_k
            # already clamped the sum against token_budget).  The
            # dispatched program is always k+1 wide — the ledger bounds
            # commits, not flops (one compile key).
            METRICS.inc("batcher.sched.decode_tokens",
                        int(np.sum((kh + 1)[live])))
            if bool((kh[live] < self.spec_k).any()):
                METRICS.inc("batcher.spec.k_downshifts")
                self.spec_stats["downshifts"] += 1
            (toks, m, lps, self.cache, self.draft_cache, last_tok,
             real_lens, valid, active, budget, counts_out) = spec_chunk(
                self.params, self.cfg, self.draft_params, self.draft_cfg,
                self.cache, self.draft_cache, last_tok, real_lens, valid,
                active, budget, k=self.spec_k, eos_id=self.eos_id,
                pad_id=self.pad_id, tables=plan["tables"],
                **self.sampling, **per_spec,
            )
        else:
            per_row = dict(plan["per_row"])
            if plan["counts"]:
                per_row["counts"] = self.tok_counts
            if plan["constrain"]:
                # The automaton-state carry chains like the KV cache: a
                # dispatched-ahead chunk consumes the PREVIOUS chunk's
                # (not-yet-materialized) state output directly.
                per_row["dfa_state"] = self._dfa_carry
            METRICS.inc("batcher.sched.decode_tokens",
                        plan["n_active"] * self.chunk_steps)
            pp = (self._prefills.get(plan["mixed"])
                  if plan["mixed"] is not None else None)
            if pp is not None and pp.done < pp.total_len:
                (toks, self.cache, last_tok, real_lens, valid, active,
                 budget, lps, counts_out, dfa_out) = self._dispatch_mixed(
                    plan, (last_tok, real_lens, valid, active, budget),
                    per_row, pp,
                )
            else:
                if self.faults is not None and self.sched.fuse_prefill():
                    # Injection site "batcher.mixed_step" tag "decode":
                    # a mixed-schedule dispatch with no prefill riding.
                    self.faults.fire("batcher.mixed_step", tag="decode")
                (toks, self.cache, last_tok, real_lens, valid, active,
                 budget, lps, counts_out, dfa_out) = \
                    decode_chunk(
                        self.params, self.cfg_decode, self.cache, last_tok,
                        real_lens, valid, active, budget,
                        self._split_rng(), self.chunk_steps,
                        eos_id=self.eos_id, pad_id=self.pad_id, pm=self.pm,
                        tables=plan["tables"],
                        **self.sampling, **per_row,
                    )
        if counts_out is not None:
            self.tok_counts = counts_out
        if dfa_out is not None:
            self._dfa_carry = dfa_out
        return toks, lps, m, (last_tok, real_lens, valid, active, budget)

    def _mixed_width(self, done: int) -> int:
        """Prefill-leg width of a fused step: ONE bucket sized to the
        policy's largest possible bite, so the steady-state compile key
        is independent of the live prefill mix (graftcheck GC4
        batcher.mixed_step).  At the row TAIL — where cache_index + T <=
        width must hold (dynamic_update_slice CLAMPS an overflowing
        start, which would misalign the suffix) — the width shrinks DOWN
        the shared bucket ladder, never to a raw remainder
        (:meth:`_clamp_bite` guarantees a bite boundary never lands
        inside the last sub-floor slots): tail keys stay on the closed
        ladder (one per bucket, the tentpole's GC4 budget) instead of
        compiling per prompt length on the engine thread mid-span."""
        cap = self.sched.token_budget or self.sched.prefill_chunk or self.s
        w = _bucket(min(cap, self.s))
        room = self.s - done
        while w > room and w > 8:  # 8 = shapes.BUCKET_FLOOR
            w //= 2
        return min(w, room)

    def _clamp_bite(self, done: int, bite: int, total_len: int) -> int:
        """Keep every bite boundary OFF the row's last sub-floor slots
        (s-8 < done' < total_len would force the NEXT chunk's width to a
        raw off-ladder remainder and a fresh XLA trace mid-span): a bite
        that would end there shortens to land exactly on s-8, and a bite
        STARTING at the boundary finishes the prompt outright (<= 7
        tokens, the budget floor notwithstanding — once per prompt at
        most).  Applied to fused and serialized bites alike, so chunk
        splits — and therefore nothing byte-visible — stay
        schedule-invariant."""
        if self.s - 8 <= done:
            return total_len - done
        end = done + bite
        if end < total_len and self.s - end < 8:
            bite = (self.s - 8) - done
        return bite

    def _dispatch_mixed(self, plan: dict, carry: tuple, per_row: dict,
                        pp: "_PendingPrefill") -> tuple:
        """Dispatch ONE fused token-budget step (schedule=mixed): the
        decode chunk AND the head pending prefill's next bite as one
        compiled program — resident decode rows never wait on a separate
        serialized prefill forward.  Host bookkeeping (``pp.done``, bite
        metrics) advances at dispatch time; the transient prefill row and
        its last-logits chain device-resident across dispatch-ahead
        chunks exactly like the decode carry.  Returns decode_chunk's
        10-tuple."""
        last_tok, real_lens, valid, active, budget = carry
        tw = self._mixed_width(pp.done)
        # Clamp AFTER the width truncation: min(bite, tw) moves the bite
        # boundary, and only the post-truncation boundary must be kept
        # out of the sub-floor tail zone (clamping first and truncating
        # after could land the boundary right back inside it).
        bite = self._clamp_bite(
            pp.done,
            min(self.sched.prefill_bite(pp.total_len - pp.done,
                                        plan["n_active"]), tw),
            pp.total_len,
        )
        bite = min(bite, tw)  # the finish branch is invariant-bounded;
        #                       never trust it past the chunk width
        off = pp.done - (pp.total_len - len(pp.ids))
        chunk = np.full((tw,), self.pad_id, np.int32)
        chunk[:bite] = pp.ids[off: off + bite]
        if self.faults is not None:
            # Injection site "batcher.mixed_step" tag "prefill": one hit
            # per fused dispatch carrying a prefill bite.
            self.faults.fire("batcher.mixed_step", tag="prefill")
        (toks, cache, last_tok, real_lens, valid, active, budget, lps,
         counts_out, dfa_out, pp.row_k, pp.row_v, pp.last_logits) = \
            mixed_step(
                self.params, self.cfg_decode, self.cfg, self.cache,
                last_tok, real_lens, valid, active, budget,
                self._split_rng(), self.chunk_steps,
                pp.row_k, pp.row_v, jnp.int32(pp.done),
                jnp.asarray(chunk), jnp.int32(bite),
                eos_id=self.eos_id, pad_id=self.pad_id, pm=self.pm,
                tables=plan["tables"], **self.sampling, **per_row,
            )
        pp.done += bite
        METRICS.inc("batcher.prefill_chunks")
        METRICS.inc("batcher.sched.prefill_tokens", bite)
        budget_t = self.sched.token_budget or (plan["n_active"] + bite)
        METRICS.inc("batcher.sched.budget_tokens", budget_t)
        METRICS.set_gauge("batcher.sched.budget_utilization",
                          (plan["n_active"] + bite) / max(budget_t, 1))
        return (toks, cache, last_tok, real_lens, valid, active, budget,
                lps, counts_out, dfa_out)

    def _overlap_ok(self, was_active: np.ndarray, chunks: int) -> bool:
        """Whether the NEXT chunk may dispatch ahead from the device
        carry, i.e. nothing needs the host scheduling mirrors at this
        boundary — the scheduler's ``sync_triggers`` hook over a host-
        state snapshot (the trigger list and its policy live in
        runtime/scheduler.py; README "Engine overlap" documents it).
        The mixed policy keeps dispatching ahead while the head pending
        prefill still has bites to ride the fused step; the alternate
        policy parks the span for any pending prefill.  ``head_left``
        reports the first prefill WITH WORK (the one _span_plan fuses)
        — but any COMPLETED prefill awaiting its finishing splice forces
        0, so the finish retries at every chunk boundary instead of
        waiting out a sibling's whole prefill."""
        head_left = 0
        for pp in self._prefills.values():
            left = pp.total_len - pp.done
            if left <= 0:
                head_left = 0
                break
            if head_left == 0:
                head_left = left
        view = scheduler_lib.SyncView(
            any_active=bool(was_active.any()),
            cancel_dirty=self._cancel_dirty,
            queued=self.has_queued(),
            kv_imports=self.has_kv_imports(),
            prefills=len(self._prefills),
            head_prefill_left=head_left,
            live_budgets=tuple(
                int(self.budget[i]) for i in range(self.b)
                if self.rows[i].rid is not None and self.active[i]
                and not self.rows[i].prefilling
            ),
            chunks_ahead=chunks,
            grow_blocked=lambda: (
                self.paged and not self._grow_ahead(chunks + 1)
            ),
        )
        return not self.sched.sync_triggers(view)

    def _spec_note(self, m, was_active: np.ndarray, plan: dict) -> None:
        """Per-round speculative accounting from the fetched commit
        counts: update each row's acceptance-rate EMA (feeding the
        scheduler's adaptive spec_k downshift at the next span plan) and
        the spec metrics.  ``accepted`` counts committed DRAFTS (the
        bonus/correction token excluded); EOS/budget clamps deflate it —
        that loss is data, matching the standalone loop's accounting.
        Everything here is a pure function of the committed stream and
        the span structure, so two identical runs downshift
        identically."""
        if m is None:
            return
        # FIFO pairing: chunks fetch in dispatch order, so the head of
        # k_hist is exactly the clamp this fetched chunk drafted with.
        kh = (plan["k_hist"].popleft() if plan["k_hist"]
              else np.full((self.b,), self.spec_k, np.int32))
        acc = rej = 0
        for i in range(self.b):
            if not was_active[i] or m[i] <= 0:
                continue
            drafted = int(kh[i])
            accepted = min(int(m[i]) - 1, drafted)
            acc += accepted
            rej += drafted - accepted
            self.spec_ema[i] = (
                (1.0 - _SPEC_EMA_ALPHA) * float(self.spec_ema[i])
                + _SPEC_EMA_ALPHA * (accepted / max(drafted, 1))
            )
        if acc:
            METRICS.inc("batcher.spec.accepted_tokens", acc)
        if rej:
            METRICS.inc("batcher.spec.rejected_tokens", rej)
        self.spec_stats["accepted"] += acc
        self.spec_stats["rejected"] += rej
        total = self.spec_stats["accepted"] + self.spec_stats["rejected"]
        if total:
            # The cumulative acceptance gauge, fed by the same per-round
            # fraction engine.spec_acceptance observes for the standalone
            # speculative loop — one histogram serves both paths.
            METRICS.set_gauge(
                "batcher.spec.acceptance",
                self.spec_stats["accepted"] / total,
            )
        if acc + rej:
            METRICS.observe("engine.spec_acceptance", acc / (acc + rej))

    def _note_gap(self, gap_s: float) -> None:
        """Record one per-chunk device gap: the host time between the
        previous chunk completing and this chunk dispatching.  A
        dispatched-ahead chunk records 0 by construction — its dispatch
        strictly precedes the predecessor's completion, so the device
        stream runs back-to-back."""
        self.overlap_stats["device_gap_s"] += gap_s
        self.overlap_stats["gap_samples"] += 1
        METRICS.observe("batcher.overlap.device_gap_seconds", gap_s)

    def _grow_ahead(self, horizon_chunks: int) -> bool:
        """Page growth ON the overlapped window: growth needs the page
        POOL, not the carry mirrors, so a span can keep dispatching ahead
        across page boundaries — rows grow against a CONSERVATIVE frontier
        bound off the stale mirrors (``horizon_chunks`` chunks may have
        advanced every row since the last sync; budget only shrinks, so
        ``min(..., budget)`` stays an upper bound).  A still-live row
        over-allocates at most one page (written as it arrives); a row
        that already died (EOS) but whose fetch hasn't landed yet can
        transiently hold up to ``horizon_chunks * chunk_steps /
        page_size`` pages it will never write — they release at that
        fetch's publish sweep, a chunk later.  Best-effort
        only: growth that would need PRESSURE (preemption reads/writes
        the mirrors and must never run against stale ones) returns False
        and the span syncs — the normal growth path then applies today's
        exact evict -> preempt -> back-pressure ladder.  Fault-armed
        engines also return False: the ``batcher.page_alloc`` drill
        windows must keep counting exactly one hit per growth round."""
        if self.faults is not None:
            return False
        blk = self.page_size
        for i in range(self.b):
            row = self.rows[i]
            if row.rid is None or not self.active[i] or row.prefilling:
                continue
            if self.speculative:
                # A speculative chunk commits at most spec_k+1 tokens and
                # always writes a spec_k+1 window past its frontier.
                horizon = int(self.real_lens[i]) + min(
                    horizon_chunks * (self.spec_k + 1), int(self.budget[i])
                ) + self.spec_k + 1
            else:
                horizon = int(self.real_lens[i]) + min(
                    horizon_chunks * self.chunk_steps, int(self.budget[i])
                )
            need = -(-horizon // blk) - len(row.pages)
            if need <= 0:
                continue
            if self._pages_available() < need:
                return False  # pressure: sync and let _grow_rows preempt
            have = len(row.pages)
            fresh = self._alloc_pages(need)
            row.pages.extend(fresh)
            self.tables[i][have: have + need] = fresh
            self._tables_dirty = True
            METRICS.inc("batcher.pages_grown", need)
        return True

    def _fetch_chunk(self, out: tuple) -> tuple:
        """Host work's D2H for a dispatched-ahead chunk: tokens, logprobs,
        speculative commit counts, and the post-chunk activity vector in
        ONE ``jax.device_get`` (blocks until the chunk completes — the
        NEXT chunk is already executing behind it).  The rest of the
        carry stays device-resident."""
        toks, lps, m, carry = out
        extras = () if m is None else (m,)
        got = jax.device_get((toks, lps) + extras + (carry[3],))
        self._t_complete = time.perf_counter()
        toks_h, lps_h, *rest = got
        return toks_h, lps_h, (rest[0] if m is not None else None), rest[-1]

    def _sync_carry(self, out: tuple) -> tuple:
        """Refresh the host scheduling mirrors from the chunk's outputs —
        one batched ``jax.device_get`` of tokens + logprobs + the whole
        carry (replicated outputs: every process reads identical values;
        copies are taken only where the backend hands back read-only
        views, since admission writes into the mirrors).  Slots whose
        host bookkeeping dropped the row while the carry was device-
        resident (cancel mid-span) are forced inactive — the device's
        activity bit for them is stale by construction."""
        toks, lps, m, carry = out
        extras = () if m is None else (m,)
        got = jax.device_get((toks, lps) + extras + carry)
        self._t_complete = time.perf_counter()
        toks_h, lps_h, *rest = got
        m_h = rest[0] if m is not None else None
        lt, rl, va, ac, bu = rest[-5:]
        self.last_tok = _writable(lt)
        self.real_lens = _writable(rl)
        self.valid = _writable(va)
        self.active = _writable(ac)
        self.budget = _writable(bu)
        for i in range(self.b):
            if self.rows[i].rid is None and self.active[i]:
                self.active[i] = False
                self.budget[i] = 0
        self._cancel_dirty = False
        return toks_h, lps_h, m_h

    def _prehash_queued(self) -> None:
        """Overlapped host window: memoize page digests for requests that
        arrived while this span ran, so the NEXT admission round (a sync
        point — the device waits on it) finds the hashing already paid.
        Engine thread only; the snapshot tolerates concurrent submits and
        a request cancelled mid-hash just wastes the digests."""
        pc = self.prefix_cache
        if pc is None:
            return
        for req in self.queue_snapshot():
            if (req.digests is None and req.prefix_cache
                    and req.prefix is None and req.swap_handle is None):
                req.digests = self._page_digests(
                    req.ids, len(req.ids) // self.page_size
                )

    def _decode_span(self, was_active: np.ndarray) -> None:
        """One decode SPAN: a first chunk dispatched from the fresh host
        mirrors, then — while :meth:`_overlap_ok` holds — chunk N+1
        dispatched directly from chunk N's device-resident carry (JAX
        async dispatch) with chunk N's host work (token D2H, delivery
        callbacks, digest pre-hashing, metrics) running concurrently
        with N+1 on device.  Every span ends by syncing the carry into
        the host mirrors, so code outside the span always sees fresh
        scheduling state.  Temp-0 outputs are byte-identical to the
        fully-synchronous loop: the chained carry feeds the same
        compiled program the mirrors would, and every scheduling
        decision (admission, growth, preemption, shed, cancel) still
        happens against synced mirrors."""
        if self.faults is not None:
            # Injection site "batcher.decode": one hit per decode /
            # speculative chunk about to be dispatched (dispatched-ahead
            # chunks included).  A "raise" rule here is the canonical
            # engine crash (propagates out of run() into the serving
            # supervisor — a dispatched-ahead chunk in flight is simply
            # dropped with the batcher); "stall" models a wedged device
            # call for the watchdog.
            self.faults.fire("batcher.decode")
        # Mirrors are fresh here by construction (every span ends in
        # _sync_carry, and nothing is in flight between spans), so any
        # cancel recorded before this point already landed on them — only
        # a cancel taken DURING the span must force the next sync.
        self._cancel_dirty = False
        plan = self._span_plan()
        t_disp = time.perf_counter()
        if self._t_complete is not None:
            # First chunk of a span follows an OBSERVED completion (the
            # previous span's sync): the host time in between is genuine
            # device idle — collect/admit/grow ran with nothing in flight.
            self._note_gap(max(0.0, t_disp - self._t_complete))
        out = self._dispatch_chunk(plan, (
            self.last_tok, self.real_lens, self.valid, self.active,
            self.budget,
        ))
        chunks = 1
        while self.overlap and self._overlap_ok(was_active, chunks):
            if self.faults is not None:
                self.faults.fire("batcher.decode")
            if self._tables_dirty:
                # In-span growth extended a row's table: the next chunk
                # must read/write through the grown pages.
                plan["tables"] = jnp.asarray(self.tables)
                self._tables_dirty = False
            rng_before = self._rng  # ghost refund point (below)
            nxt = self._dispatch_chunk(plan, out[3])
            self._note_gap(0.0)
            chunks += 1
            self.overlap_stats["dispatched_ahead"] += 1
            METRICS.inc("batcher.overlap.dispatched_ahead")
            METRICS.set_gauge("batcher.overlap.depth", 1)
            # Chunk N's host work, concurrent with chunk N+1 on device.
            host_t0 = time.perf_counter()
            toks, lps, m, active_after = self._fetch_chunk(out)
            if self.speculative:
                self._spec_note(m, was_active, plan)
            if not active_after.any():
                # Every row died (EOS) during the chunk we just fetched:
                # the chunk dispatched ahead of it is a GHOST — all rows
                # inactive, nothing sampled, its rng value irrelevant.
                # REFUND its split so the engine RNG stream stays aligned
                # with the synchronous loop (which never dispatches the
                # ghost): sampled outputs of later requests match overlap
                # off, not just temp-0 ones.  Only the last chunk of a
                # span can be a ghost — the next _overlap_ok sees the
                # all-idle activity vector and syncs.
                self._rng = rng_before
            self._collect(toks, was_active, counts=m, lps=lps,
                          active_host=active_after)
            self._prehash_queued()
            lag = time.perf_counter() - host_t0
            self.overlap_stats["host_lag_s"] += lag
            METRICS.observe("batcher.overlap.host_lag_seconds", lag)
            was_active = active_after
            out = nxt
        # Sync exit: mirrors refresh BEFORE _collect, so a cancel taken
        # inside the delivery callbacks lands on fresh state (the
        # synchronous loop's exact ordering).
        toks, lps, m = self._sync_carry(out)
        if self.speculative:
            self._spec_note(m, was_active, plan)
        METRICS.set_gauge("batcher.overlap.depth", 0)
        if self.overlap:
            self.overlap_stats["carry_syncs"] += 1
            METRICS.inc("batcher.overlap.carry_syncs")
        if plan["constrain"]:
            # Span boundary: pull the advanced automaton states back into
            # the LOCAL per-row mirrors (abs index minus the row's stack
            # offset) — preemption/cancel/admission decisions run against
            # fresh dfa_row, like every other scheduling mirror.  Rows
            # whose host bookkeeping dropped them mid-span are skipped
            # (rid mismatch — their state is garbage by construction).
            abs_states = np.asarray(jax.device_get(self._dfa_carry))
            for i, off, rid in plan["constrain"]:
                row = self.rows[i]
                if row.rid == rid and row.req is not None \
                        and row.req.constraint is not None:
                    self.dfa_row[i] = int(abs_states[i]) - off
        self._dfa_carry = None
        self._collect(toks, was_active, counts=m, lps=lps)
