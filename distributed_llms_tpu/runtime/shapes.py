"""Decode-shape policy: the bucketing ladder that bounds compile-cache keys.

Every jit entry point whose input widths follow request sizes must pad to a
shape from a SMALL, CLOSED ladder — otherwise each new prompt length traces
and compiles a fresh program ("recompile every new seq length", the classic
TPU-serving perf bug: a 20-40 s XLA wait in the middle of serving traffic).

This module is the single definition of that ladder, shared by:

- ``runtime.batcher.ContinuousBatcher`` — admission prompt/suffix widths;
- ``runtime.engine.InferenceEngine.generate_text`` — the whole-batch
  generate path pads T up the ladder instead of to the batch's raw max;
- ``tools.graftcheck`` (GC4) — the recompilation gate traces the real jit
  entry points across a request-length sweep and fails if the distinct
  compile keys exceed what :func:`bucket_count` declares.

Padding farther right is exact by construction everywhere it is applied:
prompts are right-padded and masked (extra pad slots are never attended,
never sampled from), so a wider bucket changes compiled-program count, not
tokens.
"""

from __future__ import annotations

BUCKET_FLOOR = 8


def bucket_length(n: int, floor: int = BUCKET_FLOOR) -> int:
    """Smallest power-of-two bucket >= n (>= floor).  The ladder a raw
    request length pads up to; callers cap the result at whatever width
    actually fits their cache (``min(bucket_length(n), cap)``)."""
    b = floor
    while b < n:
        b *= 2
    return b


def bucket_ladder(cap: int, floor: int = BUCKET_FLOOR) -> list[int]:
    """Every width ``min(bucket_length(n), cap)`` can produce for
    n in [1, cap] — the CLOSED set of jit-visible prompt widths, and the
    compile-key budget the GC4 gate holds the trace ladder to."""
    out: list[int] = []
    b = floor
    while b < cap:
        out.append(b)
        b *= 2
    out.append(cap)
    return out


def bucket_count(cap: int, floor: int = BUCKET_FLOOR) -> int:
    """Declared compile-key bound for one request-length-following axis."""
    return len(bucket_ladder(cap, floor))


def generate_pad_len(t: int, n_new: int, limit: int,
                     floor: int = BUCKET_FLOOR) -> int:
    """The prompt width the whole-batch generate path pads to: up the
    ladder, but never past what leaves room for ``n_new`` decode slots
    under ``limit`` — and never BELOW the raw ``t`` (an over-budget prompt
    keeps its raw width so the sequence-budget check fails exactly as it
    would have unbucketed).  Single definition shared by
    InferenceEngine._bucket_prompt and the GC4 gate."""
    return min(bucket_length(t, floor), max(limit - n_new, t))
