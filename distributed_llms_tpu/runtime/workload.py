"""Traffic-replay harness: the "millions of users" proxy.

Every serving claim in this tree ultimately cashes out against traffic,
and until now the benches hand-rolled ad-hoc storms (uniform arrivals,
one anonymous tenant).  Real serving traffic is none of that: arrivals
are bursty at short horizons (flash crowds, retry storms) and diurnal at
long ones, tenants differ by orders of magnitude in offered load and in
prompt/output shape, and a large fraction of prompts opens with a shared
prefix (system prompts, few-shot templates).  This module generates that
traffic DETERMINISTICALLY and scores what came back:

- **Arrival process.**  Per tenant, a Markov-modulated Poisson process
  (MMPP): a two-state calm/burst chain where the burst state multiplies
  the Poisson rate (``burst_rate_x``), entered/left at exponential rates
  — the standard bursty-traffic model — with an optional slow sinusoidal
  diurnal envelope over the whole horizon.  Seeded ``random.Random`` per
  tenant: the same spec + seed replays the identical trace, so two
  serving configurations (fairness on vs off, fixed vs elastic fleet)
  are measured against byte-identical offered load.
- **Request shape.**  Per-tenant prompt/output length mixes (uniform in
  a range — heavy tails belong to the spec, not the harness) and a
  ``shared_frac`` of requests opening with the tenant's shared prefix,
  which is what exercises the prefix cache and router affinity the way
  template traffic does.
- **Scoring.**  :func:`summarize` turns replay records into the numbers
  the bench ladder stamps: per-tenant GOODPUT (tokens/s from requests
  that met their SLO — work delivered late is not goodput, the Shepherd
  framing) and SLO ATTAINMENT (fraction of non-shed requests meeting
  TTFT/latency SLOs), plus shed counts and latency percentiles.

Pure host code: no model, no device, no jax import — generator and
scoring are unit-testable in milliseconds, and :func:`replay` drives any
HTTP endpoint speaking the serving gateway's protocol.
"""

from __future__ import annotations

import asyncio
import json
import math
import random
import time
from dataclasses import dataclass, field


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's offered-load model.  ``rate_rps`` is the CALM-state
    Poisson arrival rate; the burst state multiplies it by
    ``burst_rate_x`` and is entered/left at ``burst_enter_hz`` /
    ``burst_exit_hz`` (expected bursts per second / exits per second —
    mean burst length is ``1/burst_exit_hz`` seconds).  ``shared_frac``
    of requests open with this tenant's shared prefix."""

    name: str
    rate_rps: float
    weight: float = 1.0
    prompt_len: tuple[int, int] = (16, 64)   # chars, inclusive range
    output_len: tuple[int, int] = (8, 32)    # max_tokens range
    shared_frac: float = 0.0
    shared_prefix_len: int = 48
    burst_rate_x: float = 1.0
    burst_enter_hz: float = 0.0
    burst_exit_hz: float = 1.0
    priority: int = 0

    def __post_init__(self):
        if self.rate_rps <= 0:
            raise ValueError(f"tenant {self.name}: rate_rps must be > 0")
        if not 0.0 <= self.shared_frac <= 1.0:
            raise ValueError(
                f"tenant {self.name}: shared_frac must be in [0, 1]"
            )
        if self.burst_rate_x < 1.0:
            raise ValueError(
                f"tenant {self.name}: burst_rate_x must be >= 1 (the "
                "burst state intensifies, calm is the base rate)"
            )
        for nm, (lo, hi) in (("prompt_len", self.prompt_len),
                             ("output_len", self.output_len)):
            if lo < 1 or hi < lo:
                raise ValueError(
                    f"tenant {self.name}: {nm} must be 1 <= lo <= hi"
                )


@dataclass(frozen=True)
class Arrival:
    """One generated request: submit at ``t`` (seconds from replay
    start), billed to ``tenant``."""

    t: float
    tenant: str
    prompt: str
    max_tokens: int
    priority: int = 0
    shared: bool = False  # opened with the tenant's shared prefix


# Word pool for synthetic prompts: byte-tokenizer-friendly plain text,
# deterministic under the per-tenant RNG.
_WORDS = ("the quick brown fox jumps over a lazy dog while many users "
          "send serving traffic at all hours of the day and night").split()


def _text(rng: random.Random, n_chars: int) -> str:
    out: list[str] = []
    size = 0
    while size < n_chars:
        w = rng.choice(_WORDS)
        out.append(w)
        size += len(w) + 1
    return " ".join(out)[:n_chars].rstrip() or "x"


def shared_prefix(spec: TenantSpec, seed: int = 0) -> str:
    """The tenant's deterministic shared prefix (its "system prompt"):
    a pure function of (tenant name, seed), so every generation run and
    every serving leg sees the same prefix bytes — which is what lets
    the prefix cache and router affinity actually hit across requests."""
    rng = random.Random(f"prefix:{spec.name}:{seed}")
    return _text(rng, spec.shared_prefix_len) + " "


def generate(specs: list[TenantSpec], horizon_s: float, seed: int = 0,
             diurnal_period_s: float | None = None,
             diurnal_amp: float = 0.0) -> list[Arrival]:
    """Generate the merged multi-tenant arrival trace over
    ``[0, horizon_s)``.  Deterministic in (specs, horizon, seed).

    Each tenant runs its own MMPP: exponential inter-arrival gaps at the
    CURRENT rate, with calm<->burst state flips drawn as competing
    exponentials (the flip nearest in time wins — the exact simulation,
    not a discretization).  ``diurnal_period_s`` adds a sinusoidal
    envelope ``1 + diurnal_amp * sin(2*pi*t/period)`` on top (thinning:
    arrivals are kept with probability envelope/max — exact for an
    inhomogeneous Poisson process)."""
    if horizon_s <= 0:
        raise ValueError(f"horizon_s must be > 0, got {horizon_s}")
    if not 0.0 <= diurnal_amp < 1.0:
        raise ValueError(f"diurnal_amp must be in [0, 1), got {diurnal_amp}")
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate tenant names: {names}")
    out: list[Arrival] = []
    for spec in specs:
        rng = random.Random(f"workload:{spec.name}:{seed}")
        prefix = shared_prefix(spec, seed)
        t = 0.0
        burst = False
        while True:
            rate = spec.rate_rps * (spec.burst_rate_x if burst else 1.0)
            gap = rng.expovariate(rate)
            flip_hz = (spec.burst_exit_hz if burst else spec.burst_enter_hz)
            flip_in = (rng.expovariate(flip_hz) if flip_hz > 0
                       else math.inf)
            if flip_in < gap:
                # The state flips before the next arrival would land:
                # advance to the flip and redraw (memorylessness makes
                # the redraw exact).
                t += flip_in
                burst = not burst
                if t >= horizon_s:
                    break
                continue
            t += gap
            if t >= horizon_s:
                break
            if diurnal_period_s:
                envelope = 1.0 + diurnal_amp * math.sin(
                    2.0 * math.pi * t / diurnal_period_s
                )
                # Thinning against the max envelope (1 + amp).
                if rng.random() > envelope / (1.0 + diurnal_amp):
                    continue
            shared = rng.random() < spec.shared_frac
            body = _text(rng, rng.randint(*spec.prompt_len))
            out.append(Arrival(
                t=t, tenant=spec.name,
                prompt=(prefix + body) if shared else body,
                max_tokens=rng.randint(*spec.output_len),
                priority=spec.priority, shared=shared,
            ))
    out.sort(key=lambda a: (a.t, a.tenant, a.prompt))
    return out


@dataclass
class Record:
    """One replayed request's outcome."""

    tenant: str
    t_arrival: float         # scheduled offset (trace time)
    status: int = 0          # HTTP status; 0 = transport failure
    ttft_s: float | None = None   # submit -> first token (stream) or
    #                               submit -> response (buffered)
    latency_s: float = 0.0   # submit -> fully answered
    tokens: int = 0          # completion tokens billed
    itl_s: list[float] = field(default_factory=list)  # inter-token gaps
    retry_after: float | None = None  # the shed's Retry-After hint
    shed_reason: str | None = None    # machine-readable shed reason
    text: str = ""           # concatenated completion deltas — lets a
    #                          caller check byte-exactness against a
    #                          reference, not just count tokens


async def _one_request(host: str, port: int, arr: Arrival,
                       timeout_s: float) -> Record:
    """POST one completion (streamed, so TTFT/ITL are real), one record
    out.  Sheds and transport failures are RECORDS, not exceptions — the
    harness scores them."""
    rec = Record(tenant=arr.tenant, t_arrival=arr.t)
    body = json.dumps({
        "prompt": arr.prompt, "max_tokens": arr.max_tokens,
        "priority": arr.priority, "stream": True,
    }).encode()
    t0 = time.perf_counter()
    try:
        reader, writer = await asyncio.open_connection(host, port)
    except (ConnectionError, OSError):
        rec.latency_s = time.perf_counter() - t0
        return rec
    try:
        writer.write(
            f"POST /v1/completions HTTP/1.1\r\nHost: workload\r\n"
            f"X-Tenant: {arr.tenant}\r\n"
            f"Content-Length: {len(body)}\r\n\r\n".encode() + body
        )
        await writer.drain()

        async def drive() -> None:
            rec.status = int((await reader.readline()).split()[1])
            headers: dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1").partition(":")
                headers[name.strip().lower()] = value.strip()
            if rec.status != 200:
                raw = b""
                clen = headers.get("content-length")
                if clen:
                    raw = await reader.readexactly(int(clen))
                try:
                    rec.retry_after = float(headers.get("retry-after", ""))
                except ValueError:
                    pass
                try:
                    rec.shed_reason = (json.loads(raw)["error"]
                                       .get("reason"))
                except (ValueError, KeyError, TypeError):
                    pass
                return
            # SSE: every data: payload with text counts as a delivery.
            last = None
            buf = b""
            while True:
                chunk = await reader.read(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n\n" in buf:
                    evt, buf = buf.split(b"\n\n", 1)
                    if not evt.startswith(b"data: "):
                        continue
                    payload = evt[len(b"data: "):]
                    if payload.strip() == b"[DONE]":
                        return
                    try:
                        obj = json.loads(payload)
                    except ValueError:
                        continue
                    choices = obj.get("choices") or [{}]
                    text = choices[0].get("text") or \
                        (choices[0].get("delta") or {}).get("content", "")
                    if not text and "error" in obj:
                        return
                    if text:
                        now = time.perf_counter()
                        if rec.ttft_s is None:
                            rec.ttft_s = now - t0
                        elif last is not None:
                            rec.itl_s.append(now - last)
                        last = now
                        # Completion CHARS — exactly tokens under the
                        # byte tokenizer every bench/test replica runs;
                        # a close proxy elsewhere.
                        rec.tokens += len(text)
                        rec.text += text
        await asyncio.wait_for(drive(), timeout_s)
    except (asyncio.TimeoutError, ConnectionError, OSError, EOFError,
            ValueError, IndexError, asyncio.IncompleteReadError):
        pass
    finally:
        rec.latency_s = time.perf_counter() - t0
        writer.close()
    return rec


async def replay(host: str, port: int, arrivals: list[Arrival],
                 time_scale: float = 1.0,
                 request_timeout_s: float = 120.0) -> list[Record]:
    """Replay a generated trace against a live endpoint (gateway or
    router): each arrival fires at ``t * time_scale`` seconds after
    start, concurrently (open-loop — a slow server does NOT slow the
    offered load, which is exactly what makes overload measurable).
    Returns one :class:`Record` per arrival, trace order."""

    t_start = time.perf_counter()

    async def fire(arr: Arrival) -> Record:
        delay = arr.t * time_scale - (time.perf_counter() - t_start)
        if delay > 0:
            await asyncio.sleep(delay)
        return await _one_request(host, port, arr, request_timeout_s)

    return list(await asyncio.gather(*[fire(a) for a in arrivals]))


def _pct(vals: list[float], q: float) -> float | None:
    if not vals:
        return None
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(q * len(vs)))]


def summarize(records: list[Record], horizon_s: float,
              ttft_slo_s: float | None = None,
              latency_slo_s: float | None = None) -> dict[str, dict]:
    """Per-tenant goodput / SLO-attainment curves from replay records.

    - ``slo_attainment``: of the requests the server ACCEPTED (status
      200), the fraction meeting every configured SLO (TTFT and/or
      end-to-end latency).  Sheds are not attainment failures — they are
      counted separately (a 429 with Retry-After is the contract working,
      silent starvation is what attainment catches).
    - ``goodput_tok_s``: completion tokens of SLO-meeting requests per
      second of horizon — late work is not goodput (Shepherd's framing).
    """
    out: dict[str, dict] = {}
    for tenant in sorted({r.tenant for r in records}):
        rs = [r for r in records if r.tenant == tenant]
        ok = [r for r in rs if r.status == 200]

        def met(r: Record) -> bool:
            if ttft_slo_s is not None and (r.ttft_s is None
                                           or r.ttft_s > ttft_slo_s):
                return False
            if latency_slo_s is not None and r.latency_s > latency_slo_s:
                return False
            return True

        good = [r for r in ok if met(r)]
        shed = [r for r in rs if r.status in (429, 503)]
        itls = [g for r in ok for g in r.itl_s]
        out[tenant] = {
            "offered": len(rs),
            "completed": len(ok),
            "shed": len(shed),
            "shed_with_retry_after": sum(
                1 for r in shed if r.retry_after is not None
            ),
            "failed": len(rs) - len(ok) - len(shed),
            "slo_attainment": (len(good) / len(ok)) if ok else 0.0,
            "goodput_tok_s": sum(r.tokens for r in good) / horizon_s,
            "tok_s": sum(r.tokens for r in ok) / horizon_s,
            "ttft_p50_s": _pct([r.ttft_s for r in ok
                                if r.ttft_s is not None], 0.50),
            "ttft_p95_s": _pct([r.ttft_s for r in ok
                                if r.ttft_s is not None], 0.95),
            "itl_p95_s": _pct(itls, 0.95),
        }
    return out
