"""Token sampling: greedy, temperature, top-k, top-p.

The reference has no sampling or decode loop at all — its "inference" is a
single placeholder matmul (src/worker/node.py:24-32; SURVEY §2.5) — while its
plan promises real inference (plan.md:235-239).  All samplers here are pure,
jittable, and batched."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.config import RuntimeConfig


def greedy(logits: jax.Array) -> jax.Array:
    """[B, V] -> [B] int32."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _mask_top_k(logits: jax.Array, k: int) -> jax.Array:
    if k <= 0:
        return logits
    vals, _ = jax.lax.top_k(logits, k)
    cutoff = vals[..., -1:]
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def _mask_top_p(logits: jax.Array, p: float) -> jax.Array:
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep the smallest prefix with cumulative prob >= p (always keep top-1);
    # cutoff = smallest kept logit, so everything below it is masked
    keep_sorted = cum - probs < p
    cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def warp_logits(
    logits: jax.Array, temperature: float, top_k: int = 0, top_p: float = 1.0
) -> jax.Array:
    """Apply the temperature/top-k/top-p warp and return the warped logits
    (masked entries at -inf).  ``softmax(warp_logits(...))`` is the exact
    distribution :func:`sample` draws from — speculative rejection sampling
    (runtime/speculative.py) needs that distribution, not just a draw.
    Requires temperature > 0 (greedy has no distribution to expose)."""
    logits = logits / temperature
    logits = _mask_top_k(logits, top_k)
    return _mask_top_p(logits, top_p)


def sample(
    rng: jax.Array,
    logits: jax.Array,  # [B, V] float32
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Sample next tokens [B].  temperature == 0 -> greedy (rng unused).

    temperature/top_k/top_p are Python floats (static under jit): the sampler
    specializes at trace time, so the greedy path compiles to a bare argmax.
    """
    if temperature == 0.0:
        return greedy(logits)
    return jax.random.categorical(
        rng, warp_logits(logits, temperature, top_k, top_p), axis=-1
    ).astype(jnp.int32)


def _mask_top_k_rows(logits: jax.Array, k: jax.Array) -> jax.Array:
    """Top-k mask with a TRACED per-row ``k`` [B] (rows with k <= 0 keep the
    full vocabulary).  The cutoff equals :func:`_mask_top_k`'s for a uniform
    batch — same kept set, ties included — so a batch whose rows all carry
    the engine-wide k draws identically to the static path."""
    k = jnp.asarray(k, jnp.int32)[:, None]
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    ranks = jnp.arange(logits.shape[-1], dtype=jnp.int32)[None, :]
    keep_sorted = ranks < jnp.maximum(k, 1)
    cutoff = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where((k > 0) & (logits < cutoff), -jnp.inf, logits)


def _mask_top_p_rows(logits: jax.Array, p: jax.Array) -> jax.Array:
    """Top-p mask with a TRACED per-row ``p`` [B] (same math as
    :func:`_mask_top_p`, which specializes on a static scalar)."""
    p = jnp.broadcast_to(p, logits.shape[:1])[:, None]
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = cum - probs < p
    cutoff = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def sample_rows(
    rng: jax.Array,
    logits: jax.Array,       # [B, V] float32
    temperature: jax.Array,  # [B] — 0 means greedy for that row
    top_k: int = 0,
    top_p: jax.Array | float = 1.0,  # [B] or scalar, traced
    top_k_rows: jax.Array | None = None,  # [B] int32 traced — overrides the
    #   static ``top_k`` when given (per-request top_k in a shared batch)
    mask_rows: jax.Array | None = None,  # [B, V] traced additive mask —
    #   grammar-constrained / logit-biased rows (runtime/constrain.py):
    #   0 keeps a token, a large negative value forbids it; free rows in
    #   the same batch carry an all-zero row (exact identity)
) -> jax.Array:
    """Per-row sampling: each batch row draws with its OWN temperature,
    top-p, (via ``top_k_rows``) top-k, and (via ``mask_rows``) token mask
    — continuous-batching serving mixes per-request sampling configs in
    one decode step without recompiling (the knobs are traced inputs, not
    static).  Without ``top_k_rows`` the static ``top_k`` applies
    batch-wide (``lax.top_k`` needs a compile-time k; the traced variant
    pays a full [B, V] sort).  ``mask_rows`` applies BEFORE the
    temperature warp and before the greedy fallback, so constrained
    greedy rows take the masked argmax.  Rows with temperature == 0 take
    the greedy token (identical to :func:`greedy` when unmasked); the
    warp order matches :func:`sample`, so a uniform batch draws the same
    tokens as the static path under the same rng."""
    if mask_rows is not None:
        logits = logits + mask_rows
    temperature = jnp.asarray(temperature, logits.dtype)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    warped = logits / safe_t
    if top_k_rows is not None:
        warped = _mask_top_k_rows(warped, top_k_rows)
    else:
        warped = _mask_top_k(warped, top_k)
    if not (isinstance(top_p, (int, float)) and float(top_p) >= 1.0):
        # Static keep-everything fast path: the [B, V] sort+softmax+cumsum
        # is pure waste when no row asked for top-p.
        warped = _mask_top_p_rows(warped, jnp.asarray(top_p, logits.dtype))
    drawn = jax.random.categorical(rng, warped, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, drawn, greedy(logits))


def sampler_from_config(rt: RuntimeConfig):
    """Bind the static sampling knobs from a RuntimeConfig."""

    def fn(rng: jax.Array, logits: jax.Array) -> jax.Array:
        return sample(rng, logits, rt.temperature, rt.top_k, rt.top_p)

    return fn
