"""Scheduling policy for continuous batching (ROADMAP item 1).

Every scheduling DECISION the engine loop takes — admission order, prefill
chunk sizing against a per-step token budget, preemption victim selection,
the memory-pressure ladder, and the dispatch-ahead sync-trigger list —
lives here as a declared hook on a policy object, extracted from
``ContinuousBatcher`` (which had accreted them across PRs 1-12 as inline
branches of a 4k-line run loop).  The batcher owns MECHANISM (jitted
programs, pool bookkeeping, device carries); this module owns POLICY, and
the two meet only through the hooks in :data:`HOOKS` — so a new scheduling
behavior is a subclass here, not another branch in the run loop.

Two policies ship:

- ``mixed`` (default) — the stall-free fused token-budget step
  (Sarathi-Serve's chunked-prefill + decode coalescing at Orca's
  iteration-level granularity): pending prefill chunks become budgeted
  work INSIDE the decode step (``batcher.mixed_step`` — one compiled
  program runs K decode tokens for every active slot and up to
  ``token_budget - n_active`` prefill tokens), so resident decode rows
  never stall for a serialized prefill forward and the dispatch-ahead
  span keeps running while a long prompt admits.
- ``alternate`` — the PR-3..12 behavior: chunked prefills advance as
  their own ``prefill_chunk_step`` forwards serialized against
  ``decode_chunk``, and any pending prefill parks the overlap plane.

Both are byte-identical at temperature 0 (chunk splits and program fusion
change scheduling, never math — tests/runtime/test_mixed_step.py pins the
matrix), so ``--schedule`` is a latency knob, not a semantics knob.

Hooks are model-free by construction: they consume plain host data
(queues, tuples, counts) and return decisions, so policy unit tests run
without a model, a device, or a batcher.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Sequence

from ..core.observability import METRICS

# The declared hook registry: hook name -> what the batcher delegates
# through it.  README's scheduler table is generated from this mapping and
# tests/runtime/test_mixed_step.py asserts every hook exists on every
# policy — adding a scheduling decision to the batcher without declaring
# its hook here is the drift this registry exists to catch.
HOOKS: dict[str, str] = {
    "admission_order":
        "which queued request admits next (priority desc, FIFO rid "
        "within a class; preempted resumes keep their original rid)",
    "chunk_threshold":
        "prompt length above which admission takes the chunked-prefill "
        "path instead of one monolithic forward",
    "prefill_bite":
        "prefill tokens the next step may consume, sized against the "
        "per-step token budget and the live decode row count",
    "fuse_prefill":
        "whether the pending prefill bite rides the decode step as one "
        "fused program (mixed) or runs as its own serialized forward",
    "select_victim":
        "which resident row preempts under pool pressure (lowest "
        "priority first, most recently admitted among equals)",
    "pressure_rungs":
        "the ordered memory-pressure ladder a dry pool escalates "
        "through before back-pressuring admission",
    "sync_triggers":
        "which conditions end a dispatch-ahead span (the overlap "
        "plane's host-sync decision list)",
    "spec_round_k":
        "per-row COMMIT bound for the next speculative round: the sum of "
        "committable tokens (k_row+1 per live row) is clamped against "
        "the per-step token budget and each row's acceptance-rate EMA "
        "feeds an adaptive downshift — a ledger/granularity bound; the "
        "compiled round's device work is constant (one compile key)",
    "note_admitted":
        "admission-commit accounting: the batcher reports every request "
        "leaving the queue for a slot (est = prompt + budget tokens) so "
        "tenant-fair policies can charge virtual token counters and "
        "resident-row caps; the base policies keep no accounts (no-op)",
    "note_freed":
        "row-release accounting: the batcher reports every admitted "
        "row's release (completion, cancel, preemption) with the tokens "
        "it actually emitted, so per-tenant charges true up — unspent "
        "budget refunds and resident-row caps decrement (base: no-op)",
}

# The declared LOCKSTEP decision surfaces — the registry tools/graftsync
# audits (GS1 taint, GS3 set-ordering, GS4 drift).  On a multi-process
# mesh every process must take the SAME scheduling decision in the same
# round or SPMD dispatch deadlocks/diverges, so every function named here
# (and everything it transitively calls) must be deterministic in
# scheduling state alone: no wall clocks, no global-state RNG, no
# id()/hash(), no env reads, no unordered-set iteration.  "Owner.name"
# binds the method on the named class AND every subclass override.
# Adding a scheduler hook without declaring it here is GS402 drift;
# naming a function nothing declares is GS401.
LOCKSTEP_DECISIONS: dict[str, str] = {
    "Scheduler.admission_order":
        "which queued request admits next — identical pick per process",
    "Scheduler.chunk_threshold":
        "monolithic-vs-chunked admission path selection",
    "Scheduler.prefill_bite":
        "prefill tokens the next step consumes (budget arithmetic)",
    "Scheduler.fuse_prefill":
        "fused-vs-serialized prefill program selection",
    "Scheduler.select_victim":
        "which resident row preempts under pool pressure",
    "Scheduler.pressure_rungs":
        "the ordered memory-pressure escalation ladder",
    "Scheduler.sync_triggers":
        "which conditions end a dispatch-ahead span (host-sync decision)",
    "Scheduler.spec_round_k":
        "per-row speculative commit bound (k_row clamp vector)",
    "Scheduler.note_admitted":
        "admission-commit accounting feeding later admission_order picks",
    "Scheduler.note_freed":
        "release true-up accounting feeding later admission_order picks",
    "ContinuousBatcher._shed_expired_queued":
        "queue-deadline shedding: reads the injected lockstep clock "
        "(self._clock), never the wall clock directly; meshes skip it",
    "ContinuousBatcher._overlap_ok":
        "the dispatch-ahead gate (sync_triggers over a SyncView snapshot)",
    "ContinuousBatcher._span_plan":
        "compile-key static args for the span's chunks — program "
        "selection must match across processes or compiled dispatch "
        "diverges",
}

# The declared host<->device sync points — the registry tools/graftsync
# GS2 audits.  Every jax.device_get / block_until_ready in runtime/ must
# sit in a function named here: the dispatch-ahead overlap plane earns
# its throughput by syncing at exactly these boundaries, so adding a
# sync is a reviewed registry line, never a silent per-chunk round-trip.
# These are also the ONE place wall-clock/timer reads are exempt from
# GS1 (the host is already serialized against the device here — the
# lockstep clock policy's "clock reads only at declared sync points").
HOST_SYNC_SITES: dict[str, str] = {
    "ContinuousBatcher._fetch_chunk":
        "one batched D2H per dispatched chunk (tokens+logprobs+activity)",
    "ContinuousBatcher._sync_carry":
        "span exit: the whole scheduling carry returns to host mirrors",
    "ContinuousBatcher._decode_span":
        "span boundary: automaton state read-back + host-lag stamping",
    "ContinuousBatcher.register_prefix":
        "prefix registration materializes the row cache once, at admit",
    "engine._to_host":
        "generation output D2H (allgathers mesh-sharded tiles first)",
}

# Rung names of the declared pressure ladder (PR-9's order).  "evict_spill"
# is implicit in pool accounting (available() counts evictable cached
# pages, spilling them to the host tier first); the preempt rungs gate
# whether a victim's pages swap out (byte-exact restore) or requeue for
# exact recompute; "back_pressure" is the terminal rung (admission waits).
PRESSURE_LADDER = (
    "evict_spill", "swap_preempt", "recompute_preempt", "back_pressure",
)


@dataclass(frozen=True)
class SyncView:
    """Host-state snapshot ``sync_triggers`` decides from — everything is
    deterministic scheduling state (never wall clocks), so a multi-process
    mesh evaluates identical views in lockstep.  ``grow_blocked`` is a
    thunk (page growth probes pool accounting and allocates from spare
    capacity) evaluated only when no cheaper trigger already fired."""

    any_active: bool          # last-known activity vector has a live row
    cancel_dirty: bool        # resident-row cancel taken mid-span
    queued: bool              # a request awaits admission
    kv_imports: bool          # a verified KV handoff awaits adoption
    prefills: int             # chunked prefills in flight (started)
    head_prefill_left: int    # prompt tokens the head prefill still owes
    #                           (after already-dispatched bites)
    live_budgets: tuple[int, ...]  # device-budget mirrors of live rows
    chunks_ahead: int         # chunks already dispatched this span
    grow_blocked: Callable[[], bool]  # paged growth needs PRESSURE


class Scheduler:
    """The ``alternate`` policy: chunked prefills advance as serialized
    ``prefill_chunk_step`` rounds (decode stalls for each bite) and any
    pending prefill parks the dispatch-ahead plane — exactly the PR-3..12
    inline behavior, now behind the declared hooks."""

    name = "alternate"

    def __init__(self, *, chunk_steps: int = 8,
                 prefill_chunk: int | None = None,
                 prefill_concurrency: int = 2,
                 token_budget: int | None = None,
                 speculative: bool = False,
                 spec_adaptive: bool = True) -> None:
        if token_budget is not None and token_budget < 1:
            raise ValueError(
                f"token_budget must be >= 1, got {token_budget}"
            )
        self.chunk_steps = chunk_steps
        self.prefill_chunk = prefill_chunk
        self.prefill_concurrency = prefill_concurrency
        self.token_budget = token_budget
        self.speculative = speculative
        self.spec_adaptive = spec_adaptive

    # -- admission order ---------------------------------------------------

    def admission_order(self, queue: Sequence[Any]) -> Any | None:
        """Highest priority first, FIFO (rid) within a priority.  A
        preempted request keeps its original rid, so it resumes ahead of
        later same-priority arrivals.  Deterministic in the queue contents
        alone, so multi-process meshes stay lockstep."""
        if not queue:
            return None
        return max(queue, key=lambda r: (r.priority, -r.rid))

    # -- chunk sizing against the token budget -----------------------------

    def chunk_threshold(self) -> int | None:
        """Prompts longer than this take the chunked path; None = every
        prompt admits monolithically.  Alternate chunks only when the
        operator configured ``prefill_chunk``."""
        return self.prefill_chunk

    def prefill_bite(self, remaining: int, n_active: int) -> int:
        """Prompt tokens the next prefill step consumes.  Alternate spends
        a full ``prefill_chunk`` per round regardless of how many decode
        rows it stalls — the over-spend the mixed policy exists to bound."""
        return min(remaining, self.prefill_chunk or remaining)

    def fuse_prefill(self) -> bool:
        """Alternate dispatches prefill bites as their own forwards."""
        return False

    # -- victim selection --------------------------------------------------

    def select_victim(self, candidates: Sequence[tuple[int, int, int]],
                      below_priority: int | None = None) -> int | None:
        """The row to preempt under pool pressure: lowest priority first,
        most-recently-admitted among equals (its lost work is smallest —
        vLLM's recompute-preemption policy).  ``candidates`` are
        ``(slot, priority, admit_seq)`` tuples for the preemptable rows;
        ``below_priority`` restricts to STRICTLY lower-priority victims
        (the admission path: a newcomer never preempts its own class,
        which would livelock two requests trading the same pages)."""
        best: int | None = None
        best_key: tuple[int, int] | None = None
        for slot, priority, admit_seq in candidates:
            if below_priority is not None and priority >= below_priority:
                continue
            key = (priority, -admit_seq)
            if best is None or key < best_key:
                best, best_key = slot, key
        return best

    # -- pressure ladder ---------------------------------------------------

    def pressure_rungs(self) -> tuple[str, ...]:
        """The ordered ladder a dry pool escalates through
        (:data:`PRESSURE_LADDER`).  The batcher consults membership:
        dropping ``swap_preempt`` from a policy would send every victim
        straight to exact recompute."""
        return PRESSURE_LADDER

    # -- tenant accounting (no-ops on the base policies) -------------------

    def note_admitted(self, req: Any, est_tokens: int) -> None:
        """A request left the queue for a slot.  ``est_tokens`` is the
        admission-time upper bound (prompt + decode budget); tenant-fair
        subclasses charge it against the request's tenant.  Base
        policies keep no per-tenant accounts."""

    def note_freed(self, req: Any, emitted: int) -> None:
        """An admitted row released its slot (completion, cancel, or
        preemption) having actually emitted ``emitted`` tokens this
        residency.  Tenant-fair subclasses refund the unspent part of
        the admission charge and decrement residency.  Base: no-op."""

    # -- speculative round sizing ------------------------------------------

    def spec_round_k(self, k_max: int, emas: Sequence[float],
                     n_active: int) -> list[int]:
        """Per-row draft length for the next speculative round.  The
        alternate policy never downshifts: every row drafts the full k
        (the PR-6..16 behavior), and the batcher's traced clamp is inert.
        ``emas`` is one acceptance-rate EMA per batch slot (1.0 for
        non-live slots)."""
        return [k_max] * len(emas)

    # -- overlap sync triggers ---------------------------------------------

    def sync_triggers(self, view: SyncView) -> list[str]:
        """The conditions that END a dispatch-ahead span (empty list =
        the next chunk may dispatch from the device-resident carry).
        THE sync-trigger list (README "Engine overlap"):

        - ``all_idle``: every row already idle as of the last-known
          activity vector — never chain behind a possibly-all-idle chunk;
        - ``cancel``: a resident-row cancel taken while the carry was
          device-resident;
        - ``queued`` / ``kv_import``: admission work is waiting;
        - ``prefill``: a chunked prefill is in flight (alternate parks
          the overlap plane for the whole prefill; the mixed policy
          narrows this to the finishing splice);
        - ``budget_certain``: every live row will have exhausted its
          budget within the chunks already dispatched — the next chunk
          could only be a ghost;
        - ``page_pressure``: a row near its page horizon could not grow
          from spare pool capacity (preemption must run on fresh
          mirrors).
        """
        out: list[str] = []
        if not view.any_active:
            out.append("all_idle")
        if view.cancel_dirty:
            out.append("cancel")
        if view.queued:
            out.append("queued")
        if view.kv_imports:
            out.append("kv_import")
        if view.prefills:
            out.append(self._prefill_trigger(view))
        if self._budget_certain(view):
            out.append("budget_certain")
        out = [t for t in out if t]
        if not out and view.grow_blocked():
            out.append("page_pressure")
        return out

    def _prefill_trigger(self, view: SyncView) -> str | None:
        return "prefill"

    def _budget_certain(self, view: SyncView) -> bool:
        """Whether every live row will be done within the chunks already
        dispatched.  Plain chunks commit exactly ``chunk_steps`` tokens
        per active row; a speculative round commits at least one.  EOS
        finishes are not host-predictable, so a rare ghost behind an EOS
        remains (it pads nothing into the stream)."""
        per_chunk = 1 if self.speculative else self.chunk_steps
        return all(
            b <= view.chunks_ahead * per_chunk for b in view.live_budgets
        )


class MixedScheduler(Scheduler):
    """The ``mixed`` policy: one fused token-budget step.  Pending prefill
    chunks become budgeted work INSIDE the decode step — each dispatch
    runs K decode tokens for every active slot plus up to
    ``token_budget - n_active`` prompt tokens of the head pending prefill
    in the same compiled program — so decode never stalls for a
    serialized prefill forward, and a pending prefill no longer parks
    the dispatch-ahead span (it syncs only for the finishing splice,
    which is an admission decision).  With ``token_budget`` unset the
    bite falls back to ``prefill_chunk`` (fusion without re-budgeting);
    with it set, prompts longer than the budget auto-chunk even when
    ``prefill_chunk`` was never configured."""

    name = "mixed"

    def chunk_threshold(self) -> int | None:
        if self.prefill_chunk is not None:
            return self.prefill_chunk
        if self.token_budget is not None and not self.speculative:
            # Auto-chunk: any prompt the budget cannot cover in one step
            # takes the fused path (speculative admission stays
            # monolithic — its draft prefill cannot chunk).
            return self.token_budget
        return None

    def prefill_bite(self, remaining: int, n_active: int) -> int:
        if self.token_budget is None:
            return super().prefill_bite(remaining, n_active)
        # Decode rows claim their legs first; the floor of 1 keeps a
        # fully-busy batch from starving the prefill outright (one token
        # per step still makes progress toward the finishing splice).
        return min(remaining, max(1, self.token_budget - n_active))

    def fuse_prefill(self) -> bool:
        return True

    def _prefill_trigger(self, view: SyncView) -> str | None:
        # A prefill with work left feeds the NEXT fused chunk — keep
        # dispatching ahead.  Only the finishing splice (an admission:
        # first-token sample + pool scatter, a host decision) syncs.
        return None if view.head_prefill_left > 0 else "prefill_finish"


class SpecMixedScheduler(MixedScheduler):
    """Budget-aware speculative rounds — the ``mixed`` policy a
    speculative engine schedules under (selected by :func:`make_scheduler`
    when ``speculative=True``).  A round charges ``k_row+1`` committable
    tokens per live row against ``token_budget``, so two clamps size each
    row's commit bound:

    - BUDGET (engine-wide): k_row shrinks until the round's committable
      sum fits the per-step budget — the scheduler's ledger stays
      consistent and a round never commits (or delivers) more tokens
      than the budget, keeping cancel/deadline cadence bounded;
    - ACCEPTANCE (per row): each row's acceptance-rate EMA scales its
      bound (``max(1, round(ema * k_max))``) — a cold draft's commits
      shrink toward plain-decode granularity.

    These are LEDGER bounds, not compute savers: the compiled round
    always runs the full ``k_max``-step draft scan and ``k_max+1``-token
    verify (static shapes are what keep the whole ladder on ONE compile
    key — graftcheck GC4 ``batcher.spec_chunk_paged``), so clamping
    discards already-verified tokens rather than skipping work.
    Skipping a genuinely cold row's round entirely (dispatching the
    plain decode program instead) is the compute-saving follow-up; see
    ROADMAP.  Both clamps reach the compiled round as ONE traced [B]
    vector (``spec_chunk``'s ``k_row``), and the forced stop emits the
    target's own token — streams stay byte-exact at any clamp (only
    arrival granularity changes).
    """

    name = "mixed"

    def spec_round_k(self, k_max: int, emas: Sequence[float],
                     n_active: int) -> list[int]:
        if not self.spec_adaptive:
            return [k_max] * len(emas)
        kb = k_max
        if self.token_budget is not None and n_active:
            while kb > 1 and n_active * (kb + 1) > self.token_budget:
                kb -= 1
        return [
            min(kb, max(1, int(e * k_max + 0.5))) for e in emas
        ]


# Queue entries with no tenant id share one anonymous bucket: they are
# fair-shared against named tenants at the default weight, so an operator
# can turn fairness on without forcing every client to tag its traffic.
ANON_TENANT = "-"


def parse_tenant_weights(spec: "str | dict | None") -> dict[str, float]:
    """Parse the ``--tenant-weights`` / ``RuntimeConfig.tenant_weights``
    spelling (``"gold:4,free:1"``) into {tenant: weight}.  A ``*`` entry
    sets the DEFAULT weight unknown (and anonymous) tenants serve at;
    absent, it is 1.0.  Dicts pass through validated.  Weights must be
    finite and > 0 — a zero weight is a starvation knob, not a share."""
    import math

    if spec is None:
        return {}
    if isinstance(spec, dict):
        items = list(spec.items())
    else:
        items = []
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            name, sep, w = part.partition(":")
            if not sep or not name.strip():
                raise ValueError(
                    f"tenant weight entry {part!r} must look like "
                    "name:weight (e.g. gold:4,free:1)"
                )
            items.append((name.strip(), w.strip()))
    out: dict[str, float] = {}
    for name, w in items:
        try:
            weight = float(w)
        except (TypeError, ValueError):
            raise ValueError(
                f"tenant {name!r}: weight {w!r} is not a number"
            ) from None
        if not math.isfinite(weight) or weight <= 0:
            raise ValueError(
                f"tenant {name!r}: weight must be finite and > 0, "
                f"got {weight}"
            )
        out[name] = weight
    return out


class TenantScheduler(MixedScheduler):
    """Weighted-fair multi-tenant admission — the ``mixed`` policy a
    tenant-QoS engine schedules under (selected by :func:`make_scheduler`
    when ``tenant_weights`` is set).  The fairness design is the virtual
    token counter of *Fairness in Serving Large Language Models* (VTC,
    OSDI '24), lifted from the PR-3 request-level priority machinery to
    the TENANT level:

    - every admission charges its tenant's counter ``est / weight``
      tokens (est = prompt + decode budget, the same upper bound the
      router and cost gate use), and the release true-up refunds the
      unspent budget — so the counter tracks WEIGHTED SERVICE RECEIVED;
    - :meth:`admission_order` serves the backlogged tenant with the
      LOWEST counter first (then the base priority-desc / FIFO order
      within that tenant), so a tenant flooding the queue advances its
      own counter and cannot crowd out a lighter tenant's share;
    - the STARVATION GUARD is VTC's counter lift: a tenant returning
      from idle is lifted to the minimum counter among currently-live
      tenants, so idling never banks unbounded credit (it would
      otherwise monopolize the engine for its whole deficit) and a
      continuously-backlogged tenant can never be starved by returning
      ones — each admission strictly advances the minimum;
    - ``tenant_max_rows`` caps RESIDENT rows per tenant: a tenant at its
      cap defers (its queue entries wait; others admit past them), so
      one tenant can never hold every batch slot no matter its weight.

    Token-RATE quotas live one layer up at the serving gateway (the
    cheap place to shed: 429 + per-tenant Retry-After before any state
    exists); this class owns what must be decided at admission time.
    Deterministic in (queue contents, admission history) alone — no
    wall clocks — so multi-process meshes stay lockstep."""

    def __init__(self, *, tenant_weights: dict[str, float] | None = None,
                 tenant_max_rows: int | None = None, **kw: Any) -> None:
        super().__init__(**kw)
        if tenant_max_rows is not None and tenant_max_rows < 1:
            raise ValueError(
                f"tenant_max_rows must be >= 1, got {tenant_max_rows}"
            )
        self.tenant_weights = dict(tenant_weights or {})
        self.default_weight = self.tenant_weights.pop("*", 1.0)
        self.tenant_max_rows = tenant_max_rows
        self._vtc: dict[str, float] = {}       # weighted service received
        self._resident: dict[str, int] = {}    # rows currently in slots
        self._charged: dict[int, tuple[str, float]] = {}  # rid -> charge
        self._live: set[str] = set()           # tenants seen since idle

    def weight(self, tenant: "str | None") -> float:
        return self.tenant_weights.get(tenant or ANON_TENANT,
                                       self.default_weight)

    @staticmethod
    def _tenant_of(req: Any) -> str:
        return getattr(req, "tenant", None) or ANON_TENANT

    def _publish(self, tenant: str) -> None:
        METRICS.set_gauge(f"tenant.vtc.{tenant}",
                          self._vtc.get(tenant, 0.0))
        METRICS.set_gauge(f"tenant.resident_rows.{tenant}",
                          self._resident.get(tenant, 0))

    def admission_order(self, queue: Sequence[Any]) -> Any | None:
        if not queue:
            return None
        by_tenant: dict[str, list[Any]] = {}
        for r in queue:
            by_tenant.setdefault(self._tenant_of(r), []).append(r)
        # Starvation guard (the VTC lift): a tenant re-entering from idle
        # is lifted to the minimum counter among tenants already live —
        # idle time banks no credit, and the lift never REDUCES anyone.
        # sorted(): _live is a set, and this list feeds a decision —
        # iteration order must not depend on PYTHONHASHSEED / insertion
        # history, or lockstep processes could diverge (graftsync GS301;
        # min() below is order-insensitive today, but keep the closure
        # deterministic by construction, not by accident).
        live_counters = [
            self._vtc.get(t, 0.0)
            for t in sorted(self._live)
            if t in by_tenant or self._resident.get(t, 0) > 0
        ]
        floor = min(live_counters, default=0.0)
        for t in by_tenant:
            if t not in self._live:
                self._vtc[t] = max(self._vtc.get(t, 0.0), floor)
                self._publish(t)
        # Live = backlogged or resident; everyone else re-lifts on return.
        self._live = {t for t in set(self._live) | set(by_tenant)
                      if t in by_tenant or self._resident.get(t, 0) > 0}
        # Cardinality bound: tenant ids are client-minted, so idle entries
        # must not accumulate forever.  An idle tenant AT OR BELOW the
        # floor carries no information — its return is lifted to the floor
        # anyway — so dropping it is semantically a no-op; an overserved
        # idle tenant (counter above floor) keeps its debt until the floor
        # catches up.
        for t in [t for t, v in self._vtc.items()
                  if t not in self._live and v <= floor]:
            del self._vtc[t]
            self._resident.pop(t, None)
            self._publish(t)  # gauges read 0 for the dropped tenant
        cap = self.tenant_max_rows
        eligible = [
            t for t in by_tenant
            if cap is None or self._resident.get(t, 0) < cap
        ]
        if not eligible:
            # Every backlogged tenant sits at its resident-row cap: defer
            # admission (rows free at chunk boundaries and re-trigger it).
            return None
        pick = min(eligible, key=lambda t: (self._vtc.get(t, 0.0), t))
        return super().admission_order(by_tenant[pick])

    def note_admitted(self, req: Any, est_tokens: int) -> None:
        t = self._tenant_of(req)
        charge = est_tokens / self.weight(t)
        self._vtc[t] = self._vtc.get(t, 0.0) + charge
        self._resident[t] = self._resident.get(t, 0) + 1
        self._charged[req.rid] = (t, charge)
        self._live.add(t)
        self._publish(t)

    def note_freed(self, req: Any, emitted: int) -> None:
        got = self._charged.pop(req.rid, None)
        if got is None:  # unpaired release (defensive: never double-free)
            return
        t, charge = got
        # True-up: the admission charged prompt + FULL budget; refund the
        # budget tokens never emitted so a short completion is not billed
        # like a long one.  actual = prompt + emitted, never below 0.
        actual = (len(req.ids) + emitted) / self.weight(t)
        self._vtc[t] = max(0.0, self._vtc[t] - max(0.0, charge - actual))
        self._resident[t] = max(0, self._resident.get(t, 0) - 1)
        self._publish(t)


POLICIES: dict[str, type[Scheduler]] = {
    "alternate": Scheduler,
    "mixed": MixedScheduler,
}


def make_scheduler(name: str, **knobs: Any) -> Scheduler:
    """Build the named policy (``--schedule`` / ``RuntimeConfig.schedule``).
    Unknown names fail loudly — a typo'd schedule must not silently serve
    the default.  A speculative engine's ``mixed`` policy resolves to the
    :class:`SpecMixedScheduler` subclass (budget-aware spec rounds), and
    a ``tenant_weights``-carrying ``mixed`` policy to
    :class:`TenantScheduler` (weighted-fair tenant admission) — new
    scheduling behaviors land as subclasses here, not batcher branches."""
    tenant_weights = knobs.pop("tenant_weights", None)
    tenant_max_rows = knobs.pop("tenant_max_rows", None)
    tenant_fair = bool(tenant_weights) or tenant_max_rows is not None
    try:
        cls = POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown schedule {name!r}; known: {sorted(POLICIES)}"
        ) from None
    if tenant_fair:
        if cls is not MixedScheduler:
            raise ValueError(
                "tenant weighted-fair scheduling rides the mixed policy; "
                "use --schedule mixed (the default) with tenant weights"
            )
        if knobs.get("speculative"):
            raise ValueError(
                "tenant weighted-fair scheduling does not compose with "
                "speculative batching yet (the spec round ledger and the "
                "tenant counters would double-charge the budget); serve "
                "tenant-fair traffic through a plain engine"
            )
        return TenantScheduler(
            tenant_weights=parse_tenant_weights(tenant_weights),
            tenant_max_rows=tenant_max_rows, **knobs,
        )
    if knobs.get("speculative") and cls is MixedScheduler:
        cls = SpecMixedScheduler
    return cls(**knobs)
