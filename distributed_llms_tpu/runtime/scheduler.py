"""Scheduling policy for continuous batching (ROADMAP item 1).

Every scheduling DECISION the engine loop takes — admission order, prefill
chunk sizing against a per-step token budget, preemption victim selection,
the memory-pressure ladder, and the dispatch-ahead sync-trigger list —
lives here as a declared hook on a policy object, extracted from
``ContinuousBatcher`` (which had accreted them across PRs 1-12 as inline
branches of a 4k-line run loop).  The batcher owns MECHANISM (jitted
programs, pool bookkeeping, device carries); this module owns POLICY, and
the two meet only through the hooks in :data:`HOOKS` — so a new scheduling
behavior is a subclass here, not another branch in the run loop.

Two policies ship:

- ``mixed`` (default) — the stall-free fused token-budget step
  (Sarathi-Serve's chunked-prefill + decode coalescing at Orca's
  iteration-level granularity): pending prefill chunks become budgeted
  work INSIDE the decode step (``batcher.mixed_step`` — one compiled
  program runs K decode tokens for every active slot and up to
  ``token_budget - n_active`` prefill tokens), so resident decode rows
  never stall for a serialized prefill forward and the dispatch-ahead
  span keeps running while a long prompt admits.
- ``alternate`` — the PR-3..12 behavior: chunked prefills advance as
  their own ``prefill_chunk_step`` forwards serialized against
  ``decode_chunk``, and any pending prefill parks the overlap plane.

Both are byte-identical at temperature 0 (chunk splits and program fusion
change scheduling, never math — tests/runtime/test_mixed_step.py pins the
matrix), so ``--schedule`` is a latency knob, not a semantics knob.

Hooks are model-free by construction: they consume plain host data
(queues, tuples, counts) and return decisions, so policy unit tests run
without a model, a device, or a batcher.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Sequence

# The declared hook registry: hook name -> what the batcher delegates
# through it.  README's scheduler table is generated from this mapping and
# tests/runtime/test_mixed_step.py asserts every hook exists on every
# policy — adding a scheduling decision to the batcher without declaring
# its hook here is the drift this registry exists to catch.
HOOKS: dict[str, str] = {
    "admission_order":
        "which queued request admits next (priority desc, FIFO rid "
        "within a class; preempted resumes keep their original rid)",
    "chunk_threshold":
        "prompt length above which admission takes the chunked-prefill "
        "path instead of one monolithic forward",
    "prefill_bite":
        "prefill tokens the next step may consume, sized against the "
        "per-step token budget and the live decode row count",
    "fuse_prefill":
        "whether the pending prefill bite rides the decode step as one "
        "fused program (mixed) or runs as its own serialized forward",
    "select_victim":
        "which resident row preempts under pool pressure (lowest "
        "priority first, most recently admitted among equals)",
    "pressure_rungs":
        "the ordered memory-pressure ladder a dry pool escalates "
        "through before back-pressuring admission",
    "sync_triggers":
        "which conditions end a dispatch-ahead span (the overlap "
        "plane's host-sync decision list)",
    "spec_round_k":
        "per-row COMMIT bound for the next speculative round: the sum of "
        "committable tokens (k_row+1 per live row) is clamped against "
        "the per-step token budget and each row's acceptance-rate EMA "
        "feeds an adaptive downshift — a ledger/granularity bound; the "
        "compiled round's device work is constant (one compile key)",
}

# Rung names of the declared pressure ladder (PR-9's order).  "evict_spill"
# is implicit in pool accounting (available() counts evictable cached
# pages, spilling them to the host tier first); the preempt rungs gate
# whether a victim's pages swap out (byte-exact restore) or requeue for
# exact recompute; "back_pressure" is the terminal rung (admission waits).
PRESSURE_LADDER = (
    "evict_spill", "swap_preempt", "recompute_preempt", "back_pressure",
)


@dataclass(frozen=True)
class SyncView:
    """Host-state snapshot ``sync_triggers`` decides from — everything is
    deterministic scheduling state (never wall clocks), so a multi-process
    mesh evaluates identical views in lockstep.  ``grow_blocked`` is a
    thunk (page growth probes pool accounting and allocates from spare
    capacity) evaluated only when no cheaper trigger already fired."""

    any_active: bool          # last-known activity vector has a live row
    cancel_dirty: bool        # resident-row cancel taken mid-span
    queued: bool              # a request awaits admission
    kv_imports: bool          # a verified KV handoff awaits adoption
    prefills: int             # chunked prefills in flight (started)
    head_prefill_left: int    # prompt tokens the head prefill still owes
    #                           (after already-dispatched bites)
    live_budgets: tuple[int, ...]  # device-budget mirrors of live rows
    chunks_ahead: int         # chunks already dispatched this span
    grow_blocked: Callable[[], bool]  # paged growth needs PRESSURE


class Scheduler:
    """The ``alternate`` policy: chunked prefills advance as serialized
    ``prefill_chunk_step`` rounds (decode stalls for each bite) and any
    pending prefill parks the dispatch-ahead plane — exactly the PR-3..12
    inline behavior, now behind the declared hooks."""

    name = "alternate"

    def __init__(self, *, chunk_steps: int = 8,
                 prefill_chunk: int | None = None,
                 prefill_concurrency: int = 2,
                 token_budget: int | None = None,
                 speculative: bool = False,
                 spec_adaptive: bool = True) -> None:
        if token_budget is not None and token_budget < 1:
            raise ValueError(
                f"token_budget must be >= 1, got {token_budget}"
            )
        self.chunk_steps = chunk_steps
        self.prefill_chunk = prefill_chunk
        self.prefill_concurrency = prefill_concurrency
        self.token_budget = token_budget
        self.speculative = speculative
        self.spec_adaptive = spec_adaptive

    # -- admission order ---------------------------------------------------

    def admission_order(self, queue: Sequence[Any]) -> Any | None:
        """Highest priority first, FIFO (rid) within a priority.  A
        preempted request keeps its original rid, so it resumes ahead of
        later same-priority arrivals.  Deterministic in the queue contents
        alone, so multi-process meshes stay lockstep."""
        if not queue:
            return None
        return max(queue, key=lambda r: (r.priority, -r.rid))

    # -- chunk sizing against the token budget -----------------------------

    def chunk_threshold(self) -> int | None:
        """Prompts longer than this take the chunked path; None = every
        prompt admits monolithically.  Alternate chunks only when the
        operator configured ``prefill_chunk``."""
        return self.prefill_chunk

    def prefill_bite(self, remaining: int, n_active: int) -> int:
        """Prompt tokens the next prefill step consumes.  Alternate spends
        a full ``prefill_chunk`` per round regardless of how many decode
        rows it stalls — the over-spend the mixed policy exists to bound."""
        return min(remaining, self.prefill_chunk or remaining)

    def fuse_prefill(self) -> bool:
        """Alternate dispatches prefill bites as their own forwards."""
        return False

    # -- victim selection --------------------------------------------------

    def select_victim(self, candidates: Sequence[tuple[int, int, int]],
                      below_priority: int | None = None) -> int | None:
        """The row to preempt under pool pressure: lowest priority first,
        most-recently-admitted among equals (its lost work is smallest —
        vLLM's recompute-preemption policy).  ``candidates`` are
        ``(slot, priority, admit_seq)`` tuples for the preemptable rows;
        ``below_priority`` restricts to STRICTLY lower-priority victims
        (the admission path: a newcomer never preempts its own class,
        which would livelock two requests trading the same pages)."""
        best: int | None = None
        best_key: tuple[int, int] | None = None
        for slot, priority, admit_seq in candidates:
            if below_priority is not None and priority >= below_priority:
                continue
            key = (priority, -admit_seq)
            if best is None or key < best_key:
                best, best_key = slot, key
        return best

    # -- pressure ladder ---------------------------------------------------

    def pressure_rungs(self) -> tuple[str, ...]:
        """The ordered ladder a dry pool escalates through
        (:data:`PRESSURE_LADDER`).  The batcher consults membership:
        dropping ``swap_preempt`` from a policy would send every victim
        straight to exact recompute."""
        return PRESSURE_LADDER

    # -- speculative round sizing ------------------------------------------

    def spec_round_k(self, k_max: int, emas: Sequence[float],
                     n_active: int) -> list[int]:
        """Per-row draft length for the next speculative round.  The
        alternate policy never downshifts: every row drafts the full k
        (the PR-6..16 behavior), and the batcher's traced clamp is inert.
        ``emas`` is one acceptance-rate EMA per batch slot (1.0 for
        non-live slots)."""
        return [k_max] * len(emas)

    # -- overlap sync triggers ---------------------------------------------

    def sync_triggers(self, view: SyncView) -> list[str]:
        """The conditions that END a dispatch-ahead span (empty list =
        the next chunk may dispatch from the device-resident carry).
        THE sync-trigger list (README "Engine overlap"):

        - ``all_idle``: every row already idle as of the last-known
          activity vector — never chain behind a possibly-all-idle chunk;
        - ``cancel``: a resident-row cancel taken while the carry was
          device-resident;
        - ``queued`` / ``kv_import``: admission work is waiting;
        - ``prefill``: a chunked prefill is in flight (alternate parks
          the overlap plane for the whole prefill; the mixed policy
          narrows this to the finishing splice);
        - ``budget_certain``: every live row will have exhausted its
          budget within the chunks already dispatched — the next chunk
          could only be a ghost;
        - ``page_pressure``: a row near its page horizon could not grow
          from spare pool capacity (preemption must run on fresh
          mirrors).
        """
        out: list[str] = []
        if not view.any_active:
            out.append("all_idle")
        if view.cancel_dirty:
            out.append("cancel")
        if view.queued:
            out.append("queued")
        if view.kv_imports:
            out.append("kv_import")
        if view.prefills:
            out.append(self._prefill_trigger(view))
        if self._budget_certain(view):
            out.append("budget_certain")
        out = [t for t in out if t]
        if not out and view.grow_blocked():
            out.append("page_pressure")
        return out

    def _prefill_trigger(self, view: SyncView) -> str | None:
        return "prefill"

    def _budget_certain(self, view: SyncView) -> bool:
        """Whether every live row will be done within the chunks already
        dispatched.  Plain chunks commit exactly ``chunk_steps`` tokens
        per active row; a speculative round commits at least one.  EOS
        finishes are not host-predictable, so a rare ghost behind an EOS
        remains (it pads nothing into the stream)."""
        per_chunk = 1 if self.speculative else self.chunk_steps
        return all(
            b <= view.chunks_ahead * per_chunk for b in view.live_budgets
        )


class MixedScheduler(Scheduler):
    """The ``mixed`` policy: one fused token-budget step.  Pending prefill
    chunks become budgeted work INSIDE the decode step — each dispatch
    runs K decode tokens for every active slot plus up to
    ``token_budget - n_active`` prompt tokens of the head pending prefill
    in the same compiled program — so decode never stalls for a
    serialized prefill forward, and a pending prefill no longer parks
    the dispatch-ahead span (it syncs only for the finishing splice,
    which is an admission decision).  With ``token_budget`` unset the
    bite falls back to ``prefill_chunk`` (fusion without re-budgeting);
    with it set, prompts longer than the budget auto-chunk even when
    ``prefill_chunk`` was never configured."""

    name = "mixed"

    def chunk_threshold(self) -> int | None:
        if self.prefill_chunk is not None:
            return self.prefill_chunk
        if self.token_budget is not None and not self.speculative:
            # Auto-chunk: any prompt the budget cannot cover in one step
            # takes the fused path (speculative admission stays
            # monolithic — its draft prefill cannot chunk).
            return self.token_budget
        return None

    def prefill_bite(self, remaining: int, n_active: int) -> int:
        if self.token_budget is None:
            return super().prefill_bite(remaining, n_active)
        # Decode rows claim their legs first; the floor of 1 keeps a
        # fully-busy batch from starving the prefill outright (one token
        # per step still makes progress toward the finishing splice).
        return min(remaining, max(1, self.token_budget - n_active))

    def fuse_prefill(self) -> bool:
        return True

    def _prefill_trigger(self, view: SyncView) -> str | None:
        # A prefill with work left feeds the NEXT fused chunk — keep
        # dispatching ahead.  Only the finishing splice (an admission:
        # first-token sample + pool scatter, a host decision) syncs.
        return None if view.head_prefill_left > 0 else "prefill_finish"


class SpecMixedScheduler(MixedScheduler):
    """Budget-aware speculative rounds — the ``mixed`` policy a
    speculative engine schedules under (selected by :func:`make_scheduler`
    when ``speculative=True``).  A round charges ``k_row+1`` committable
    tokens per live row against ``token_budget``, so two clamps size each
    row's commit bound:

    - BUDGET (engine-wide): k_row shrinks until the round's committable
      sum fits the per-step budget — the scheduler's ledger stays
      consistent and a round never commits (or delivers) more tokens
      than the budget, keeping cancel/deadline cadence bounded;
    - ACCEPTANCE (per row): each row's acceptance-rate EMA scales its
      bound (``max(1, round(ema * k_max))``) — a cold draft's commits
      shrink toward plain-decode granularity.

    These are LEDGER bounds, not compute savers: the compiled round
    always runs the full ``k_max``-step draft scan and ``k_max+1``-token
    verify (static shapes are what keep the whole ladder on ONE compile
    key — graftcheck GC4 ``batcher.spec_chunk_paged``), so clamping
    discards already-verified tokens rather than skipping work.
    Skipping a genuinely cold row's round entirely (dispatching the
    plain decode program instead) is the compute-saving follow-up; see
    ROADMAP.  Both clamps reach the compiled round as ONE traced [B]
    vector (``spec_chunk``'s ``k_row``), and the forced stop emits the
    target's own token — streams stay byte-exact at any clamp (only
    arrival granularity changes).
    """

    name = "mixed"

    def spec_round_k(self, k_max: int, emas: Sequence[float],
                     n_active: int) -> list[int]:
        if not self.spec_adaptive:
            return [k_max] * len(emas)
        kb = k_max
        if self.token_budget is not None and n_active:
            while kb > 1 and n_active * (kb + 1) > self.token_budget:
                kb -= 1
        return [
            min(kb, max(1, int(e * k_max + 0.5))) for e in emas
        ]


POLICIES: dict[str, type[Scheduler]] = {
    "alternate": Scheduler,
    "mixed": MixedScheduler,
}


def make_scheduler(name: str, **knobs: Any) -> Scheduler:
    """Build the named policy (``--schedule`` / ``RuntimeConfig.schedule``).
    Unknown names fail loudly — a typo'd schedule must not silently serve
    the default.  A speculative engine's ``mixed`` policy resolves to the
    :class:`SpecMixedScheduler` subclass (budget-aware spec rounds) — new
    scheduling behaviors land as subclasses here, not batcher branches."""
    try:
        cls = POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown schedule {name!r}; known: {sorted(POLICIES)}"
        ) from None
    if knobs.get("speculative") and cls is MixedScheduler:
        cls = SpecMixedScheduler
    return cls(**knobs)
