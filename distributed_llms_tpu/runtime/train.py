"""Training step: next-token cross-entropy + optax optimizer, mesh-parallel.

The reference has no training path at all (no gradients, no optimizer —
SURVEY "What it is"), but a TPU-native framework's parallel layers must be
differentiable end-to-end: the pipeline schedule (parallel/pipeline.py) is a
pure ``lax.scan`` over ``ppermute`` hops, so ``jax.grad`` derives the
backward pipeline automatically, and GSPMD handles gradient collectives for
the tensor/data axes.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax

from ..core.config import ModelConfig
from ..models import model as model_lib
from ..parallel.api import ParallelModel

Params = Any


def cross_entropy_loss(
    logits: jax.Array,  # [B, T, V] float32
    targets: jax.Array,  # [B, T] int32
    mask: jax.Array | None = None,  # [B, T] float/bool; 0 => ignore
) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        return -jnp.mean(ll)
    mask = mask.astype(jnp.float32)
    return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def lm_loss(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B, T+1]: input = [:, :-1], target = [:, 1:]
    mask: jax.Array | None = None,
    forward_fn: Any = None,
    remat: bool = False,
) -> jax.Array:
    """Next-token CE; MoE models additionally get the Switch-style
    load-balance aux term (cfg.moe_aux_loss_weight) so the router cannot
    collapse onto a few experts and capacity-drop the rest."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    fwd = forward_fn if forward_fn is not None else model_lib.forward
    if cfg.num_experts > 0:
        logits, _, aux = fwd(params, cfg, inputs, remat=remat, return_aux=True)
    else:
        logits, _ = fwd(params, cfg, inputs, remat=remat)
        aux = 0.0
    tmask = mask[:, 1:] if mask is not None else None
    return cross_entropy_loss(logits, targets, tmask) + cfg.moe_aux_loss_weight * aux


@dataclass
class Trainer:
    """Holds optimizer + compiled step.  Works single-device or over a mesh
    (pass a ParallelModel)."""

    cfg: ModelConfig
    optimizer: optax.GradientTransformation
    parallel: ParallelModel | None = None
    remat: bool = False

    def init(self, params: Params) -> Any:
        return self.optimizer.init(params)

    def make_step(self):
        """Returns jitted (params, opt_state, tokens, mask) ->
        (params, opt_state, loss)."""
        cfg = self.cfg
        pm = self.parallel
        remat = self.remat

        def fwd(params, cfg, inputs, remat=False, return_aux=False):
            if pm is None:
                return model_lib.forward(params, cfg, inputs, remat=remat, return_aux=return_aux)
            return pm.forward(params, inputs, remat=remat, return_aux=return_aux)

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(params, opt_state, tokens, mask):
            loss, grads = jax.value_and_grad(lm_loss)(
                params, cfg, tokens, mask, forward_fn=fwd, remat=remat
            )
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return step


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.01) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(lr, weight_decay=weight_decay),
    )
