"""Decode sessions with host-DRAM KV spill (SURVEY §7 hard part 3).

The reference kept no state between inference calls (each RUN_INFERENCE was
a fresh placeholder matmul).  Here a *session* keeps its KV cache alive
across turns — continuation prefills only the new chunk — and a bounded
number of sessions stay HBM-resident: the rest are spilled to host DRAM and
restored by ``jax.device_put`` (async; the transfer overlaps the current
request's compute) when the conversation resumes.  This is what makes the
13B-on-8-stages budget work: weights own most of HBM, idle conversations
don't.

Cache layout note: every session's cache is allocated at a fixed
``max_len`` so the jitted step function compiles once per (batch, chunk,
steps) shape, not per history length.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import ModelConfig
from ..core.observability import METRICS, get_logger
from ..models import model as model_lib
from . import sampling

log = get_logger("session")


def continuation_mask(
    valid_mask: jax.Array,  # [B, S] (or [1, S]) prior-content slots
    base: jax.Array,  # scalar int32 — first padded slot of the new chunk
    t: int,  # chunk length (padded)
    slots: jax.Array,  # [S] = arange(S)
) -> jax.Array:
    """[B, 1, T, S] attention mask for prefilling a chunk at slots
    [base, base+t) against existing cache content: query i attends prior
    valid slots plus chunk slots j <= i (right padding means pad slots have
    j greater than every real query's i).  Shared by session continuation
    and the continuous batcher's prefix-cached admission."""
    rel = slots[None, :] - base  # [1, S]: slot index within the chunk
    chunk_causal = (rel[:, None, :] >= 0) & (
        rel[:, None, :] <= jnp.arange(t, dtype=jnp.int32)[None, :, None]
    )  # [1, T, S]
    return (valid_mask[:, None, :] | chunk_causal)[:, None, :, :]


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "max_new_tokens", "temperature", "top_k", "top_p", "eos_id",
        "pad_id", "forward_fn",
    ),
)
def session_step(
    params: Any,
    cfg: ModelConfig,
    chunk: jax.Array,  # [B, T] int32 new tokens, right-padded
    chunk_lens: jax.Array,  # [B] int32 true lengths
    real_lens: jax.Array,  # [B] int32 tokens already in the session (RoPE base)
    valid_mask: jax.Array,  # [B, S] bool — cache slots holding prior turns
    cache: Any,  # KVCache sized S = session max_len
    base: jax.Array,  # scalar int32 — first free padded cache slot
    rng: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int = -1,
    pad_id: int = 0,
    forward_fn: Any = None,
    slot_positions: jax.Array | None = None,  # [B, S] true RoPE position of
    #   each cache slot — REQUIRED session state for sliding-window models:
    #   the padded multi-turn layout makes slot != position, and the map
    #   depends on every prior turn's base/lens, so it must be carried, not
    #   recomputed.  None for global-attention models.
) -> tuple[jax.Array, Any, jax.Array, jax.Array, jax.Array | None]:
    """Append a chunk to the session and decode.

    Generalizes runtime.generate.generate_tokens: the one-shot case is
    ``base=0, valid_mask=zeros, real_lens=zeros``.  The two are deliberately
    NOT merged — one-shot prefill passes attn_mask=None, which unlocks the
    flash kernel's prefill path, while continuation needs the explicit
    prior-turn mask; tests/runtime/test_session.py pins their equivalence
    (any decode-loop change must land in both).  All rows write the chunk
    at the same padded slots [base, base+T) (single dynamic_update_slice);
    per-row masks keep attention on real slots only; per-row positions
    (``real_lens + i``) keep RoPE/learned-pos correct across turns.

    Returns (new_tokens [B, N], cache, valid_mask', real_lens',
    slot_positions' | None).
    """
    if forward_fn is None:
        forward_fn = _default_forward
    b, t = chunk.shape
    s = cache.k.shape[-3]  # [..., B, S, KVH, HD] -> S
    slots = jnp.arange(s, dtype=jnp.int32)  # [S]

    windowed = cfg.sliding_window is not None
    if windowed and slot_positions is None:
        raise ValueError(
            "sliding-window sessions need the slot_positions state (the "
            "padded multi-turn layout makes slot != position; engine "
            "sessions allocate and carry it)"
        )

    # --- chunk prefill at padded slots [base, base+t)
    positions = real_lens[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    mask = continuation_mask(valid_mask, base, t, slots)  # [B,1,T,S]
    chunk_kw = {}
    if windowed:
        rel0 = slots[None, :] - base  # [1, S]
        slot_positions = jnp.where(
            (rel0 >= 0) & (rel0 < t),
            real_lens[:, None] + jnp.clip(rel0, 0, t - 1), slot_positions,
        )
        chunk_kw["key_positions"] = slot_positions
    logits, cache = forward_fn(
        params, cfg, chunk, positions=positions, cache=cache,
        cache_index=base, attn_mask=mask, **chunk_kw,
    )
    last_idx = jnp.maximum(chunk_lens - 1, 0)
    next_logits = jnp.take_along_axis(logits, last_idx[:, None, None], axis=1)[:, 0]

    # slots valid after the chunk: prior turns + this chunk's real tokens
    rel = slots[None, :] - base  # [1, S]: slot index within the chunk
    chunk_valid = (rel >= 0) & (rel < chunk_lens[:, None])  # [B, S]
    valid_after_chunk = valid_mask | chunk_valid
    real_after_chunk = real_lens + chunk_lens

    gen_base = base + t  # padded slot where generated tokens start
    gen_kw = {}
    if windowed:
        # Generated slot gen_base + j holds position real_after_chunk + j —
        # fill the whole gen region once (slots past the current step are
        # masked invalid, so early values are never consulted).
        gen_rel = slots[None, :] - gen_base
        slot_positions = jnp.where(
            gen_rel >= 0, real_after_chunk[:, None] + gen_rel, slot_positions
        )
        gen_kw["key_positions"] = slot_positions

    def step(carry, inputs):
        cache, cur_logits, done = carry
        j, rng_step = inputs
        tok = sampling.sample(rng_step, cur_logits, temperature, top_k, top_p)
        tok = jnp.where(done, jnp.int32(pad_id), tok)
        if eos_id >= 0:
            done = jnp.logical_or(done, tok == eos_id)
        gen_valid = (slots[None, :] >= gen_base) & (slots[None, :] <= gen_base + j)
        mask = (valid_after_chunk | gen_valid)[:, None, None, :]
        positions = (real_after_chunk + j)[:, None]
        logits, new_cache = forward_fn(
            params, cfg, tok[:, None],
            positions=positions, cache=cache, cache_index=gen_base + j,
            attn_mask=mask, **gen_kw,
        )
        return (new_cache, logits[:, 0], done), tok

    rngs = jax.random.split(rng, max_new_tokens)
    steps = jnp.arange(max_new_tokens, dtype=jnp.int32)
    done0 = jnp.zeros((b,), dtype=bool)
    (cache, _, _), toks = jax.lax.scan(step, (cache, next_logits, done0), (steps, rngs))
    toks = toks.T  # [B, N]

    gen_valid_final = (slots[None, :] >= gen_base) & (
        slots[None, :] < gen_base + max_new_tokens
    )
    valid_final = valid_after_chunk | gen_valid_final
    real_final = real_after_chunk + max_new_tokens
    return toks, cache, valid_final, real_final, (
        slot_positions if windowed else None
    )


def _default_forward(params, cfg, tokens, positions=None, cache=None,
                     cache_index=None, attn_mask=None, key_positions=None):
    return model_lib.forward(
        params, cfg, tokens, positions=positions, cache=cache,
        cache_index=cache_index, attn_mask=attn_mask,
        key_positions=key_positions,
    )


# ---------------------------------------------------------------------------
# Session state + host spill
# ---------------------------------------------------------------------------

@dataclass
class Session:
    sid: str
    cache: Any  # KVCache (device) when resident; _HostCache when spilled
    valid_mask: jax.Array
    real_lens: jax.Array
    base: int  # next free padded slot (python int — static per call shape)
    max_len: int
    n_real: int = 0  # caller's row count (rest is mesh-divisibility padding)
    # [B, S] true RoPE position per cache slot — sliding-window models only
    # (session_step carries it turn to turn; None for global attention).
    slot_positions: jax.Array | None = None
    last_used: float = field(default_factory=time.monotonic)

    @property
    def spilled(self) -> bool:
        return isinstance(self.cache, _HostCache)


@dataclass
class _HostCache:
    """KV leaves moved to host memory, shardings remembered for restore."""

    leaves: list[np.ndarray]
    treedef: Any
    shardings: list[Any]

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.leaves)


class SessionManager:
    """LRU residency manager: at most ``max_resident`` session caches live in
    device memory; the rest live in host DRAM until their next turn."""

    def __init__(self, max_resident: int = 4) -> None:
        if max_resident < 1:
            raise ValueError(f"max_resident must be >= 1, got {max_resident}")
        self.max_resident = max_resident
        self.sessions: dict[str, Session] = {}
        self._counter = 0

    # -- bookkeeping -------------------------------------------------------

    def new_session(self, cache: Any, valid_mask, real_lens, base: int,
                    max_len: int) -> Session:
        self._counter += 1
        sid = f"session-{self._counter}"
        sess = Session(sid, cache, valid_mask, real_lens, base, max_len)
        self.sessions[sid] = sess
        self._enforce_residency(keep=sid)
        return sess

    def get(self, sid: str) -> Session:
        sess = self.sessions.get(sid)
        if sess is None:
            raise KeyError(f"unknown session {sid!r}")
        return sess

    def touch(self, sess: Session) -> None:
        sess.last_used = time.monotonic()
        self._enforce_residency(keep=sess.sid)

    def drop(self, sid: str) -> None:
        self.sessions.pop(sid, None)
        self._update_gauges()

    # -- spill / restore ---------------------------------------------------

    def make_room(self, keep: str | None = None) -> None:
        """Spill LRU residents until one more cache can come in WITHOUT
        exceeding max_resident — called *before* allocating or restoring a
        cache, so peak device memory never holds max_resident + 1 caches
        (the regime kv_host_spill exists for has no slack for that)."""
        resident = sorted(
            (s for s in self.sessions.values() if not s.spilled),
            key=lambda s: s.last_used,
        )
        excess = len(resident) - (self.max_resident - 1)
        for sess in resident:
            if excess <= 0:
                break
            if sess.sid == keep:
                continue
            log.info("spilling %s to host to make room", sess.sid)
            self._spill(sess)
            excess -= 1

    def ensure_resident(self, sess: Session) -> None:
        """Restore a spilled cache onto its original shardings (making room
        first).  device_put is asynchronous — the H2D copy overlaps whatever
        is queued ahead."""
        if not sess.spilled:
            return
        self.make_room(keep=sess.sid)
        hc: _HostCache = sess.cache
        leaves = [
            jax.device_put(a, s) if s is not None else jax.device_put(a)
            for a, s in zip(hc.leaves, hc.shardings)
        ]
        sess.cache = jax.tree.unflatten(hc.treedef, leaves)
        METRICS.inc("kv_spill.restores")
        self._update_gauges()

    def _spill(self, sess: Session) -> None:
        leaves, treedef = jax.tree.flatten(sess.cache)
        shardings = [getattr(a, "sharding", None) for a in leaves]
        host = [np.asarray(a) for a in leaves]  # D2H; frees HBM refs
        sess.cache = _HostCache(host, treedef, shardings)
        METRICS.inc("kv_spill.spills")
        self._update_gauges()

    def _enforce_residency(self, keep: str) -> None:
        resident = [s for s in self.sessions.values() if not s.spilled]
        resident.sort(key=lambda s: s.last_used)
        excess = len(resident) - self.max_resident
        for sess in resident:
            if excess <= 0:
                break
            if sess.sid == keep:
                continue
            log.info("spilling %s to host (%d resident > %d)",
                     sess.sid, len(resident), self.max_resident)
            self._spill(sess)
            excess -= 1
        self._update_gauges()

    def _update_gauges(self) -> None:
        host_bytes = sum(
            s.cache.nbytes for s in self.sessions.values() if s.spilled
        )
        METRICS.set_gauge("kv_spill.host_bytes", host_bytes)
        METRICS.set_gauge(
            "kv_spill.resident_sessions",
            sum(1 for s in self.sessions.values() if not s.spilled),
        )
        METRICS.set_gauge("kv_spill.spilled_sessions",
                      sum(1 for s in self.sessions.values() if s.spilled))
