"""Autoregressive generation: jitted prefill + lax.scan decode loop.

This is the real replacement for the reference's RUN_INFERENCE path
(src/master/node.py:227-277 -> src/worker/node.py:218-238), which did one
placeholder matmul per worker and returned the first worker's raw partial
(defect D9).  Here: prefill fills the KV cache for the whole (right-padded)
prompt in one pass, then a ``lax.scan`` emits one token per step with
EOS-aware freezing — all inside a single jit, static shapes throughout.

Ragged batches: prompts are right-padded to T.  Every decode step writes all
rows' K/V at the *same* cache slot (T + step) so the update is a single
``dynamic_update_slice``; per-row token positions (``prompt_lens + step``)
feed RoPE / learned position embeddings, and an explicit attention mask keeps
each row attending only its own real prompt slots plus generated slots.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from ..core.config import ModelConfig, RuntimeConfig
from ..models import model as model_lib
from . import sampling


def _default_forward(params, cfg, tokens, positions=None, cache=None, cache_index=None, attn_mask=None, key_positions=None):
    return model_lib.forward(
        params, cfg, tokens, positions=positions, cache=cache,
        cache_index=cache_index, attn_mask=attn_mask,
        key_positions=key_positions,
    )


def window_key_positions(t: int, prompt_lens: jax.Array, max_len: int) -> jax.Array:
    """[B, S] true RoPE position of every cache slot under THE right-padded
    generate layout (prompt slots 0..t-1, generated token j at slot t+j,
    position len+j) — the single definition of the slot->position map the
    sliding-window mask needs (models.model._attention key_positions).
    Shared by generate_tokens and runtime.speculative."""
    slots = jnp.arange(max_len, dtype=jnp.int32)
    return jnp.where(
        slots[None, :] < t, slots[None, :],
        prompt_lens[:, None] + (slots[None, :] - t),
    )


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "max_new_tokens", "temperature", "top_k", "top_p", "eos_id",
        "pad_id", "forward_fn", "make_cache", "decode_fn",
    ),
)
def generate_tokens(
    params: Any,
    cfg: ModelConfig,
    prompt: jax.Array,  # [B, T] int32, right-padded with pad_id
    prompt_lens: jax.Array,  # [B] int32 true lengths
    rng: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    eos_id: int = -1,  # -1 => never stops early
    pad_id: int = 0,
    forward_fn: Any = None,  # (params, cfg, tokens, positions=, cache=, cache_index=, attn_mask=) -> (logits, cache)
    make_cache: Any = None,  # (cfg, batch, max_len) -> KVCache
    decode_fn: Any = None,  # fused decode loop (ParallelModel.as_decode_fn())
) -> jax.Array:
    """Generate.  Returns new tokens [B, max_new_tokens] int32; positions
    after a sequence's EOS are filled with pad_id.

    ``forward_fn``/``make_cache`` default to the single-device model; a
    mesh-parallel model (parallel.api.ParallelModel) plugs in its own.

    runtime.session.session_step is the multi-turn generalization of this
    loop; the pair is deliberately unmerged (this prefill's attn_mask=None
    unlocks the flash kernel) and pinned equivalent by
    tests/runtime/test_session.py — decode-loop changes must land in both.

    The KV cache is sized T + max_new_tokens exactly, so the
    ``cache_index + T <= max_len`` contract of models.model.forward holds by
    construction.
    """
    if forward_fn is None:
        forward_fn = _default_forward
    if make_cache is None:
        make_cache = model_lib.init_cache
    b, t = prompt.shape
    max_len = t + max_new_tokens
    cache = make_cache(cfg, b, max_len, prompt_len=t)

    # --- prefill: causal attention over prompt slots (pad queries produce
    # garbage but nothing reads their logits; pad K/V slots are masked during
    # decode via the explicit mask below).
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
    logits, cache = forward_fn(
        params, cfg, prompt, positions=positions, cache=cache, cache_index=jnp.int32(0)
    )
    last_idx = jnp.maximum(prompt_lens - 1, 0)
    next_logits = jnp.take_along_axis(logits, last_idx[:, None, None], axis=1)[:, 0]

    if decode_fn is not None:
        # Fused wavefront decode (pipelined models): the whole loop runs as
        # one schedule that never drains the pipeline between tokens —
        # max(M, P) ticks per token round instead of M + P - 1.
        rng0, rng_loop = jax.random.split(rng)
        tok0 = sampling.sample(rng0, next_logits, temperature, top_k, top_p)
        return decode_fn(
            params, tok0, prompt_lens, t, cache, rng_loop, max_new_tokens,
            temperature, top_k, top_p, eos_id, pad_id,
        )

    slots = jnp.arange(max_len, dtype=jnp.int32)  # [S]
    prompt_valid = slots[None, :] < prompt_lens[:, None]  # [B, S]
    # Sliding-window models: the decode mask below carries causality and
    # validity in SLOT space, but the window bound compares RoPE POSITIONS —
    # and in this right-padded layout generated slot T+j sits at position
    # len+j.  Hand the true slot->position map to the forward or the window
    # silently widens by the pad amount (models.model._attention).
    win_kwargs = {}
    if cfg.sliding_window is not None:
        win_kwargs["key_positions"] = window_key_positions(t, prompt_lens, max_len)

    def step(carry, inputs):
        cache, cur_logits, done = carry
        j, rng_step = inputs
        tok = sampling.sample(rng_step, cur_logits, temperature, top_k, top_p)
        tok = jnp.where(done, jnp.int32(pad_id), tok)
        if eos_id >= 0:
            done = jnp.logical_or(done, tok == eos_id)
        # Valid keys: real prompt slots + generated slots up to and including
        # this step's write slot (t + j).
        gen_valid = jnp.logical_and(slots[None, :] >= t, slots[None, :] <= t + j)
        mask = jnp.logical_or(prompt_valid, gen_valid)[:, None, None, :]  # [B,1,1,S]
        positions = (prompt_lens + j)[:, None]  # [B, 1]
        logits, new_cache = forward_fn(
            params, cfg, tok[:, None],
            positions=positions, cache=cache, cache_index=t + j, attn_mask=mask,
            **win_kwargs,
        )
        return (new_cache, logits[:, 0], done), tok

    rngs = jax.random.split(rng, max_new_tokens)
    steps = jnp.arange(max_new_tokens, dtype=jnp.int32)
    done0 = jnp.zeros((b,), dtype=bool)
    _, toks = jax.lax.scan(step, (cache, next_logits, done0), (steps, rngs))
    return toks.T  # [B, N]


def check_sequence_budget(
    prompt_len: int, max_new_tokens: int, rt: RuntimeConfig, cfg: ModelConfig
) -> None:
    """Shared guard: prompt + decode budget must fit both the runtime limit
    and the model's position table (GPT-2 wpe indexes OOB -> NaN fill)."""
    limit = min(rt.max_seq_len, cfg.max_seq_len)
    if prompt_len + max_new_tokens > limit:
        raise ValueError(
            f"prompt len {prompt_len} + {max_new_tokens} new tokens exceeds "
            f"sequence limit {limit} (min of runtime {rt.max_seq_len} and "
            f"model {cfg.max_seq_len})"
        )


def generate(
    params: Any,
    cfg: ModelConfig,
    rt: RuntimeConfig,
    prompt: jax.Array,
    prompt_lens: jax.Array | None = None,
    rng: jax.Array | None = None,
    eos_id: int = -1,
    pad_id: int = 0,
) -> jax.Array:
    """Convenience wrapper binding knobs from a RuntimeConfig."""
    b, t = prompt.shape
    if prompt_lens is None:
        prompt_lens = jnp.full((b,), t, dtype=jnp.int32)
    if rng is None:
        rng = jax.random.key(rt.seed)
    check_sequence_budget(t, rt.max_decode_steps, rt, cfg)
    return generate_tokens(
        params, cfg, prompt, prompt_lens, rng,
        max_new_tokens=rt.max_decode_steps,
        temperature=rt.temperature, top_k=rt.top_k, top_p=rt.top_p,
        eos_id=eos_id, pad_id=pad_id,
    )
