"""JAX version-compat shims for the seq-parallel / Pallas stack.

The package targets the jax >= 0.5 surface (``jax.shard_map``,
``jax.lax.axis_size``, ``jax.lax.pcast`` vma tracking, ``jax.typeof``).
Older runtimes (0.4.x) still ship everything we need — shard_map lives in
``jax.experimental.shard_map`` and vma tracking simply does not exist —
so each symbol here resolves to the native API when present and to a
semantically-equivalent fallback otherwise:

- :func:`shard_map`: native ``jax.shard_map`` (``axis_names=`` kwarg), or
  the experimental one with ``axis_names`` translated to its ``auto=``
  complement and replication checking off (pre-vma shard_map rejects
  programs written for the explicit-pcast world).
- :func:`axis_size`: ``lax.axis_size``, or the classic ``psum(1, axis)``
  trick (statically evaluated to the bound axis size).
- :func:`pcast`: identity when vma tracking doesn't exist — there is
  nothing to cast.
- :func:`vma_of`: the value's varying-manual-axes set (empty pre-vma).
- :func:`shape_dtype_struct`: ``jax.ShapeDtypeStruct`` minus the ``vma=``
  kwarg on runtimes whose constructor predates it.

Every shim is exercised by tools/graftcheck, which traces the real ops
under fake meshes on whatever JAX the image carries.
"""

from __future__ import annotations

from typing import Any

import jax

__all__ = [
    "HAS_VMA", "axis_size", "def_partition", "pcast", "shape_dtype_struct",
    "shard_map", "vma_of",
]

# vma (varying manual axes) tracking arrived with the jax 0.6-era shard_map;
# pcast is its cast operator, so its presence is the feature probe.
HAS_VMA = hasattr(jax.lax, "pcast")


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name) -> int:
        # psum of a Python literal folds statically to the axis size (and
        # raises NameError on an unbound axis, matching lax.axis_size).
        return jax.lax.psum(1, axis_name)


if HAS_VMA:
    pcast = jax.lax.pcast
else:
    def pcast(x, axis_name, to="varying"):
        del axis_name, to  # no vma types to move between
        return x


def vma_of(x) -> frozenset:
    """Varying-manual-axes of a value (empty on pre-vma runtimes)."""
    if hasattr(jax, "typeof"):
        return frozenset(getattr(jax.typeof(x), "vma", frozenset()))
    return frozenset()


def def_partition(f, *, partition, infer_sharding_from_operands,
                  sharding_rule: str | None = None) -> None:
    """``custom_partitioning.def_partition`` with the Shardy factor rule
    attached only on runtimes whose signature takes it (jax >= 0.5).
    The 0.4.x GSPMD partitioner ignores Shardy rules entirely, so
    dropping the kwarg there is semantically the same registration —
    passing it raises TypeError instead (the bug that silently disarmed
    the quant-matmul SPMD wrapper on this runtime)."""
    import inspect

    kwargs = {}
    if sharding_rule is not None and "sharding_rule" in inspect.signature(
            f.def_partition).parameters:
        kwargs["sharding_rule"] = sharding_rule
    f.def_partition(
        partition=partition,
        infer_sharding_from_operands=infer_sharding_from_operands,
        **kwargs,
    )


def shape_dtype_struct(shape, dtype, vma: frozenset = frozenset()):
    if HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def shard_map(f, *, mesh, in_specs, out_specs, axis_names: Any = None,
              check_vma: bool | None = None):
    """``jax.shard_map`` with the jax < 0.5 experimental fallback.

    ``axis_names`` follows the native semantics: the manual axes of the
    body; every other mesh axis stays GSPMD-auto inside.  None = all axes
    manual.  ``check_vma`` is forwarded only where the native API takes it
    (pre-vma shard_map has check_rep instead, which rejects programs
    written for the explicit-pcast world — the fallback disables it).
    """
    if hasattr(jax, "shard_map"):
        import inspect

        native = set(inspect.signature(jax.shard_map).parameters)
        kw: dict[str, Any] = {}
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        if check_vma is not None and "check_vma" in native:
            kw["check_vma"] = check_vma
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    kw = {}
    if axis_names is not None:
        # Only axes that actually shard anything need to stay GSPMD-auto;
        # size-1 axes are manual no-ops, and dropping them usually empties
        # ``auto`` entirely (partial-auto is NotImplemented in the old
        # shard_map for most collectives).
        auto = frozenset(
            a for a in mesh.axis_names
            if a not in frozenset(axis_names) and dict(mesh.shape).get(a, 1) > 1
        )
        if auto:
            kw["auto"] = auto
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False, **kw
    )
