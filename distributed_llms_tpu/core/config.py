"""Typed configuration system.

The reference hard-codes every knob (ports at src/master/node.py:15 and
src/worker/node.py:35, model id and shard count at run_master.py:17, heartbeat
period at src/worker/node.py:273, timeouts at src/master/node.py:117 and
src/network/protocol.py:77) and its planned YAML/JSON config system
(plan.md:70-73) never landed.  Here every knob lives in one typed dataclass
tree, loadable from JSON/YAML files or CLI-style ``key=value`` overrides.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any

try:  # yaml is available in the image; gate anyway.
    import yaml

    _HAVE_YAML = True
except Exception:  # pragma: no cover
    _HAVE_YAML = False


_ATTN_IMPLS = {"dot", "ring", "flash", "ulysses"}


@dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters for a decoder-only transformer.

    One dataclass covers every supported family (GPT-2, OPT, TinyLlama,
    Llama-2, Llama-3, Mixtral); ``family`` selects the block flavour
    (LayerNorm+learned-pos vs RMSNorm+RoPE+GQA).  "opt" is the gpt2 layout
    with separate q/k/v projections folded in conversion, a ReLU MLP, and
    HF OPT's position-table offset of 2 — the reference's own default model
    (run_master.py:17, facebook/opt-125m).
    """

    family: str = "gpt2"  # "gpt2" | "opt" | "llama" | "neox"
    vocab_size: int = 50257
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: int = 12  # < num_heads => grouped-query attention
    head_dim: int | None = None  # default hidden_size // num_heads
    max_seq_len: int = 1024
    rope_theta: float = 10000.0
    # Llama-3.1-style rope scaling ("rope_type": "llama3"): piecewise
    # frequency rescale that stretches low-frequency (long-wavelength)
    # components by `factor` while keeping high-frequency ones, with a
    # smooth ramp between — how 3.1/3.2 extend 8k-trained RoPE to 128k.
    # factor == 1.0 disables (plain RoPE).  Other HF rope_type values
    # (linear, dynamic, yarn, longrope) are rejected at convert.
    rope_scaling_factor: float = 1.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_len: int = 8192
    # GPT-NeoX/Pythia: rotate only the first rotary_pct of each head's dims
    # (partial rotary); the rest pass through position-free.
    rotary_pct: float = 1.0
    # GPT-NeoX/Pythia parallel residual: x + attn(ln1 x) + mlp(ln2 x)
    # (HF use_parallel_residual; False = sequential pre-LN like GPT-2).
    parallel_residual: bool = False
    norm_eps: float = 1e-5
    tie_embeddings: bool = True
    dtype: str = "bfloat16"
    # MLP activation for the gpt2-layout families ("gelu" for GPT-2, "relu"
    # for OPT); the llama family is SwiGLU regardless.
    activation: str = "gelu"
    # Attention implementation: "dot" (XLA-fused), "flash" (Pallas fused
    # blockwise kernel, ops/flash.py: prefill and training forwards use it —
    # note the backward recomputes attention densely at O(T^2) memory —
    # while single-token decode falls back to dot), "ring" (sequence-parallel
    # ppermute ring over the 'seq' mesh axis; prefill/training only), or
    # "ulysses" (sequence-parallel all-to-all head scatter over 'seq';
    # needs num_heads and num_kv_heads divisible by the seq axis).
    attn_impl: str = "dot"
    # Llama-layout blocks with q/k/v projection biases (Qwen2's one
    # architectural delta from Llama); gpt2/opt layouts always carry theirs.
    qkv_bias: bool = False
    # Gated-MLP activation for the llama family: "silu" (Llama/Qwen2) or
    # "gelu_tanh" (Gemma's GeGLU).  MoE blocks stay silu (Mixtral).
    gate_act: str = "silu"
    # Embedding multiplier applied after lookup (Gemma: sqrt(hidden_size)).
    embed_scale: float = 1.0
    # CONVERTER-ONLY flag: the checkpoint's RMSNorm computes with
    # (1 + weight) (Gemma); convert folds the +1 into the stored scales so
    # the runtime rms_norm stays unchanged.  Random init (ones) is already
    # the folded identity.
    norm_plus_one: bool = False
    # Ragged single-token decode attention (ops/decode_attn.py): row b reads
    # only its cache prefix [0, cache_index[b]] instead of the full width S.
    # Opt-in CONTRACT flag, not just a speed knob: setting it asserts the
    # caller's attn_mask on the per-row-cache_index decode path is exactly
    # that prefix mask (the ContinuousBatcher's is; arbitrary masks are not).
    ragged_decode: bool = False
    # Sliding-window attention (Mistral): query at position p attends keys in
    # (p - window, p].  None = global causal.  Enforced via masks on the dot
    # paths; the flash and ragged-decode kernels carry the window natively
    # (flash skips out-of-window tiles without DMAing them; ragged decode
    # reads only each row's window span).  The paged decode kernel and the
    # seq-parallel impls reject it (full-prefix / global-causal by
    # construction).  The KV cache keeps max_seq_len slots (no rolling
    # buffer yet) — masking is what bounds the attention span, not cache
    # size.
    sliding_window: int | None = None

    def __post_init__(self):
        if self.attn_impl not in _ATTN_IMPLS:
            raise ValueError(
                f"unknown attn_impl {self.attn_impl!r}; choose from {sorted(_ATTN_IMPLS)}"
            )
        if self.gate_act not in ("silu", "gelu_tanh"):
            raise ValueError(
                f"unknown gate_act {self.gate_act!r}; choose silu or gelu_tanh"
            )
        if self.gate_act != "silu" and self.num_experts > 0:
            # moe_swiglu hardcodes silu (Mixtral); accepting another
            # activation here would silently ignore it.
            raise ValueError("MoE blocks support gate_act='silu' only")
        if not 0.0 < self.rotary_pct <= 1.0:
            raise ValueError(
                f"rotary_pct must be in (0, 1], got {self.rotary_pct}"
            )
        if self.rotary_pct < 1.0:
            rot = int(self.head_dim_ * self.rotary_pct)
            if rot < 2 or rot % 2:
                raise ValueError(
                    f"rotary_pct {self.rotary_pct} of head_dim "
                    f"{self.head_dim_} gives {rot} rotary dims; need an "
                    "even count >= 2"
                )
        if self.sliding_window is not None:
            if self.sliding_window < 1:
                raise ValueError(
                    f"sliding_window must be >= 1, got {self.sliding_window}"
                )
            if self.attn_impl in ("ring", "ulysses"):
                # The seq-parallel impls attend the full (causal) global
                # sequence; silently ignoring the window would be wrong
                # numerics for any prompt longer than it.
                raise ValueError(
                    "sliding_window is not supported with ring/ulysses "
                    "sequence parallelism (global causal attention only)"
                )
            # ragged_decode composes: the kernel takes a window bound and
            # reads only [length - window, length) per row — exact for the
            # contract layout (slot == position), which is the same layout
            # the ragged contract already demands.
    # MoE (expert parallelism); num_experts == 0 -> dense MLP.
    num_experts: int = 0
    num_experts_per_token: int = 2
    # Per-expert buffer = capacity_factor * k * tokens / num_experts; tokens
    # routed past a full expert are dropped (standard GShard semantics).
    moe_capacity_factor: float = 1.25
    # Weight of the Switch-style load-balance aux loss added by lm_loss.
    moe_aux_loss_weight: float = 0.02

    @property
    def head_dim_(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads


@dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh.  Axes follow the scaling-book convention:

    - ``data``:  batch sharding (data parallelism)
    - ``pipe``:  pipeline stages (the reference's layer-sharding, done right)
    - ``model``: tensor parallelism (attention heads / MLP hidden)
    - ``seq``:   sequence/context parallelism (ring attention)
    - ``expert``: expert parallelism for MoE layers
    """

    data: int = 1
    pipe: int = 1
    model: int = 1
    seq: int = 1
    expert: int = 1

    @property
    def axis_names(self) -> tuple[str, ...]:
        return ("data", "pipe", "model", "seq", "expert")

    @property
    def shape(self) -> tuple[int, ...]:
        return (self.data, self.pipe, self.model, self.seq, self.expert)

    @property
    def num_devices(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n


@dataclass(frozen=True)
class RuntimeConfig:
    """Serving/runtime knobs (decode loop, KV cache, microbatching)."""

    max_seq_len: int = 1024
    max_decode_steps: int = 64
    microbatches: int = 1  # pipeline microbatches per step
    temperature: float = 0.0  # 0 => greedy
    top_k: int = 0
    top_p: float = 1.0
    kv_cache_dtype: str = "bfloat16"
    # Session KV residency: with kv_host_spill, at most max_resident_sessions
    # session caches stay in HBM; least-recently-used ones spill to host DRAM
    # and are restored (async device_put) on their next turn.
    kv_host_spill: bool = False
    max_resident_sessions: int = 4
    # Weight-only quantized serving: keep an int8/int4 store's decoder-block
    # weights quantized in device memory; the blockwise dequant fuses into
    # each layer's matmuls (halves/quarters weight HBM + read bandwidth).
    serve_quantized: bool = False
    remat: bool = False  # jax.checkpoint on decoder blocks
    seed: int = 0
    profile_dir: str | None = None  # capture jax.profiler traces of generate
    # Persistent XLA compilation cache: a serving process restarted on the
    # same model skips the first-compile wait (~20-40 s on TPU for a 7B
    # decode graph).  Enabled once per process, before the first jit.
    compilation_cache_dir: str | None = None
    # Paged KV cache for continuous batching (runtime/batcher.py): rows
    # allocate pages from a shared pool instead of owning max_seq_len slots;
    # a dry pool back-pressures admission.  None = contiguous per-slot KV.
    paged_pages: int | None = None
    page_size: int = 64
    # Automatic prefix caching over the paged pool (runtime/batcher.py
    # PrefixCache): full prompt pages are content-hashed and shared
    # copy-free across rows (refcounted; LRU eviction under pool
    # pressure), so repeated prompt prefixes — system prompts, few-shot
    # templates, multi-turn history — prefill only their un-cached
    # suffix.  Requires paged_pages; ignored (with a warning) otherwise.
    prefix_cache: bool = False
    # KV memory tiering (runtime/batcher.py, paged mode only):
    # kv_bits=8 stores pool pages as int8 with blockwise absmax scales —
    # roughly half the KV bytes per token, so ~1.9x concurrent rows per
    # pool byte; dequant fuses into the decode-attention read and greedy
    # outputs are parity-bounded (not bit-exact) vs bf16 pages.  16 = the
    # full-width kv_cache_dtype pool.
    kv_bits: int = 16
    # Host-RAM tier behind the paged pool, in pages: preemption SWAPS
    # victim rows out (byte-exact restore instead of prefix recompute;
    # exact-recompute fallback when the budget is dry) and cold
    # prefix-cache pages spill there before LRU eviction (a later hit
    # restores instead of re-prefilling).  0 disables.
    host_pages: int = 0
    # Dispatch-ahead engine loop (runtime/batcher.py): while no scheduling
    # work is pending, decode chunk N+1 dispatches directly from chunk N's
    # device-resident carry and chunk N's host work (token D2H, streaming
    # delivery, digest hashing, metrics) overlaps N+1's device execution.
    # Temp-0 outputs are byte-identical either way; admission/growth/
    # preemption semantics are unchanged (every scheduling decision still
    # runs against synced host mirrors).  Off = the fully-synchronous
    # loop, one host round-trip per chunk.
    overlap: bool = True
    # Scheduling policy (runtime/scheduler.py): "mixed" (default) fuses
    # pending prefill-chunk bites into the decode step as one compiled
    # token-budget program, so resident decode rows never stall for a
    # serialized prefill forward and the dispatch-ahead span keeps
    # running while a long prompt admits; "alternate" keeps the classic
    # serialized prefill_chunk_step rounds.  Temp-0 token streams are
    # byte-identical either way — this is a latency knob, not a
    # semantics knob.
    schedule: str = "mixed"
    # Per-step token budget the mixed policy sizes prefill bites
    # against: each fused step runs one decode leg per active slot plus
    # up to token_budget - n_active prompt tokens of the head pending
    # prefill.  Set, it also auto-chunks any prompt longer than the
    # budget even when prefill_chunk was never configured.  None/0 =
    # prefill_chunk-sized bites (fusion without re-budgeting).
    token_budget: int | None = None
    # Speculative decoding (runtime/speculative.py).  With spec_decode=True
    # on a single-device full-precision engine, generate_text transparently
    # routes greedy requests through the speculative loop (results are
    # bit-identical by construction — the draft only changes speed); the
    # draft is the engine's own blocks weight-only quantized to
    # spec_draft_quantize bits (self-speculation).  temperature > 0 and
    # mesh engines fall back to the plain decode loop.
    spec_decode: bool = False
    spec_k: int = 4
    spec_draft_quantize: int = 4
    # Adaptive spec_k downshift (greedy engines, schedule=mixed): per-row
    # acceptance-rate EMAs feed the scheduler's spec_round_k hook, which
    # clamps each row's COMMITTED tokens per round against the per-step
    # token budget.  The clamp is a ledger bound (a round never commits
    # more than the budget; cancel/deadline checks run at bounded
    # intervals) — the compiled round's device work is CONSTANT by design
    # (full k-draft + (k+1)-token verify, one compile key), so the clamp
    # trades commit granularity, never flops.  Streams stay byte-exact at
    # any clamp (the forced stop emits the target's own token); only
    # arrival granularity changes.
    spec_adaptive_k: bool = True
    # Deterministic fault injection (runtime/faults.py): a comma-separated
    # spec like "batcher.decode:raise@3,proto.send/HEARTBEAT:drop@1+".
    # Engine/batcher hot paths and the cluster protocol framing consult the
    # parsed FaultPlane; the serving supervisor's restart/re-admit path is
    # what this exists to exercise.  None disables.
    faults: str | None = None
    # Default per-request wall-clock deadline (seconds) applied by the
    # serving gateway when a request carries no "timeout_s" field of its
    # own.  An expired request cancels at the next chunk boundary and
    # returns finish_reason "timeout" with the tokens produced so far; one
    # that expires while still QUEUED is shed with 503 + Retry-After.
    # None = no default deadline.
    request_timeout_s: float | None = None
    # Estimated-cost admission gate (runtime/server.py): new requests 429
    # (with Retry-After) once queued + resident token mass exceeds this
    # multiple of the batcher's KV capacity — sustained overload sheds at
    # the front door instead of queueing work doomed to time out.
    # None/0 disables the gate.
    shed_cost_factor: float | None = 2.0
    # Grammar-constrained structured output (runtime/constrain.py): the
    # serving gateway's response_format={"type": "json_schema"|"regex"}
    # fields plus the logit_bias / banned_tokens ride-alongs.  False
    # answers every constrained request 400 (operator kill-switch —
    # automaton compiles are host CPU work an adversarial schema could
    # lean on).
    constrained_decoding: bool = True
    # LRU capacity of the compiled (constraint, tokenizer) -> token-mask
    # automaton cache: each entry holds two [n_states, vocab] tables, so
    # the capacity bounds host RAM spent on remembered schemas.
    constrain_cache_size: int = 64
    # Multi-tenant QoS (runtime/scheduler.py TenantScheduler + the
    # serving gateway's per-tenant quota gate).  tenant_weights turns on
    # weighted-fair admission: "gold:4,free:1"-style shares ("*" sets
    # the default weight unknown/anonymous tenants serve at), billed via
    # per-tenant virtual token counters — a tenant flooding the queue
    # advances its own counter and cannot crowd out a lighter tenant's
    # share.  None/"" = tenant-blind scheduling.
    tenant_weights: str | None = None
    # Per-tenant token-RATE quota at the serving gateway: admitted token
    # mass (prompt + budget) per second, PER UNIT WEIGHT — a tenant over
    # its rate sheds 429 with a per-tenant Retry-After before any
    # admission state exists.  None/0 disables rate quotas.
    tenant_quota_tps: float | None = None
    # Per-tenant RESIDENT-row cap in the batcher: a tenant at the cap
    # defers admission (others admit past it), so one tenant can never
    # hold every batch slot.  None/0 = uncapped.
    tenant_max_rows: int | None = None


@dataclass(frozen=True)
class ClusterConfig:
    """Control-plane knobs.  Replaces the reference's hard-coded ports and
    timers (src/master/node.py:15, src/worker/node.py:35,273)."""

    coordinator_host: str = "0.0.0.0"
    coordinator_port: int = 65432
    heartbeat_interval_s: float = 5.0
    heartbeat_timeout_s: float = 15.0  # deadline eviction (reference never evicts, D10)
    connect_retry_s: float = 5.0
    connect_max_retries: int = 5
    task_timeout_s: float = 60.0
    # Prometheus /metrics + /healthz + /status HTTP port on the coordinator
    # (implementation.md:34-37 parity). None disables; 0 binds ephemeral.
    metrics_port: int | None = None
    # jax.distributed settings for multi-host slices
    distributed_coordinator: str | None = None
    num_processes: int = 1
    process_id: int = 0


@dataclass(frozen=True)
class CheckpointConfig:
    """Shard-store / conversion knobs (successor of shard_info.json,
    src/model/shard_manager.py:63-74)."""

    cache_dir: str = "./models"
    shard_dir: str = "./shards"
    num_shards: int = 2
    quantization: str | None = None  # None | "int8" | "int4"
    quant_block_size: int = 128


@dataclass(frozen=True)
class Config:
    """Root config: everything the framework needs in one place."""

    model: ModelConfig = field(default_factory=ModelConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    model_id: str = "gpt2"


def _dataclass_from_dict(cls: type, data: dict[str, Any]) -> Any:
    """Recursively build a (frozen) dataclass from a plain dict, rejecting
    unknown keys so config typos fail loudly."""
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise ValueError(f"unknown config keys for {cls.__name__}: {sorted(unknown)}")
    kwargs: dict[str, Any] = {}
    for name, value in data.items():
        ftype = fields[name].type
        target = _nested_dataclass(ftype)
        if target is not None and isinstance(value, dict):
            kwargs[name] = _dataclass_from_dict(target, value)
        else:
            kwargs[name] = value
    return cls(**kwargs)


_NESTED = {
    "ModelConfig": ModelConfig,
    "MeshConfig": MeshConfig,
    "RuntimeConfig": RuntimeConfig,
    "ClusterConfig": ClusterConfig,
    "CheckpointConfig": CheckpointConfig,
}


def _nested_dataclass(ftype: Any) -> type | None:
    name = ftype if isinstance(ftype, str) else getattr(ftype, "__name__", "")
    return _NESTED.get(name)


def config_to_dict(cfg: Any) -> dict[str, Any]:
    return dataclasses.asdict(cfg)


def load_config(path: str | None = None, overrides: list[str] | None = None) -> Config:
    """Load a :class:`Config` from a JSON/YAML file plus dotted overrides.

    Overrides look like ``model.num_layers=24`` or ``mesh.pipe=4``; values are
    parsed as JSON when possible, else kept as strings.
    """
    data: dict[str, Any] = {}
    if path is not None:
        with open(path) as f:
            if path.endswith((".yaml", ".yml")):
                if not _HAVE_YAML:  # pragma: no cover
                    raise RuntimeError("yaml not available; use JSON config")
                data = yaml.safe_load(f) or {}
            else:
                data = json.load(f)
    for ov in overrides or []:
        key, _, raw = ov.partition("=")
        if not _:
            raise ValueError(f"override must be key=value, got {ov!r}")
        try:
            value = json.loads(raw)
        except json.JSONDecodeError:
            value = raw
        node = data
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return _dataclass_from_dict(Config, data)


def save_config(cfg: Config, path: str) -> None:
    with open(path, "w") as f:
        if path.endswith((".yaml", ".yml")) and _HAVE_YAML:
            yaml.safe_dump(config_to_dict(cfg), f)
        else:
            json.dump(config_to_dict(cfg), f, indent=2)
