"""Profiling: jax.profiler capture + step timing + HBM occupancy.

The reference has no profiler or timing instrumentation of any kind — only
``print()`` logging (SURVEY §5.1; benchmarking was a plan item,
plan.md:297-300).  Here:

- :func:`trace` captures a TensorBoard/Perfetto trace of everything run
  inside it (XLA ops, host callbacks, transfers) via ``jax.profiler``;
- :func:`annotate` labels host-side regions so they show up on the trace;
- :class:`StepTimer` measures wall-per-step and derived throughput into the
  global METRICS registry (tokens/s, p50/p95 step time — the BASELINE.md
  north-star metrics);
- :func:`record_memory_stats` snapshots per-device HBM occupancy gauges.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator

import jax

from .observability import METRICS, get_logger

log = get_logger("profiling")


@contextlib.contextmanager
def trace(log_dir: str, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a profiler trace into ``log_dir`` (view with TensorBoard's
    profile plugin or Perfetto).  Usage:

        with profiling.trace("/tmp/trace"):
            engine.generate_text([...])
    """
    with jax.profiler.trace(log_dir, create_perfetto_trace=True):
        yield
    log.info("profiler trace written to %s", log_dir)


def annotate(name: str):
    """Label a host-side region on the profiler timeline (and in nested
    StepTimer logs)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Times steps and feeds METRICS.

        timer = StepTimer("train")
        for batch in data:
            with timer.step(tokens=batch.size):
                run_step(batch)

    Records ``<name>.step_seconds`` (histogram -> p50/p95) and a
    ``<name>.tokens_per_second`` gauge over a sliding window.
    """

    def __init__(self, name: str, window: int = 32,
                 clock=time.perf_counter) -> None:
        self.name = name
        self._window = window
        self._samples: list[tuple[float, int]] = []  # (seconds, tokens)
        self.steps = 0
        # Injectable clock: tests drive a fake monotonic counter instead of
        # sleeping wall-clock time to make dt nonzero (graftlint GL501).
        self._clock = clock

    @contextlib.contextmanager
    def step(self, tokens: int = 0) -> Iterator[None]:
        t0 = self._clock()
        with annotate(f"{self.name}.step"):
            yield
        dt = self._clock() - t0
        self.steps += 1
        METRICS.observe(f"{self.name}.step_seconds", dt)
        if tokens:
            self._samples.append((dt, tokens))
            if len(self._samples) > self._window:
                self._samples = self._samples[-self._window :]
            total_t = sum(s for s, _ in self._samples)
            total_tok = sum(n for _, n in self._samples)
            METRICS.set_gauge(
                f"{self.name}.tokens_per_second", total_tok / max(total_t, 1e-9)
            )

    @property
    def tokens_per_second(self) -> float:
        return METRICS.snapshot()["gauges"].get(f"{self.name}.tokens_per_second", 0.0)


def record_memory_stats(prefix: str = "device") -> dict[str, float]:
    """Snapshot per-device memory occupancy into gauges (HBM on TPU).
    Returns {gauge_name: bytes}; devices without stats are skipped."""
    out: dict[str, float] = {}
    for i, dev in enumerate(jax.local_devices()):
        stats = getattr(dev, "memory_stats", lambda: None)()
        if not stats:
            continue
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                name = f"{prefix}{i}.{key}"
                # graftlint: ignore[GL302](gauge names are per-device — "<prefix><i>.bytes_in_use" — an open-ended family no registry entry can enumerate)
                METRICS.set_gauge(name, float(stats[key]))
                out[name] = float(stats[key])
    return out
