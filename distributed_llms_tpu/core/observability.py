"""Structured logging + metrics.

The reference logs with bare ``print()`` throughout (src/master/node.py:36,
197, 206, 215) and its Prometheus/ELK plans (implementation.md:34-41,
:146-157) never landed.  Here: std ``logging`` with an optional JSON
formatter, and an in-process metrics registry (counters, gauges, histogram
summaries) that the coordinator exports over its control-plane endpoint —
tokens/s, p50/p95 hop latency, HBM occupancy, per-stage step time.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": record.created,
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if extra:
            out.update(extra)
        return json.dumps(out)


def get_logger(name: str, json_format: bool = False, level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        if json_format:
            handler.setFormatter(JsonFormatter())
        else:
            handler.setFormatter(
                logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
            )
        logger.addHandler(handler)
        logger.setLevel(level)
        logger.propagate = False
    return logger


@dataclass
class _Histogram:
    values: list[float] = field(default_factory=list)
    max_keep: int = 4096
    # Cumulative across the full lifetime (Prometheus summary semantics);
    # the percentile window above slides, these never reset.
    total_count: int = 0
    total_sum: float = 0.0

    def observe(self, v: float) -> None:
        if len(self.values) >= self.max_keep:
            # Keep a sliding window: drop oldest half.
            self.values = self.values[self.max_keep // 2 :]
        self.values.append(v)
        self.total_count += 1
        self.total_sum += v

    def summary(self) -> dict[str, float]:
        if not self.values:
            return {"count": 0}
        vs = sorted(self.values)
        n = len(vs)

        def pct(p: float) -> float:
            return vs[min(n - 1, int(p * n))]

        return {
            "count": n,
            "mean": sum(vs) / n,
            "p50": pct(0.50),
            "p95": pct(0.95),
            "p99": pct(0.99),
            "min": vs[0],
            "max": vs[-1],
        }


class Metrics:
    """Thread-safe in-process metrics registry."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, _Histogram] = defaultdict(_Histogram)

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def set_gauges(self, values: dict[str, float]) -> None:
        """Set a family of gauges under one lock acquisition — occupancy
        views (e.g. the KV pool's batcher_pool_* snapshot) publish several
        numbers that should land atomically for a scrape."""
        with self._lock:
            self._gauges.update(values)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._hists[name].observe(value)

    def timer(self, name: str) -> "_Timer":
        return _Timer(self, name)

    def get_counter(self, name: str) -> float:
        """Point read of one counter (0.0 when never incremented) — the
        supervisor's restart accounting and tests read through this
        instead of snapshotting the whole registry."""
        with self._lock:
            return self._counters.get(name, 0.0)

    def get_gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary() for k, h in self._hists.items()},
            }

    def prometheus_text(self) -> str:
        """Render the registry in Prometheus exposition format (text/plain
        version 0.0.4).  Histograms export as summaries: quantile series plus
        cumulative _count/_sum.  The reference planned a Prometheus endpoint
        (implementation.md:34-37, :146-157) but never built one."""

        def name_of(raw: str) -> str:
            # Prometheus names: [a-zA-Z_:][a-zA-Z0-9_:]*
            out = "".join(c if c.isalnum() or c == "_" else "_" for c in raw)
            return out if out[:1].isalpha() or out[:1] == "_" else "_" + out

        lines: list[str] = []
        with self._lock:
            for raw, v in sorted(self._counters.items()):
                n = name_of(raw)
                lines.append(f"# TYPE {n} counter")
                lines.append(f"{n} {v}")
            for raw, v in sorted(self._gauges.items()):
                n = name_of(raw)
                lines.append(f"# TYPE {n} gauge")
                lines.append(f"{n} {v}")
            for raw, h in sorted(self._hists.items()):
                n = name_of(raw)
                s = h.summary()
                lines.append(f"# TYPE {n} summary")
                for q in ("p50", "p95", "p99"):
                    if q in s:
                        lines.append(f'{n}{{quantile="0.{q[1:]}"}} {s[q]}')
                lines.append(f"{n}_count {h.total_count}")
                lines.append(f"{n}_sum {h.total_sum}")
        return "\n".join(lines) + "\n"


class _Timer:
    def __init__(self, metrics: Metrics, name: str) -> None:
        self._metrics = metrics
        self._name = name

    def __enter__(self) -> "_Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._metrics.observe(self._name, time.perf_counter() - self._t0)


METRICS = Metrics()
